"""Distributed reference counting: ownership, borrowing, containment.

Role parity with the reference's ReferenceCounter (reference:
src/ray/core_worker/reference_count.h:57) — every object has exactly one
owner (the worker that created it); other holders are borrowers who
report to the owner; submitted-task arguments hold refs while in flight;
values containing ObjectRefs pin the inner objects via containment
edges. When an owned object's counts drain, release callbacks free the
data everywhere (memory store, shm segments, remote replicas).

Keying: the internal table is keyed by the id's raw 28 bytes (C-speed
dict hashing — an ObjectID key would run a Python ``__hash__`` frame on
every probe; the submit hot path does one insert per task and teardown
does one pop per object).  Public methods accept ObjectID or raw bytes;
callbacks always receive a real ObjectID, reconstructed on the (cold)
release/borrow-removed paths.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional, Set

from ray_tpu._private import object_events as oev
from ray_tpu._private.ids import ObjectID, id_key as _key

logger = logging.getLogger(__name__)


def _interesting(r: "Reference") -> bool:
    """Whether a released ref earns an OUT_OF_SCOPE object event. The
    event pipeline covers the objects the store layers fight over —
    plasma residents, borrows, containment edges, location entries.
    Trivial owned in-process values (every task return of a 1M-task
    drain) are deliberately silent here: recording each would only spin
    the drop counter and FIFO-evict the interesting records out of the
    GCS table; the live driver-side ref table still lists them.

    Set-valued fields test ``is not None`` (ever-allocated), not
    truthiness: the release walk empties ``contained_in`` (and borrow
    release empties ``borrowers``) BEFORE the ref is judged — an
    object that ever had those edges is exactly the kind whose release
    must be visible."""
    return bool(r.in_plasma or not r.owned or r.borrowers is not None
                or r.contains is not None or r.contained_in is not None
                or r.locations is not None)


class Reference:
    """Per-object refcount record. The set-valued fields start as ``None``
    and are allocated on first use — one Reference is created per task
    return on the submit hot path, and most objects never have borrowers,
    containment edges, or plasma locations."""

    __slots__ = ("owned", "owner_address", "local_refs", "submitted_refs",
                 "contained_in", "contains", "borrowers", "locations",
                 "in_plasma", "pinned_lineage", "freed", "size",
                 "shard_group")

    def __init__(self):
        self.owned = False
        self.owner_address = ""
        self.local_refs = 0
        self.submitted_refs = 0
        self.contained_in: Optional[Set[bytes]] = None
        self.contains: Optional[Set[bytes]] = None
        self.borrowers: Optional[Set[str]] = None
        # Object data locations (node ids) — owner-resident location index,
        # the analog of OwnershipBasedObjectDirectory.
        self.locations: Optional[Set[bytes]] = None
        self.in_plasma = False
        self.pinned_lineage = False
        self.freed = False
        # Data size in bytes (plasma objects; feeds locality scheduling).
        self.size = 0
        # DistributedArray shard set: a SHARED set of member keys, the
        # same set object on every member ref. The shard set is ONE
        # lineage unit — no member releases until every member is
        # releasable (see _maybe_release).
        self.shard_group: Optional[Set[bytes]] = None

    def is_releasable(self) -> bool:
        return (self.local_refs == 0 and self.submitted_refs == 0
                and not self.borrowers and not self.contained_in)


class ReferenceCounter:
    """Thread-safe; mutations come from both the API threads (ObjectRef
    ctor/dtor) and the IO loop (task completions, borrower RPCs)."""

    def __init__(self, own_address: str = ""):
        self._lock = threading.RLock()
        self._refs: Dict[bytes, Reference] = {}
        self.own_address = own_address
        # Object-lifecycle recorder (object_events.ObjectEventBuffer),
        # installed by the CoreWorker. The reference counter owns the
        # CREATED / BORROWED / CONTAINED / location / OUT_OF_SCOPE
        # transitions, so it stamps them (cold paths only — see
        # _interesting; the lock-free submit fast path never records).
        self.events = None
        # Fired when an owned object becomes releasable: storage layers
        # delete data; lineage unpins.
        self._on_release: List[Callable[[ObjectID, "Reference"], None]] = []
        # Fired to tell a remote owner we dropped a borrowed ref.
        self._on_borrow_removed: List[Callable[[ObjectID, str], None]] = []

    def add_release_callback(self, cb: Callable[[ObjectID, "Reference"], None]):
        """``cb(object_id, released_record)`` — the record is already out
        of the table; its ``owned``/``locations`` drive data deletion."""
        self._on_release.append(cb)

    def add_borrow_removed_callback(self, cb: Callable[[ObjectID, str], None]):
        self._on_borrow_removed.append(cb)

    # -- ownership ----------------------------------------------------------

    def add_owned_object(self, object_id, in_plasma: bool = False,
                         pin_lineage: bool = False) -> None:
        k = _key(object_id)
        with self._lock:
            ref = self._refs.get(k)
            if ref is None:
                ref = self._refs[k] = Reference()
            ref.owned = True
            ref.owner_address = self.own_address
            ref.in_plasma = in_plasma
            ref.pinned_lineage = pin_lineage
        ev = self.events
        if ev is not None and ev.enabled:
            ev.record(k, oev.CREATED, {"owner": self.own_address})

    def add_owned_with_local_ref(self, object_id,
                                 pin_lineage: bool = False) -> None:
        """Fused add_owned_object + add_local_reference, LOCK-FREE on
        the per-task submit path: the id was freshly minted by the
        caller, so no other thread can reach this entry until the
        submission lands on the IO loop — dict get/insert are
        GIL-atomic, and concurrent mutations of OTHER keys don't
        interleave with them (callers construct the ObjectRef with
        skip_adding_local_ref=True)."""
        k = _key(object_id)
        ref = self._refs.get(k)
        if ref is None:
            ref = self._refs[k] = Reference()
        ref.owned = True
        ref.owner_address = self.own_address
        ref.local_refs += 1
        ref.pinned_lineage = pin_lineage

    def has_reference(self, object_id) -> bool:
        """Whether any reference record (local/submitted/borrowed) for
        the object is still live. Single GIL-atomic dict probe — safe
        without the lock from any thread."""
        return _key(object_id) in self._refs

    def add_borrowed_object(self, object_id, owner_address: str) -> bool:
        """Returns True if this is the first borrow (caller should notify
        the owner)."""
        k = _key(object_id)
        with self._lock:
            ref = self._refs.get(k)
            first = ref is None or (not ref.owned and not ref.local_refs
                                    and not ref.submitted_refs)
            if ref is None:
                ref = self._refs[k] = Reference()
            if not ref.owned:
                ref.owner_address = owner_address
        ev = self.events
        if first and ev is not None and ev.enabled:
            # borrower-side adoption (the owner's own BORROWED event —
            # stamped in add_borrower — carries the borrower address)
            ev.record(k, oev.BORROWED, {"owner": owner_address,
                                        "by": self.own_address})
        return first

    def owner_address_of(self, object_id) -> str:
        with self._lock:
            ref = self._refs.get(_key(object_id))
            return ref.owner_address if ref else ""

    def is_owned(self, object_id) -> bool:
        with self._lock:
            ref = self._refs.get(_key(object_id))
            return bool(ref and ref.owned)

    # -- local refs ---------------------------------------------------------

    def add_local_reference(self, object_id) -> None:
        with self._lock:
            ref = self._refs.setdefault(_key(object_id), Reference())
            ref.local_refs += 1

    def remove_local_reference(self, object_id) -> None:
        self._decrement(_key(object_id), "local")

    # -- submitted-task refs ------------------------------------------------

    def update_submitted_task_references(self, arg_ids) -> None:
        with self._lock:
            for oid in arg_ids:
                ref = self._refs.setdefault(_key(oid), Reference())
                ref.submitted_refs += 1

    def update_finished_task_references(self, arg_ids) -> None:
        for oid in arg_ids:
            self._decrement(_key(oid), "submitted")

    # -- containment --------------------------------------------------------

    def add_contained_refs(self, outer, inner) -> None:
        ko = _key(outer)
        with self._lock:
            outer_ref = self._refs.setdefault(ko, Reference())
            if outer_ref.contains is None:
                outer_ref.contains = set()
            for oid in inner:
                ki = _key(oid)
                inner_ref = self._refs.setdefault(ki, Reference())
                if inner_ref.contained_in is None:
                    inner_ref.contained_in = set()
                inner_ref.contained_in.add(ko)
                outer_ref.contains.add(ki)
        ev = self.events
        if ev is not None and ev.enabled:
            # contained-ref adoption: the INNER objects gain a pinning
            # containment edge (one event each, cold path — values
            # carrying ObjectRefs are serialized, never the raw submit)
            outer_hex = ko.hex()
            for oid in inner:
                ev.record(_key(oid), oev.CONTAINED, {"in": outer_hex})

    # -- shard groups (DistributedArray lineage units) -----------------------

    def add_shard_group(self, object_ids) -> None:
        """Bind the shard refs of one DistributedArray into a single
        lineage unit. Every member ref points at the SAME shared set of
        member keys; ``_maybe_release`` refuses to release any member
        while a sibling is still reachable, then releases the whole set
        at once — so a half-dropped array never strands shard segments
        on remote nodes, and the leak detector sees one coherent
        out-of-scope wave instead of a ragged trickle."""
        keys = [_key(oid) for oid in object_ids]
        group = set(keys)
        with self._lock:
            for k in keys:
                ref = self._refs.setdefault(k, Reference())
                ref.shard_group = group

    def _shard_group_releasable(self, group: Set[bytes]) -> bool:
        """All members gone-or-releasable? Caller holds the lock. A key
        missing from the table counts as released (already freed)."""
        for mk in group:
            mref = self._refs.get(mk)
            if mref is None or mref.freed:
                continue
            if not mref.is_releasable():
                return False
        return True

    # -- borrowers (owner side) ---------------------------------------------

    def add_borrower(self, object_id, borrower_address: str) -> None:
        k = _key(object_id)
        recorded = False
        with self._lock:
            ref = self._refs.setdefault(k, Reference())
            if borrower_address != self.own_address:
                if ref.borrowers is None:
                    ref.borrowers = set()
                if borrower_address not in ref.borrowers:
                    ref.borrowers.add(borrower_address)
                    recorded = True
        ev = self.events
        if recorded and ev is not None and ev.enabled:
            ev.record(k, oev.BORROWED, {"borrower": borrower_address})

    def remove_borrower(self, object_id, borrower_address: str) -> None:
        k = _key(object_id)
        removed = False
        with self._lock:
            ref = self._refs.get(k)
            if ref is None:
                return
            if ref.borrowers and borrower_address in ref.borrowers:
                ref.borrowers.discard(borrower_address)
                removed = True
        ev = self.events
        if removed and ev is not None and ev.enabled:
            ev.record(k, oev.BORROW_RELEASED,
                      {"borrower": borrower_address})
        self._maybe_release(k)

    # -- locations (owner-resident object directory) ------------------------

    def add_location(self, object_id, node_id: bytes,
                     size: int = 0) -> None:
        k = _key(object_id)
        with self._lock:
            ref = self._refs.setdefault(k, Reference())
            if ref.locations is None:
                ref.locations = set()
            new = node_id not in ref.locations
            ref.locations.add(node_id)
            ref.in_plasma = True
            if size:
                ref.size = size
        ev = self.events
        if new and ev is not None and ev.enabled:
            ev.record(k, oev.LOCATION_ADDED,
                      {"node": node_id.hex()[:12], "size": size})

    def add_location_if_tracked(self, object_id, node_id: bytes,
                                size: int = 0) -> bool:
        """Like ``add_location`` but refuses to resurrect a released
        ref (a late replica report racing the owner's final release
        must not re-create the entry — the replica would leak)."""
        k = _key(object_id)
        with self._lock:
            ref = self._refs.get(k)
            if ref is None:
                return False
            if ref.locations is None:
                ref.locations = set()
            new = node_id not in ref.locations
            ref.locations.add(node_id)
            ref.in_plasma = True
            if size:
                ref.size = size
        ev = self.events
        if new and ev is not None and ev.enabled:
            ev.record(k, oev.LOCATION_ADDED,
                      {"node": node_id.hex()[:12], "size": size})
        return True

    def remove_location(self, object_id, node_id: bytes) -> None:
        k = _key(object_id)
        dropped = False
        with self._lock:
            ref = self._refs.get(k)
            if ref and ref.locations and node_id in ref.locations:
                ref.locations.discard(node_id)
                dropped = True
        ev = self.events
        if dropped and ev is not None and ev.enabled:
            ev.record(k, oev.LOCATION_DROPPED,
                      {"node": node_id.hex()[:12]})

    def get_locations(self, object_id) -> Set[bytes]:
        with self._lock:
            ref = self._refs.get(_key(object_id))
            return set(ref.locations) if ref and ref.locations else set()

    def location_info(self, object_id):
        """(size_bytes, sorted location node ids) for locality scheduling
        (reference: the owner-fed LocalityData in lease_policy.h)."""
        with self._lock:
            ref = self._refs.get(_key(object_id))
            if ref is None:
                return 0, []
            return ref.size, sorted(ref.locations or ())

    # -- internals ----------------------------------------------------------

    def _decrement(self, k: bytes, kind: str) -> None:
        notify_owner = None
        with self._lock:
            ref = self._refs.get(k)
            if ref is None:
                return
            if kind == "local":
                ref.local_refs = max(0, ref.local_refs - 1)
            else:
                ref.submitted_refs = max(0, ref.submitted_refs - 1)
            if (not ref.owned and ref.local_refs == 0
                    and ref.submitted_refs == 0 and ref.owner_address):
                notify_owner = ref.owner_address
        if notify_owner:
            oid = ObjectID(k)
            for cb in self._on_borrow_removed:
                try:
                    cb(oid, notify_owner)
                except Exception:
                    logger.exception("borrow-removed callback failed")
        self._maybe_release(k)

    def _maybe_release(self, k: bytes) -> None:
        # Release callbacks receive the popped Reference record: the entry
        # leaves the table BEFORE callbacks fire (so late borrower/location
        # reports can't resurrect it), but the callback still needs the
        # ownership bit and the location set to free remote replicas.
        to_release: List[tuple] = []
        with self._lock:
            ref = self._refs.get(k)
            if ref is None or ref.freed or not ref.is_releasable():
                return
            stack: List[tuple] = []

            def expand(ki, r) -> None:
                # Shard-group gate: a releasable member DEFERS until every
                # sibling is releasable; the last drop then releases the
                # whole set in one wave (each member cleared of its group
                # tag so the normal walk below handles it — containment
                # edges included).
                group = r.shard_group
                if group is None:
                    stack.append((ki, r))
                    return
                if not self._shard_group_releasable(group):
                    return
                for mk in group:
                    mref = self._refs.get(mk)
                    if mref is None or mref.freed:
                        continue
                    mref.shard_group = None
                    stack.append((mk, mref))

            expand(k, ref)
            # Transitive containment walk: releasing an outer object drops
            # the containment edges on its inner objects, which may free
            # them — and their own contained objects, to any depth.
            while stack:
                ki, r = stack.pop()
                if r.freed:
                    continue
                r.freed = True
                to_release.append((ki, r))
                for inner in list(r.contains or ()):
                    iref = self._refs.get(inner)
                    if iref is None:
                        continue
                    if iref.contained_in:
                        iref.contained_in.discard(ki)
                    if iref.is_releasable() and not iref.freed:
                        expand(inner, iref)
            for ki, _ in to_release:
                self._refs.pop(ki, None)
        ev = self.events
        for ki, r in to_release:
            if ev is not None and ev.enabled and _interesting(r):
                ev.record(ki, oev.OUT_OF_SCOPE,
                          {"owned": r.owned} if r.owned
                          else {"owned": False,
                                "owner": r.owner_address})
            oid = ObjectID(ki)
            for cb in self._on_release:
                try:
                    cb(oid, r)
                except Exception:
                    logger.exception("release callback failed")

    # -- introspection ------------------------------------------------------

    def num_tracked(self) -> int:
        with self._lock:
            return len(self._refs)

    def debug_summary(self) -> dict:
        # snapshot first: add_owned_with_local_ref inserts WITHOUT the
        # lock (submit hot path), so a live .values() iteration could
        # see a resize; list(dict.values()) is one atomic C call
        refs = list(self._refs.values())
        return {
            "tracked": len(refs),
            "owned": sum(1 for r in refs if r.owned),
            "borrowed": sum(1 for r in refs
                            if not r.owned and r.owner_address),
        }

    def all_refs(self) -> Dict[str, dict]:
        return {
            k.hex(): {
                "owned": r.owned,
                "local_refs": r.local_refs,
                "submitted_refs": r.submitted_refs,
                "borrowers": sorted(r.borrowers or ()),
                "in_plasma": r.in_plasma,
            }
            for k, r in list(self._refs.items())
        }
