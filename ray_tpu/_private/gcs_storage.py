"""GCS metadata persistence: append-only journal + replay.

Role parity: reference GcsTableStorage over a store client
(reference: src/ray/gcs/gcs_server/gcs_table_storage.h; restart reload
via GcsInitData in gcs_server.cc). Redis is deliberately not a
dependency — mutations append msgpack-framed records to one journal
file, and a restarting GCS replays it to rebuild its tables.

Record format: [u32 length][msgpack [op, payload]] per mutation.
"""

from __future__ import annotations

import logging
import os
import struct
from typing import Any, Iterator, Tuple

import msgpack

logger = logging.getLogger(__name__)

_U32 = struct.Struct("<I")


class GcsJournal:
    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "ab")

    def append(self, op: str, payload: Any) -> None:
        body = msgpack.packb([op, payload], use_bin_type=True)
        self._f.write(_U32.pack(len(body)))
        self._f.write(body)
        self._f.flush()

    def size(self) -> int:
        try:
            return self._f.tell()
        except Exception:  # noqa: BLE001
            return 0

    def rewrite(self, records) -> None:
        """Compaction: atomically replace the journal with a snapshot of
        the CURRENT tables (an append-only log otherwise grows without
        bound and replay time with it — the analog of the reference's
        table snapshots in GcsTableStorage)."""
        tmp = self.path + ".compact"
        with open(tmp, "wb") as f:
            for op, payload in records:
                body = msgpack.packb([op, payload], use_bin_type=True)
                f.write(_U32.pack(len(body)))
                f.write(body)
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass  # journal file already closed


def replay(path: str) -> Iterator[Tuple[str, Any]]:
    """Yield (op, payload) records; a torn trailing record (crash mid-
    append) is ignored, everything before it is recovered."""
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        while True:
            hdr = f.read(4)
            if len(hdr) < 4:
                break
            (n,) = _U32.unpack(hdr)
            body = f.read(n)
            if len(body) < n:
                logger.warning("journal %s: torn trailing record dropped",
                               path)
                break
            try:
                op, payload = msgpack.unpackb(body, raw=False)
            except Exception:  # noqa: BLE001 — corrupt tail
                logger.warning("journal %s: corrupt record dropped", path)
                break
            yield op, payload
