"""Shared retry/backoff policy: seeded exponential backoff with jitter.

Before this module each retry loop in the tree rolled its own policy —
a one-shot fixed sleep on pull location refresh, a fixed 0.2 s spin on
raylet->GCS reconnect, 0.05/0.5 s constants in actor re-resolution.
Under real failures those constants are either too eager (thundering
reconnect herds against a restarting GCS) or too slow (a whole extra
round-trip budget burnt sleeping). One policy object replaces them all
(reference: the reference's ExponentialBackoff in
src/ray/common/ray_config_def.h-driven retry helpers).

Jitter is FULL jitter (delay drawn uniformly from [base, target]) from
a ``random.Random`` that tests can SEED to pin the delay sequence
(test_faultpoints pins reproducibility). Production call sites run
unseeded — retry *timing* is not part of the chaos determinism
contract (the chaos scheduler's *event sequence* is what replays
byte-identically from a seed; wall-clock interleaving never was
deterministic).
"""

from __future__ import annotations

import asyncio
import random
import time
from typing import Optional


class Backoff:
    """Exponential-jitter delay sequence, cap + deadline aware.

    Usage::

        bo = Backoff(base_s=0.05, cap_s=2.0, deadline_s=60.0)
        while not bo.expired():
            if try_thing():
                break
            await bo.sleep()      # or time.sleep(bo.next_delay())

    ``deadline_s`` is measured from construction (or the last
    :meth:`reset`); ``sleep``/``next_delay`` never overshoot it — the
    final sleep is clamped so the caller re-checks exactly at the
    deadline instead of up to ``cap_s`` past it.
    """

    def __init__(self, base_s: float, cap_s: float,
                 multiplier: float = 2.0,
                 deadline_s: Optional[float] = None,
                 seed: Optional[int] = None):
        if base_s <= 0:
            raise ValueError("base_s must be > 0")
        if cap_s < base_s:
            raise ValueError("cap_s must be >= base_s")
        self.base_s = base_s
        self.cap_s = cap_s
        self.multiplier = multiplier
        self.deadline_s = deadline_s
        self._rng = random.Random(seed)
        self.attempts = 0
        self._t0 = time.monotonic()

    def reset(self) -> None:
        """Back to the first-attempt delay (a success mid-loop resets
        the policy, so the next failure starts gentle again)."""
        self.attempts = 0
        self._t0 = time.monotonic()

    def expired(self) -> bool:
        return (self.deadline_s is not None and
                time.monotonic() - self._t0 >= self.deadline_s)

    def next_delay(self) -> float:
        """The next delay in seconds (advances the sequence). Full
        jitter: uniform in [base, min(cap, base * mult**attempt)];
        clamped so the caller never sleeps past the deadline."""
        target = min(self.cap_s,
                     self.base_s * (self.multiplier ** self.attempts))
        self.attempts += 1
        delay = self.base_s if target <= self.base_s else \
            self._rng.uniform(self.base_s, target)
        if self.deadline_s is not None:
            remaining = self.deadline_s - (time.monotonic() - self._t0)
            delay = max(0.0, min(delay, remaining))
        return delay

    async def sleep(self) -> None:
        await asyncio.sleep(self.next_delay())

    def sleep_sync(self) -> None:
        time.sleep(self.next_delay())


def from_config(config, deadline_s: Optional[float] = None,
                seed: Optional[int] = None) -> Backoff:
    """The cluster-wide default policy off the config knobs
    (``retry_backoff_base_s`` / ``retry_backoff_cap_s`` /
    ``retry_backoff_multiplier``). Misconfigured knobs are clamped to
    a sane floor rather than raising — a bad retry knob must degrade
    pacing, never break every retry loop in the cluster."""
    base = max(getattr(config, "retry_backoff_base_s", 0.05), 1e-3)
    return Backoff(
        base_s=base,
        cap_s=max(getattr(config, "retry_backoff_cap_s", 2.0), base),
        multiplier=getattr(config, "retry_backoff_multiplier", 2.0),
        deadline_s=deadline_s, seed=seed)
