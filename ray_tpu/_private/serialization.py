"""Value serialization: cloudpickle envelope with out-of-band buffers.

Equivalent role to the reference's msgpack+pickle5 SerializationContext
(reference: python/ray/serialization.py): values are pickled with protocol 5
so large contiguous buffers (numpy / jax host arrays) travel out-of-band and
can be mapped zero-copy out of the shared-memory store on the receive side.
ObjectRefs and actor handles embedded in values are intercepted so the
ownership layer can record borrows.

Single-memcpy put pipeline: serialization is the FIRST pass of a
two-pass writer. ``serialize()`` keeps every out-of-band buffer as a
live ``PickleBuffer`` (no flattening), ``frame_views()`` exposes them
as raw uint8 memoryviews, and ``total_bytes()`` sums their sizes so
``shm_store.write_segment`` can size the target segment exactly and
copy each frame straight into the mapped memory — the payload is
traversed ONCE, by one (GIL-releasing, possibly striped) memcpy per
frame, and no intermediate ``bytes`` is ever materialized.  On the
wire, ``wire_frames()`` (buffer objects handed to the socket as-is)
is ONLY for frames no user code can mutate after the send — error
replies, driver-side task-arg pickles. Inline task returns and owner
GetObject replies deliberately use ``to_wire()``'s flattening-copy
SNAPSHOT instead: their flush is deferred by write coalescing, and
the next actor method (or the putting caller) may mutate the returned
buffers in place — live views would send torn bytes (see the SNAPSHOT
comments at the call sites in task_executor.py / core_worker.py).
The measured gap put-GB/s vs host-memcpy-GB/s is tracked per round by
``bench.py`` (``put_vs_memcpy_ceiling``).
"""

from __future__ import annotations

import io
import pickle
import traceback
from typing import Any, Callable, List, Sequence, Tuple

import cloudpickle

from ray_tpu import exceptions as exc

# Metadata tags. The first frame of a serialized object is the pickle
# payload; metadata describes how to interpret it.
META_PICKLE = b"py"          # cloudpickle protocol-5 payload
META_RAW = b"raw"            # raw bytes payload (zero-copy passthrough)
META_ERROR = b"err"          # pickled exception; get() raises it
META_ACTOR_HANDLE = b"actor"
META_INLINE_REF = b"inref"   # value is an object ref forwarded inline


class SerializedObject:
    """A serialized value: metadata tag + list of byte frames.

    frames[0] is the pickle (or raw) payload; frames[1:] are out-of-band
    pickle-5 buffers. ``contained_refs`` lists ObjectIDs of refs embedded in
    the value (for borrow tracking by the reference counter).
    """

    __slots__ = ("metadata", "frames", "contained_refs")

    def __init__(self, metadata: bytes, frames: Sequence[Any], contained_refs=None):
        self.metadata = metadata
        self.frames = list(frames)
        self.contained_refs = contained_refs or []

    def total_bytes(self) -> int:
        total = 0
        for f in self.frames:
            if isinstance(f, (bytes, bytearray)):
                total += len(f)
            elif isinstance(f, pickle.PickleBuffer):
                total += f.raw().nbytes
            else:
                total += f.nbytes
        return total

    def frame_views(self) -> List[memoryview]:
        """Raw flat uint8 views of every frame — the no-copy second
        input of the two-pass writer. PickleBuffer frames resolve via
        ``.raw()`` (guaranteed 1-D C-contiguous uint8); everything else
        is wrapped/cast without touching the payload."""
        out = []
        for f in self.frames:
            if isinstance(f, pickle.PickleBuffer):
                out.append(f.raw())
            else:
                mv = f if isinstance(f, memoryview) else memoryview(f)
                if mv.format != "B" or mv.ndim != 1:
                    mv = mv.cast("B")
                out.append(mv)
        return out

    def wire_frames(self) -> Tuple[bytes, List[Any]]:
        """(metadata, frames) with frames as buffer objects (bytes or
        live memoryviews) — no flattening copy. ONLY for sends whose
        source buffers cannot mutate before the (deferred, coalesced)
        transport flush: freshly pickled error payloads, sealed shm
        segments. Anything aliasing user-mutable values (inline task
        returns, memory-store replies) must snapshot via ``to_wire()``
        instead — a live view there can send torn bytes."""
        return self.metadata, [
            f if isinstance(f, bytes) else v
            for f, v in zip(self.frames, self.frame_views())]

    def to_wire(self) -> Tuple[bytes, List[bytes]]:
        """Flatten to (metadata, [bytes...]): an owned SNAPSHOT,
        decoupled from the source buffers (which the caller may mutate
        later). Large-frame hot paths use ``wire_frames()`` /
        ``frame_views()`` — this copying form is for frames that
        outlive the call (by-value task args held for retries)."""
        out = []
        for f in self.frames:
            if isinstance(f, pickle.PickleBuffer):
                out.append(f.raw().tobytes())
            elif isinstance(f, memoryview):
                out.append(f.tobytes())
            elif isinstance(f, bytearray):
                out.append(bytes(f))
            else:
                out.append(f)
        return self.metadata, out


class SerializationContext:
    """Per-process serializer. Hooks let the core worker observe refs that
    cross the boundary (ownership / borrowing bookkeeping)."""

    def __init__(self):
        # Called with ObjectRef during pickling -> returns reducible state.
        self._ref_serializer: Callable | None = None
        # Called with the reduced state during unpickling -> ObjectRef.
        self._ref_deserializer: Callable | None = None
        self._actor_serializer: Callable | None = None
        self._actor_deserializer: Callable | None = None
        self._custom_reducers = {}

    def set_object_ref_reducer(self, serializer, deserializer):
        self._ref_serializer = serializer
        self._ref_deserializer = deserializer

    def set_actor_handle_reducer(self, serializer, deserializer):
        self._actor_serializer = serializer
        self._actor_deserializer = deserializer

    def register_custom_serializer(self, cls, reducer):
        """reducer(obj) -> (reconstruct_fn, args)."""
        self._custom_reducers[cls] = reducer

    # -- serialize ----------------------------------------------------------

    def serialize(self, value: Any) -> SerializedObject:
        from ray_tpu._private.object_ref import ObjectRef  # cycle-free at call time
        from ray_tpu.actor import ActorHandle

        if isinstance(value, bytes):
            # Fast path for raw byte payloads.
            return SerializedObject(META_RAW, [value])

        contained: List = []
        buffers: List[pickle.PickleBuffer] = []
        ctx = self

        class _Pickler(cloudpickle.CloudPickler):
            def reducer_override(self, obj):
                if isinstance(obj, ObjectRef):
                    if ctx._ref_serializer is not None:
                        contained.append(obj)
                        state = ctx._ref_serializer(obj)
                        return (_deserialize_ref_placeholder, (state,))
                elif isinstance(obj, ActorHandle):
                    if ctx._actor_serializer is not None:
                        state = ctx._actor_serializer(obj)
                        return (_deserialize_actor_placeholder, (state,))
                elif type(obj) in ctx._custom_reducers:
                    return ctx._custom_reducers[type(obj)](obj)
                # delegate to CloudPickler's reducer_override — it is
                # what pickles local functions/classes by value
                return super().reducer_override(obj)

        f = io.BytesIO()
        p = _Pickler(f, protocol=5, buffer_callback=buffers.append)
        p.dump(value)
        frames: List[Any] = [f.getvalue()]
        frames.extend(buffers)
        meta = META_PICKLE
        if isinstance(value, BaseException):
            meta = META_ERROR
        return SerializedObject(meta, frames,
                                contained_refs=[r.object_id for r in contained])

    def serialize_error(self, error: BaseException) -> SerializedObject:
        try:
            so = self.serialize(error)
        except Exception:
            # Unpicklable exception: degrade to a RayTaskError with the repr.
            so = self.serialize(exc.RayTaskError(
                function_name=getattr(error, "function_name", ""),
                traceback_str=repr(error)))
        so.metadata = META_ERROR
        return so

    # -- deserialize --------------------------------------------------------

    def deserialize(self, metadata: bytes, frames: Sequence[Any]) -> Any:
        if metadata == META_RAW:
            f = frames[0]
            return bytes(f) if not isinstance(f, bytes) else f
        payload, bufs = frames[0], [pickle.PickleBuffer(b) for b in frames[1:]]
        token = _DeserCtx.push(self)
        try:
            value = pickle.loads(payload, buffers=bufs)
        finally:
            _DeserCtx.pop(token)
        if metadata == META_ERROR:
            if isinstance(value, exc.RayTaskError):
                raise value.as_instanceof_cause()
            if isinstance(value, BaseException):
                raise value
            raise exc.RaySystemError(f"malformed error object: {value!r}")
        return value


class _DeserCtx:
    """Thread-local stack of active deserialization contexts so the module-
    level placeholder reconstructors can find the right hooks."""

    import threading
    _local = threading.local()

    @classmethod
    def push(cls, ctx):
        stack = getattr(cls._local, "stack", None)
        if stack is None:
            stack = cls._local.stack = []
        stack.append(ctx)
        return len(stack) - 1

    @classmethod
    def pop(cls, token):
        cls._local.stack.pop()

    @classmethod
    def current(cls) -> SerializationContext:
        stack = getattr(cls._local, "stack", None)
        if not stack:
            raise RuntimeError("no active deserialization context")
        return stack[-1]


def _deserialize_ref_placeholder(state):
    ctx = _DeserCtx.current()
    if ctx._ref_deserializer is None:
        raise RuntimeError("ObjectRef deserializer not registered")
    return ctx._ref_deserializer(state)


def _deserialize_actor_placeholder(state):
    ctx = _DeserCtx.current()
    if ctx._actor_deserializer is None:
        raise RuntimeError("ActorHandle deserializer not registered")
    return ctx._actor_deserializer(state)


def format_task_error(function_name: str, e: BaseException) -> exc.RayTaskError:
    return exc.RayTaskError(
        function_name=function_name,
        traceback_str=traceback.format_exc(),
        cause=e,
    )
