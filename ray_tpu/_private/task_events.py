"""Task-lifecycle event recording: per-task state machines, end to end.

Role parity: the reference's task-event pipeline (reference:
src/ray/core_worker/task_event_buffer.h TaskEventBuffer batching
per-task status changes to the GCS task table, and the state API
rebuilt on top of it — python/ray/util/state). Before this module the
snapshot recorded ONE ``task:execute`` interval per task
(core_worker.add_exec_event), so a task stuck in lease queueing,
arg-pull or spillback was indistinguishable from one that never
existed.

Every task gets a recorded state machine::

    SUBMITTED -> [PENDING_ARGS] -> PENDING_LEASE -> LEASE_GRANTED
              -> DISPATCHED -> RUNNING -> FINISHED | FAILED(reason)

with RETRY / SPILLBACK annotations — and, under streaming leases,
``CREDIT_DISPATCHED`` in place of ``DISPATCHED`` for tasks pushed to a
credit-granted worker: those tasks legitimately have NO
PENDING_LEASE/LEASE_GRANTED hops (the lease round-trip is exactly what
the credit window eliminated), and the distinct state keeps
``grant_wait`` honestly measured — a credit dispatch is never counted
as a zero-wait legacy grant, and a missing lease hop is visibly a
credit hit, not a recording gap. Transitions are stamped AT THE LAYER
THAT OWNS THEM:

* core_worker.py — SUBMITTED, PENDING_ARGS (arg resolution), RETRY,
  DISPATCHED / CREDIT_DISPATCHED (this runtime's direct transport
  pushes task batches from the owner, so dispatch is owner-side),
  owner-observed FAILED (worker death, cancellation, infeasibility).
* raylet.py — PENDING_LEASE (lease request queued), LEASE_GRANTED,
  SPILLBACK, and TRANSFER records for data-plane pulls. Lease requests
  carry the sample task at the head of the owner's queue
  (TaskSpec.lease_summary), so pipelined followers that ride an
  existing lease legitimately skip the lease states.
* task_executor.py — RUNNING, FINISHED, FAILED(exception).

Transitions accumulate in bounded per-process buffers (drop counter
when full — never unbounded memory, never a hot-path RPC) and ship to
the GCS task-event table in batches piggybacked on the existing
reporting cadence: workers/drivers flush with the metrics report loop
(``AddTaskEvents``), raylets piggyback on their heartbeat. The GCS
keeps a capped per-job index with honest eviction counts.

Recording is ON by default at state-transition granularity (the whole
point is that the history exists when the straggler happens); disable
with ``task_events_enabled=False`` / ``RAY_TPU_TASK_EVENTS_ENABLED=0``.
bench.py's ``task_events_overhead`` row tracks the submit-path cost.

All timestamps are ``time.time()`` (wall clock) so owner, raylet,
worker and tracing spans merge onto ONE clock in
``ray_tpu.state.timeline()``.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, List, Optional

# Canonical lifecycle states (reference: rpc::TaskStatus in gcs.proto).
SUBMITTED = "SUBMITTED"
PENDING_ARGS = "PENDING_ARGS"
PENDING_LEASE = "PENDING_LEASE"
LEASE_GRANTED = "LEASE_GRANTED"
SPILLBACK = "SPILLBACK"
DISPATCHED = "DISPATCHED"
# Dispatch against a pre-granted lease credit (streaming leases): the
# task skipped the PENDING_LEASE/LEASE_GRANTED hops by design.
CREDIT_DISPATCHED = "CREDIT_DISPATCHED"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
FAILED = "FAILED"
# Annotation: the owner re-queued the task (worker death, application
# error with retry_exceptions, lineage reconstruction).
RETRY = "RETRY"
# Data-plane pull interval (task_id is empty): merged into the
# timeline so a trace shows submit -> lease wait -> pull -> execute.
TRANSFER = "TRANSFER"

TERMINAL_STATES = (FINISHED, FAILED)


class TaskEventBuffer:
    """Bounded per-process event buffer.

    ``record`` is the hot-path entry: one truthiness check, one length
    check and one GIL-atomic deque append — no lock, no RPC, no
    formatting (wire dicts are built at drain time, off the per-task
    path). When full, new events are DROPPED and counted; memory stays
    flat (bench.py ``task_events_overhead`` pins both properties).

    Thread model: ``record`` may run from any thread (submit threads,
    the exec thread, the IO loop); ``drain_wire`` runs on the flushing
    loop. The buffer is ONE deque for its whole lifetime and the drain
    pops from the head (GIL-atomic popleft) — an append racing the
    drain lands either in this flush or the next one, never nowhere.
    (An earlier swap-the-list design could strand a concurrent append
    on the already-iterated old list: silent, uncounted loss.)
    """

    __slots__ = ("capacity", "enabled", "dropped", "_dropped_flushed",
                 "_buf")

    # Wire-dict key the drained id lands under. The object-plane twin
    # (object_events.ObjectEventBuffer) subclasses with "object_id" —
    # everything else about the buffer contract is shared.
    WIRE_KEY = "task_id"

    def __init__(self, capacity: int = 65536, enabled: bool = True):
        self.capacity = max(1, int(capacity))
        self.enabled = enabled
        # MONOTONIC total of dropped events. drain_wire reports deltas
        # against _dropped_flushed instead of zeroing: a reset would
        # race concurrent record() increments into lost (or re-reported)
        # drop counts — the counter must stay honest exactly when drops
        # are actively happening.
        self.dropped = 0
        self._dropped_flushed = 0
        self._buf: "deque[tuple]" = deque()

    def __len__(self) -> int:
        return len(self._buf)

    def record(self, task_id: bytes, state: str,
               attrs: Any = None, ts: Optional[float] = None) -> None:
        """Append one transition. ``attrs`` is a dict, a bare string
        (shorthand for ``{"name": attrs}`` — saves a dict per submit on
        the hot path), or None."""
        if not self.enabled:
            return
        buf = self._buf
        if len(buf) >= self.capacity:
            self.dropped += 1
            return
        buf.append((task_id, state,
                    time.time() if ts is None else ts, attrs))

    def record_many(self, task_ids, state: str, attrs: Any = None,
                    ts: Optional[float] = None) -> None:
        """Bulk append — one timestamp read, one capacity check and one
        ``list.extend`` for a whole batch (the DISPATCHED stamp of a
        512-deep push batch must not cost 512 record() calls). The
        shared ``attrs`` may alias across events: records are read-only
        once appended."""
        if not self.enabled:
            return
        if ts is None:
            ts = time.time()
        buf = self._buf
        room = self.capacity - len(buf)
        if room <= 0:
            self.dropped += len(task_ids)
            return
        if len(task_ids) > room:
            self.dropped += len(task_ids) - room
            task_ids = task_ids[:room]
        # list comprehension, not a generator: extend() over a genexp
        # measured SLOWER than per-item record() on the target box
        buf.extend([(t, state, ts, attrs) for t in task_ids])

    def drain_wire(self, max_events: int = 0):
        """-> (wire_events, dropped): up to ``max_events`` buffered
        events (0 = everything present at entry) as wire dicts, popped
        off the head of the live deque — a tail beyond the batch stays
        buffered for the next flush (safe: same deque, no swap to race)
        and the capacity check in record() keeps memory bounded
        meanwhile. ``dropped`` is the delta of the monotonic drop total
        since the last drain — never a counter reset, which would
        clobber a concurrent record()'s increment. The per-flush
        payload is bounded by ``capacity`` (events accumulated between
        two flush periods): the default sizes a ~1.5 MB worst case."""
        raw, dropped = self._drain_raw(max_events)
        key = self.WIRE_KEY
        return [{key: t, "state": s, "ts": ts, "attrs": a}
                for t, s, ts, a in raw], dropped

    def _drain_raw(self, max_events: int = 0):
        """-> (raw_records, dropped): the popleft + drop-delta half of
        the drain contract, shared with subclasses whose records are
        already wire-shaped (events.ClusterEventBuffer)."""
        buf = self._buf
        n = len(buf)
        if max_events:
            n = min(n, max_events)
        out = []
        for _ in range(n):
            try:
                out.append(buf.popleft())
            except IndexError:  # raced another drainer; nothing lost
                break
        total = self.dropped
        dropped = total - self._dropped_flushed
        self._dropped_flushed = total
        return out, dropped


def _norm_attrs(attrs: Any) -> Optional[dict]:
    if isinstance(attrs, str):
        return {"name": attrs}
    return attrs


def _hex(b) -> str:
    return b.hex() if isinstance(b, bytes) else (b or "")


class TaskEventTable:
    """GCS-side task table: per-task ordered transition history with a
    capped per-job index (reference: GcsTaskManager's task-event
    storage with per-job limits and honest ``num_profile_events_dropped``
    style counters).

    Eviction is FIFO per job (oldest first-seen task goes first) and
    COUNTED per job — a truncated view is always reported as truncated,
    never passed off as complete. Reporter-side ring-buffer drops
    arrive with each batch and aggregate into ``dropped_events``.
    """

    MAX_TRANSFERS = 10_000

    def __init__(self, max_tasks_per_job: int = 8192):
        self.max_tasks_per_job = max(1, int(max_tasks_per_job))
        # task_id -> record, insertion-ordered (dict semantics).
        self._tasks: Dict[bytes, dict] = {}
        # job_id -> task ids in first-seen order (the eviction queue).
        self._per_job: Dict[bytes, List[bytes]] = {}
        self.evicted_tasks: Dict[bytes, int] = {}
        self.dropped_events = 0
        self.transfers: List[dict] = []
        self.transfers_dropped = 0

    def num_tasks(self) -> int:
        return len(self._tasks)

    def ingest(self, events, dropped: int = 0, job_id: bytes = b"") -> None:
        """Fold one reporter batch in. ``job_id`` is the batch-level
        job of the reporting owner (raylet batches pass b"": their
        events attach to records the owner's SUBMITTED creates, or to
        a job-less record that upgrades when the owner's batch lands)."""
        self.dropped_events += int(dropped or 0)
        for e in events:
            state = e.get("state")
            attrs = _norm_attrs(e.get("attrs"))
            if state == TRANSFER:
                if len(self.transfers) >= self.MAX_TRANSFERS:
                    self.transfers_dropped += 1
                else:
                    rec = {"ts": e.get("ts", 0.0)}
                    rec.update(attrs or {})
                    self.transfers.append(rec)
                continue
            tid = e.get("task_id") or b""
            if not tid:
                continue
            rec = self._tasks.get(tid)
            if rec is None:
                rec = {"task_id": tid, "job_id": job_id,
                       "name": (attrs or {}).get("name", ""),
                       "attempt": 0, "events": []}
                self._tasks[tid] = rec
                self._index(tid, job_id)
            else:
                if attrs and attrs.get("name") and not rec["name"]:
                    rec["name"] = attrs["name"]
                if job_id and not rec["job_id"]:
                    # raylet events arrived first: adopt the owner's job
                    order = self._per_job.get(b"")
                    if order is not None and tid in order:
                        order.remove(tid)
                    rec["job_id"] = job_id
                    self._index(tid, job_id)
            rec["events"].append((state, e.get("ts", 0.0), attrs))
            if state == RETRY:
                rec["attempt"] += 1

    def _index(self, tid: bytes, job_id: bytes) -> None:
        order = self._per_job.setdefault(job_id, [])
        order.append(tid)
        while len(order) > self.max_tasks_per_job:
            old = order.pop(0)
            if self._tasks.pop(old, None) is not None:
                self.evicted_tasks[job_id] = \
                    self.evicted_tasks.get(job_id, 0) + 1

    def list(self, state: Optional[str] = None, name: Optional[str] = None,
             node: Optional[str] = None, job_id: Optional[str] = None,
             limit: int = 1000) -> List[dict]:
        """Public-form records (hex ids, ts-sorted events with
        durations), newest-submitted last, filtered then tail-limited.
        Filters run on the RAW records and only the post-limit tail is
        converted — the public conversion (per-task event sort + dict
        build) must not scan the whole table on every dashboard poll.
        ``limit`` <= 0 returns nothing (a negative limit must not alias
        to 'the entire table'); 0 < limit bounds the tail."""
        try:
            limit = int(limit if limit is not None else 0)
        except (TypeError, ValueError):
            limit = 0
        if limit <= 0:
            return []
        matched = []
        for rec in self._tasks.values():
            if name and name not in rec["name"]:
                continue
            if job_id and _hex(rec["job_id"]) != job_id:
                continue
            if state and _current_state(rec["events"]) != state:
                continue
            if node and not any(
                    isinstance(e[2], dict) and
                    str(e[2].get("node", "")).startswith(node)
                    for e in rec["events"]):
                continue
            matched.append(rec)
        return [task_record_to_public(r) for r in matched[-limit:]]

    def summary(self) -> dict:
        """Aggregate view for ``summary_tasks()`` / the dashboard."""
        by_state: Dict[str, int] = {}
        by_name: Dict[str, Dict[str, int]] = {}
        for rec in self._tasks.values():
            st = _current_state(rec["events"])
            by_state[st] = by_state.get(st, 0) + 1
            per = by_name.setdefault(rec["name"] or "?", {})
            per[st] = per.get(st, 0) + 1
        return {
            "num_tasks": len(self._tasks),
            "by_state": by_state,
            "by_name": by_name,
            "evicted_tasks": {_hex(k): v
                              for k, v in self.evicted_tasks.items()},
            "dropped_events": self.dropped_events,
            "num_transfers": len(self.transfers),
            "transfers_dropped": self.transfers_dropped,
        }


def _current_state(events) -> str:
    """State of the latest-by-timestamp transition. A terminal state
    wins ties (the worker's FINISHED and the owner's bookkeeping can
    share a wall-clock microsecond)."""
    if not events:
        return ""
    best = max(events, key=lambda e: (e[1], e[0] in TERMINAL_STATES))
    return best[0]


def task_record_to_public(rec: dict) -> dict:
    """GCS-internal record -> API/JSON form: hex ids, events sorted by
    timestamp, and per-hop durations (``dur`` = gap to the next
    transition; None on the last one)."""
    events = sorted(rec["events"], key=lambda e: e[1])
    out_events = []
    for i, (state, ts, attrs) in enumerate(events):
        dur = events[i + 1][1] - ts if i + 1 < len(events) else None
        out_events.append({"state": state, "ts": ts, "dur": dur,
                           "attrs": attrs})
    return {
        "task_id": _hex(rec["task_id"]),
        "job_id": _hex(rec["job_id"]),
        "name": rec["name"],
        "state": _current_state(events),
        "attempt": rec["attempt"],
        "events": out_events,
    }
