"""Raylet: per-node control — worker pool, leases, scheduling, object plane.

Role parity: reference raylet (src/ray/raylet/node_manager.h NodeManager,
worker_pool.h WorkerPool, scheduling/cluster_task_manager.h) plus the
node-local shared-memory store it hosts (the plasma thread in the reference,
src/ray/object_manager/object_manager.cc ObjectStoreRunner) and the
node-to-node object transfer path (src/ray/object_manager/object_manager.h
Push/Pull).

Protocol surface (all framed-msgpack RPC, see rpc.py):
  workers   : RegisterWorker, ActorExited, SealObject, GetObjectInfo,
              EnsureObjectLocal, PinObject, FreeObject
  clients   : RequestWorkerLease, ReturnWorker (lease pipelining is
              client-side, reference: direct_task_transport.h);
              streaming leases: ReportLeaseDemand (owner -> raylet
              push, backlog refresh), GrantLeaseCredits (raylet ->
              owner push, pre-granted worker slots + window target),
              RevokeLeaseCredits (raylet -> owner call, reclaim of
              unused credits)
  GCS       : ScheduleActorCreation, KillActorWorker, PreparePGBundle,
              CommitPGBundle, ReturnPGBundle, DrainSelf
  raylets   : FetchObjectMeta (pull probe) + FetchObjectChunk (legacy
              chunk serve); bulk chunk bytes ride the striped raw-socket
              data plane (data_channel.py), never this control stream
  ops       : GetNodeStats, GetLogs, DumpWorkerStacks, SetResource

The reference's per-node dashboard/runtime-env AGENT process
(dashboard/agent.py + raylet/agent_manager.h:43) is folded INTO this
raylet by design: runtime envs (working_dir packages, pip installs)
materialize lazily in workers keyed by env hash, and the agent's
stats/log/stack serving is the ops RPC surface above — one less
process per node, same capabilities.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu._private import backoff as backoff_mod
from ray_tpu._private import faultpoints
from ray_tpu._private import protocol
from ray_tpu._private import rpc
from ray_tpu._private import runtime_env as runtime_env_mod
from ray_tpu._private.config import RayTpuConfig
from ray_tpu._private.ids import NodeID, ObjectID, WorkerID
from ray_tpu._private.scheduler import (
    GRANT, INFEASIBLE, SPILL, WAIT, NodeView, PendingRequest, make_backend,
)
from ray_tpu._private.object_events import (
    LEAK_CLEARED, LEAK_RECLAIMED, LEAKED, PULLED, ObjectEventBuffer,
)
from ray_tpu._private.shm_store import (
    ShmStoreServer, map_cache_stats as _map_cache_stats,
)
from ray_tpu._private.task_events import (
    LEASE_GRANTED, PENDING_LEASE, SPILLBACK, TRANSFER, TaskEventBuffer,
)

logger = logging.getLogger(__name__)

# Prometheus counters for the SPMD layer (distributed_array.py verbs
# executed by this raylet). Lazily registered like data_channel's
# _plane_metrics: the counters exist only in processes that actually
# run gathers/gang leases, and ride the existing metric reporters —
# no new transport.
_spmd_prom = None


def _spmd_metrics() -> dict:
    global _spmd_prom
    if _spmd_prom is None:
        from ray_tpu._private import metrics as m
        _spmd_prom = {
            "reshard_bytes": m.Counter(
                "ray_tpu_reshard_bytes_total",
                "DistributedArray bytes moved by GatherShards "
                "(reshard/all-gather/all-reduce destinations)"),
            "gang_leases": m.Counter(
                "ray_tpu_gang_leases_total",
                "SPMD gang leases granted (one per all-or-nothing "
                "N-worker booking round)"),
            "collective_bytes": m.Counter(
                "ray_tpu_collective_bytes_total",
                "DistributedArray collective wire bytes this node "
                "pulled, by algorithm (ring reduce-scatter/all-gather "
                "steps vs the fold GatherShards path)"),
        }
    return _spmd_prom


def _read_file_chunk(path: str, pos: int, limit: int = 256 * 1024) -> bytes:
    """Bounded read at an offset — executor-thread helper so the log
    monitor never does file I/O on the event loop."""
    with open(path, "rb") as f:
        f.seek(pos)
        return f.read(limit)


def _read_file_tail(path: str, limit: int) -> bytes:
    """Last ``limit`` bytes of a file (executor-thread helper)."""
    with open(path, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        f.seek(max(0, size - limit))
        return f.read()


WORKER_IDLE = "idle"
WORKER_LEASED = "leased"
WORKER_ACTOR = "actor"
WORKER_STARTING = "starting"
WORKER_DEAD = "dead"


class WorkerHandle:
    def __init__(self, worker_id: bytes, pid: int, proc: Optional[subprocess.Popen]):
        self.worker_id = worker_id
        self.pid = pid
        self.proc = proc
        # "zygote" | "popen" | "" (externally started / not yet known)
        self.spawned_via = ""
        self.address = ""
        self.conn: Optional[rpc.Connection] = None
        self.state = WORKER_STARTING
        self.lease_id: Optional[int] = None
        self.actor_id: bytes = b""
        self.job_id: bytes = b""
        self.started_at = time.time()
        # Memory-watchdog victim ordering (memory_monitor.py): when the
        # current lease was granted, and whether its sample task is
        # retriable — the watchdog kills the NEWEST retriable leased
        # worker first and never touches non-retriable work.
        self.leased_at = 0.0
        self.lease_retriable = False
        # set once the watchdog dispatched this worker to the async
        # owner-acked kill path (prevents double-selection)
        self.oom_kill_pending = False
        # Runtime env this worker last activated: leases prefer a match
        # (reference: worker_pool.h:135 runtime_env_hash PopWorker key).
        self.env_hash: str = ""


class LeaseEntry:
    def __init__(self, lease_id: int, worker: WorkerHandle,
                 resources: Dict[str, float], client: rpc.Connection):
        self.lease_id = lease_id
        self.worker = worker
        self.resources = resources
        self.client = client


class CreditWindow:
    """Per-(owner connection, scheduling class) streaming-lease state:
    how many pre-granted worker slots this owner may hold, sized from
    its reported backlog and the real scheduler view, renewed on the
    heartbeat cadence and revocable at any time (memory pressure zeroes
    the window; stale demand shrinks it). A credit is an ordinary
    LeaseEntry — owner-liveness reclaim, ReturnWorker, and the memory
    watchdog's victim ordering all see it exactly like a legacy lease."""

    __slots__ = ("conn", "sched_class", "resources", "env_hash",
                 "retriable", "demand", "demand_ts", "lease_ids",
                 "target", "last_revoke_ts", "revoking")

    def __init__(self, conn: rpc.Connection, sched_class: int,
                 resources: Dict[str, float], env_hash: str,
                 retriable: bool):
        self.conn = conn
        self.sched_class = sched_class
        self.resources = resources
        self.env_hash = env_hash
        # Victim eligibility for the memory watchdog (sample-task
        # approximation, same as the legacy lease summary's flag).
        self.retriable = retriable
        self.demand = 0          # last reported backlog (tasks)
        self.demand_ts = 0.0     # when that report landed (monotonic)
        self.lease_ids: Set[int] = set()  # outstanding credits
        self.target = -1         # last window target pushed to the owner
        self.last_revoke_ts = 0.0
        self.revoking = False


class Raylet:
    def __init__(self, config: RayTpuConfig, num_cpus: float,
                 custom_resources: Optional[Dict[str, float]] = None,
                 session_dir: str = "/tmp/ray_tpu", node_name: str = ""):
        self.config = config
        self.node_id = NodeID.from_random()
        self.node_name = node_name or f"node-{self.node_id.hex()[:8]}"
        self.session_dir = session_dir
        self.resources_total: Dict[str, float] = {"CPU": float(num_cpus)}
        if custom_resources:
            self.resources_total.update(custom_resources)
        self.resources_available = dict(self.resources_total)

        self.store = ShmStoreServer(
            capacity_bytes=config.object_store_memory,
            spill_dir=os.path.join(session_dir, "spill", self.node_id.hex()[:8]),
            spilling_enabled=config.object_spilling_enabled,
            external_storage_url=config.spill_external_storage_url)

        # Structured event log (reference: util/event.h RAY_EVENT).
        # Emissions ALSO land in the bounded cluster-event buffer and
        # ride the heartbeat into the GCS ClusterEventTable — the
        # queryable event plane (events.py); the file tier alone is
        # gated by event_log_enabled.
        from ray_tpu._private.events import ClusterEventBuffer, EventEmitter
        self.cluster_events = ClusterEventBuffer(
            getattr(config, "cluster_event_buffer_size", 4096))
        self.events = EventEmitter(
            "raylet", os.path.join(session_dir, "logs")
            if config.event_log_enabled else None,
            buffer=self.cluster_events)
        # Control-plane flight recorder (rpc.py): per-method telemetry
        # + loop-lag probe config for this process.
        rpc.telemetry.configure(config)

        self.workers: Dict[bytes, WorkerHandle] = {}
        self.leases: Dict[int, LeaseEntry] = {}
        self._lease_counter = itertools.count(1)
        self._req_counter = itertools.count(1)
        self.max_workers = int(config.max_workers_per_node or max(1, int(num_cpus)))
        self._num_starting = 0
        # Zygote worker factory (zygote.py): one pre-imported template
        # process this raylet fork()s workers from. Launched at node
        # boot when workers are prestarted, else on first demand; once
        # it fails, every later spawn stays on the cold-Popen path.
        self._zygote: Optional[Any] = None
        self._zygote_failed = False
        # Live async reapers for SIGKILLed/“disconnected” worker procs —
        # kept so stop() can await the stragglers instead of leaking
        # zombies past the raylet's lifetime.
        self._reap_tasks: Set[asyncio.Task] = set()

        # Pending lease requests in arrival order: req_id -> (PendingRequest,
        # reply future). The scheduler seam consumes this queue each tick.
        self._pending: Dict[int, Tuple[PendingRequest, asyncio.Future]] = {}
        self.backend = make_backend(config.scheduler_backend)

        # Cluster view for spillback (fed by GCS NODE pubsub + polling).
        self.remote_nodes: Dict[bytes, dict] = {}

        # Placement group reservations: (pg_id, bundle_idx) -> resources.
        self._pg_prepared: Dict[Tuple[bytes, int], Dict[str, float]] = {}
        self._pg_committed: Set[Tuple[bytes, int]] = set()
        # Per-bundle remaining capacity for leases inside a PG.
        self._pg_available: Dict[Tuple[bytes, int], Dict[str, float]] = {}

        self.gcs_conn: Optional[rpc.Connection] = None
        # wire version agreed with the GCS at registration (RegisterNode
        # handshake); MIN until the first register completes
        self.negotiated_protocol_version = protocol.MIN_PROTOCOL_VERSION
        self._server = rpc.RpcServer(self._handlers(), name="raylet")
        self.address = ""
        self._peer_raylets: Dict[str, rpc.Connection] = {}
        self._owner_conns: Dict[str, rpc.Connection] = {}
        self._hb_task: Optional[asyncio.Task] = None
        self._tick_scheduled = False
        self._closing = False
        # Pull state (reference: PullManager): dedupe + admission control.
        self._active_pulls: Dict[ObjectID, asyncio.Task] = {}
        self._pull_inflight_bytes = 0
        # Admission waiters park on this Condition and are notified on
        # every pull completion (no sleep-polling on the loop).
        self._pull_cond = asyncio.Condition()
        # Striped data plane (data_channel.py): bulk chunk bytes ride
        # dedicated raw sockets, never the RPC control stream.
        self.data_server: Optional[Any] = None
        self.data_address = ""
        self._data_channels: Dict[str, Any] = {}
        # Pull-side node directory for peers that registered BEFORE this
        # raylet subscribed to NODE (the pubsub view misses them): filled
        # on demand from the GCS, used ONLY by the pull path — the
        # scheduler's cluster view stays the pubsub one.
        self._node_directory: Dict[bytes, dict] = {}
        self._node_dir_refresh: Optional[asyncio.Task] = None
        # Serve-side attachment cache: chunked pulls hit the same segment
        # many times; re-mmap'ing per chunk would sit on the transfer hot
        # path (reference: ObjectBufferPool holds chunk buffers open).
        self._serve_attachments: Dict[str, Any] = {}
        self.num_leases_granted = 0
        self.num_spillbacks = 0
        # Streaming-lease credit windows: (id(owner conn), scheduling
        # class) -> CreditWindow. Issuance rides demand registration
        # (RequestWorkerLease backlog / ReportLeaseDemand pushes) plus
        # the heartbeat cadence; every credit is accounted as a real
        # LeaseEntry against resources_available — never a side ledger.
        self._credit_windows: Dict[Tuple[int, int], CreditWindow] = {}
        self._credit_topup_scheduled = False
        self.num_credit_grants = 0
        self.num_credit_revoked = 0
        # SPMD gang leases (distributed_array.py): gang_id -> record
        # {epoch, members, broken, dead_members, created, owner_drop}
        # on the HOME raylet (the one the owner asked), plus the member
        # bookings THIS node holds for gangs homed on a peer raylet
        # (gang_id -> {epoch, lease_ids}). Epoch-fenced like actor
        # incarnations: any gang frame carrying an older epoch is
        # rejected, and a re-formation at a higher epoch releases the
        # previous incarnation's members before booking.
        self.gangs: Dict[bytes, dict] = {}
        self._gang_members: Dict[bytes, dict] = {}
        self.num_gang_leases = 0
        self.num_gang_rejects = 0
        # Ring-collective members this raylet hosts: member_id (28-byte
        # driver-minted id, one per collective x rank — several ranks
        # of ONE collective may live here in single-node runs) ->
        # accumulator record {segment mapping, layout, reduce spec,
        # per-step wire/fold counters}. Members are created by RingInit,
        # stepped by RingStep, and freed by RingFinish/RingAbort (or
        # the TTL sweep when a driver died between rounds).
        self._ring_members: Dict[bytes, dict] = {}
        self.num_ring_collectives = 0
        # Schedule latency (request arrival -> decision dispatched), a
        # bounded reservoir for percentile reporting (reference: the
        # north-star p50/p99 schedule-latency metric, BASELINE.json).
        from collections import deque as _deque
        self._sched_latencies: Any = _deque(maxlen=65536)
        # split reservoirs: arrival->first-decision / first-decision->grant
        self._decision_latencies: Any = _deque(maxlen=65536)
        self._grant_waits: Any = _deque(maxlen=65536)
        # (queue_len, wall_s) per scheduler tick — the pure decision
        # cost of the kernel, free of queueing effects.
        self._tick_durations: Any = _deque(maxlen=65536)
        # Task-lifecycle recorder (task_events.py): lease-queue / grant
        # / spillback transitions for the sample task each lease request
        # carries, plus TRANSFER records for data-plane pulls. Flushed
        # piggybacked on the heartbeat — never its own RPC.
        self.task_events = TaskEventBuffer(
            config.task_events_buffer_size,
            enabled=config.task_events_enabled)
        self._nid12 = self.node_id.hex()[:12]
        # Object-lifecycle recorder (object_events.py): the shm store
        # stamps seal/pin/expose/evict/spill/free + segment events into
        # this buffer; the raylet adds PULLED and the leak-detector
        # verdicts. Flushed piggybacked on the heartbeat (object_events
        # header keys) — never its own RPC.
        self.object_events = ObjectEventBuffer(
            config.object_events_buffer_size,
            enabled=config.object_events_enabled)
        self.store.events = self.object_events
        self.store.node_tag = self._nid12
        # Leak detector (object_events.py): owner address per stored
        # object (fed by SealObject's owner_address and the pull path),
        # consecutive dead-verdict counts, the currently-leaked set and
        # the reclaim counter. The sweep rides the heartbeat loop.
        self._object_owners: Dict[bytes, str] = {}
        self._leak_suspects: Dict[bytes, int] = {}
        self._leaked: Set[bytes] = set()
        self.leak_reclaims = 0
        self.leak_sweeps = 0
        self._last_leak_sweep = 0.0
        self._leak_sweep_task: Optional[asyncio.Task] = None
        # per-pull throughput reservoir (GB/s), reported by GetNodeStats
        self._pull_rates: Any = _deque(maxlen=4096)
        # bounded history of finished/aborted ring-collective members,
        # surfaced by GetNodeStats' collectives block (telemetry for
        # the bench's bandwidth assertion: wire bytes per rank)
        self._recent_collectives: Any = _deque(maxlen=64)
        # Host-stats collection handles, cached once: importing psutil
        # and constructing a fresh Process() every heartbeat wasted
        # ~100us/beat, and cpu_percent(interval=None) on a fresh
        # object has no "last call" to diff against (first sample is
        # meaningless 0.0) — the cached handle makes the since-last-
        # call sample real from the second beat on.
        try:
            import psutil as _psutil
            self._psutil = _psutil
            self._psutil_proc = _psutil.Process()
            _psutil.cpu_percent(interval=None)  # prime the diff sample
        except Exception:  # noqa: BLE001 — host stats are best-effort decoration
            self._psutil = None
            self._psutil_proc = None
        # Node memory watchdog (memory_monitor.py): polled from the
        # heartbeat loop; turns memory pressure into ordered relief ->
        # retriable OOM kill -> lease backpressure instead of letting
        # the kernel OOM killer shoot a random process.
        from ray_tpu._private.memory_monitor import MemoryMonitor
        self.memory_monitor = MemoryMonitor(
            config, self.store, self._nid12,
            workers=lambda: self.workers.values(),
            kill_worker=self._oom_kill_worker)

    def _handlers(self):
        return {
            "RegisterWorker": self.handle_register_worker,
            "RequestWorkerLease": self.handle_request_worker_lease,
            "ReportLeaseDemand": self.handle_report_lease_demand,
            "ReturnWorker": self.handle_return_worker,
            "RequestGangLease": self.handle_request_gang_lease,
            "BookGangMembers": self.handle_book_gang_members,
            "ReleaseGangMembers": self.handle_release_gang_members,
            "ReleaseGangLease": self.handle_release_gang_lease,
            "GatherShards": self.handle_gather_shards,
            "RingInit": self.handle_ring_init,
            "RingStep": self.handle_ring_step,
            "RingFinish": self.handle_ring_finish,
            "RingAbort": self.handle_ring_abort,
            "ScheduleActorCreation": self.handle_schedule_actor_creation,
            "KillActorWorker": self.handle_kill_actor_worker,
            "ActorExited": self.handle_actor_exited,
            "SealObject": self.handle_seal_object,
            "AllocSegment": self.handle_alloc_segment,
            "AbortSegment": self.handle_abort_segment,
            "GetObjectInfo": self.handle_get_object_info,
            "EnsureObjectLocal": self.handle_ensure_object_local,
            "FetchObjectMeta": self.handle_fetch_object_meta,
            "FetchObjectChunk": self.handle_fetch_object_chunk,
            "PinObject": self.handle_pin_object,
            "FreeObject": self.handle_free_object,
            "PreparePGBundle": self.handle_prepare_pg_bundle,
            "CommitPGBundle": self.handle_commit_pg_bundle,
            "ReturnPGBundle": self.handle_return_pg_bundle,
            "GetNodeStats": self.handle_get_node_stats,
            "SetResource": self.handle_set_resource,
            "DumpWorkerStacks": self.handle_dump_worker_stacks,
            "GetLogs": self.handle_get_logs,
            "Published": self.handle_published,
        }

    # ------------------------------------------------------------- lifecycle

    async def start(self, gcs_address: str, listen_address: str = "") -> str:
        # Warm the native copy tier off-loop: copy_into on the chunked
        # pull path uses only the already-loaded module (it never
        # builds), so the one compile a cold cache costs happens here,
        # in an executor, before the raylet serves anything.
        from ray_tpu._private import native
        await asyncio.get_running_loop().run_in_executor(
            None, native.load_fastpath)
        sock_dir = os.path.join(self.session_dir, "sockets")
        os.makedirs(sock_dir, exist_ok=True)
        if not listen_address:
            listen_address = f"unix://{sock_dir}/raylet-{self.node_id.hex()[:12]}"
        self.address = await self._server.listen(listen_address)
        if self.config.data_plane_stripes > 0:
            # Bulk-transfer listener next to the RPC server (reference:
            # the object manager's own server, separate from the node
            # manager's — src/ray/object_manager/object_manager.h).
            from ray_tpu._private.data_channel import DataPlaneServer
            host = "127.0.0.1"
            if self.address.startswith("tcp://"):
                host = self.address[len("tcp://"):].rpartition(":")[0] \
                    or host
            self.data_server = DataPlaneServer(self.store, host=host)
            self.data_address = await self.data_server.start()
        self.gcs_address = gcs_address
        # Full handler map on the GCS connection too: the GCS issues
        # requests (actor scheduling, PG 2PC, kills) back over this pipe.
        self.gcs_conn = await rpc.connect(
            gcs_address, handlers=self._handlers(), peer_name="gcs")
        await self._register_with_gcs()
        self._hb_task = asyncio.get_running_loop().create_task(self._heartbeat_loop())
        self._log_monitor_task = asyncio.get_running_loop().create_task(
            self._log_monitor_loop())
        n_prestart = self.config.num_prestart_workers
        if n_prestart < 0:  # auto: one warm worker per CPU slot
            n_prestart = min(int(self.resources_total.get("CPU", 0)),
                             self.max_workers)
        for _ in range(n_prestart):
            self._start_worker_process()
        logger.info("raylet %s listening at %s (%s)",
                    self.node_id.hex()[:8], self.address, self.resources_total)
        self.events.emit("INFO", "RAYLET_STARTED",
                         f"raylet listening at {self.address}",
                         node=self.node_id.hex()[:12],
                         resources=self.resources_total)
        return self.address

    async def stop(self):
        self._closing = True
        if self._hb_task:
            self._hb_task.cancel()
        if getattr(self, "_log_monitor_task", None):
            self._log_monitor_task.cancel()
        if self._leak_sweep_task is not None and \
                not self._leak_sweep_task.done():
            self._leak_sweep_task.cancel()
        self.events.close()
        procs = []
        for w in list(self.workers.values()):
            self._kill_worker(w)
            if w.proc is not None:
                procs.append(w.proc)
        await self._reap_procs(procs)
        for t in list(self._reap_tasks):
            t.cancel()
        if self._zygote is not None:
            await self._zygote.close()
            self._zygote = None
        await self._server.close()
        if self.gcs_conn and not self.gcs_conn.closed:
            # Graceful departure: tell the GCS we're draining so a planned
            # shutdown isn't reported as a node failure (reference:
            # NodeManager drain / UnregisterNode path).
            try:
                await self.gcs_conn.call(
                    "DrainNode", {"node_id": self.node_id.binary()},
                    timeout=2)
            except (ConnectionError, asyncio.TimeoutError):
                pass
            await self.gcs_conn.close()
        for ch in list(self._data_channels.values()):
            await ch.close()
        self._data_channels.clear()
        # in-flight ring collectives die with the node: release their
        # leased accumulator segments (the driver's step RPC fails and
        # it aborts the surviving members on the other nodes)
        for mid, mrec in list(self._ring_members.items()):
            self._ring_members.pop(mid, None)
            self._discard_ring_member(mid, mrec, reason="raylet stopped")
        if self.data_server is not None:
            await self.data_server.close()
        for att in self._serve_attachments.values():
            try:
                att.close()
            except BufferError:
                pass
        self._serve_attachments.clear()
        self.store.shutdown()

    async def _log_monitor_loop(self):
        """Tail this node's worker log files and publish new lines to
        the GCS LOGS channel; drivers with log_to_driver print them
        (reference: python/ray/_private/log_monitor.py tailing into
        Redis pubsub, worker.py print_logs)."""
        log_dir = os.path.join(self.session_dir, "logs")
        offsets: Dict[str, int] = {}
        while not self._closing:
            await asyncio.sleep(0.25)
            try:
                names = [n for n in os.listdir(log_dir)
                         if n.startswith("worker-") and n.endswith(".log")]
            except FileNotFoundError:
                continue
            pid_by_wid_hex = {w.worker_id.hex(): w.pid
                              for w in self.workers.values()}
            for name in names:
                path = os.path.join(log_dir, name)
                pos = offsets.get(name, 0)
                try:
                    # Off-loop read: log files live on local disk, and a
                    # cold-cache 256 KiB read can stall the loop for ms.
                    chunk = await asyncio.get_running_loop() \
                        .run_in_executor(None, _read_file_chunk, path, pos)
                except OSError:
                    continue
                if not chunk:
                    continue
                # only publish complete lines; keep the tail buffered —
                # and only advance the offset over lines actually
                # published (a chatty worker's extra lines are picked up
                # by the next poll, never dropped). Split on raw \n so
                # the byte offset always matches the line count.
                cut = chunk.rfind(b"\n")
                if cut < 0:
                    continue
                raw = chunk[:cut].split(b"\n")
                if len(raw) > 1000:
                    raw = raw[:1000]
                    cut = sum(len(r) for r in raw) + len(raw) - 1
                lines = [r.decode("utf-8", "replace") for r in raw]
                offsets[name] = pos + cut + 1
                wid_hex = name[len("worker-"):-len(".log")]
                pid = next((p for w, p in pid_by_wid_hex.items()
                            if w.startswith(wid_hex)), 0)
                try:
                    await self.gcs_conn.call("Publish", {
                        "channel": "LOGS",
                        "msg": {"node": self.node_id.hex()[:12],
                                "ip": self.node_name or "local",
                                "pid": pid, "lines": lines},
                    })
                except ConnectionError:
                    pass  # heartbeat loop owns reconnects

    def _heartbeat_stats(self) -> dict:
        """Flat per-node stats piggybacked on heartbeats → GCS metrics
        endpoint + dashboard API (reference: raylet resource/stats
        reports feeding the metrics agent, metric_defs.h gauges; host
        stats parity: reporter_agent.py:126 psutil collection)."""
        s = self.store.stats()
        out = {
            "num_workers": self._alive_worker_count(),
            "num_pending_leases": len(self._pending),
            "num_leases_granted": self.num_leases_granted,
            "num_credit_grants": self.num_credit_grants,
            "num_credit_revoked": self.num_credit_revoked,
            "num_credit_windows": len(self._credit_windows),
            "num_spillbacks": self.num_spillbacks,
            "store_used_bytes": s["used_bytes"],
            "store_num_objects": s["num_objects"],
            "store_num_spills": s["num_spills"],
            "store_num_evictions": s["num_evictions"],
            # Object-plane rollups (ISSUE 13 satellite): the store /
            # recycle-pool / map-cache / data-plane truth GetNodeStats
            # always had, now on every beat so summary_nodes() and the
            # dashboard show it without a per-node RPC.
            "store_capacity_bytes": s["capacity_bytes"],
            "store_num_pinned": s["num_pinned"],
            "store_num_spilled": s["num_spilled"],
            "store_recycle_bytes": s["recycle_pool_bytes"],
            "store_recycle_segments": s["recycle_pool_segments"],
            "store_lent_segments": s["recycle_lent_segments"],
            "store_lent_bytes": s["recycle_lent_bytes"],
            "data_plane_inflight_bytes": self._pull_inflight_bytes,
            "objects_leaked": len(self._leaked),
            "leak_reclaims": self.leak_reclaims,
        }
        mc = _map_cache_stats()
        out["map_cache_entries"] = mc["entries"]
        out["map_cache_bytes"] = mc["bytes"]
        out["map_cache_hits"] = mc["hits"]
        out["map_cache_misses"] = mc["misses"]
        mon = self.memory_monitor
        if mon is not None:
            # watchdog state rides every beat (flat, same style as the
            # spill/eviction counters): per-worker RSS sum, pressure
            # flag, cumulative kill/backpressure counts — all honest
            # (monotonic counters, last-poll gauges).
            out["workers_rss_bytes"] = sum(
                mon.workers_rss.values())
            out["memory_pressure"] = mon.pressure
            out["memory_usage_fraction"] = round(mon.usage_fraction, 4)
            out["memory_monitor_kills"] = mon.kills
            out["lease_backpressure_rejects"] = mon.backpressure_rejects
        if self._psutil is not None:
            try:
                # cached module + Process handle (set at __init__):
                # interval=None is a non-blocking since-last-call
                # sample, real because init primed the first call
                out["host_cpu_percent"] = \
                    self._psutil.cpu_percent(interval=None)
                vm = self._psutil.virtual_memory()
                out["host_mem_used_bytes"] = float(vm.used)
                out["host_mem_total_bytes"] = float(vm.total)
                du = self._psutil.disk_usage(self.session_dir or "/")
                out["host_disk_used_bytes"] = float(du.used)
                out["host_disk_total_bytes"] = float(du.total)
                out["raylet_rss_bytes"] = float(
                    self._psutil_proc.memory_info().rss)
            # raylint: disable=exception-hygiene — host stats are best-effort decoration
            except Exception:
                pass
        # NOTE: scheduler latency percentiles are deliberately NOT
        # computed here — sorting a 64k reservoir 4x/s on the event
        # loop would stall heartbeats under load; GetNodeStats computes
        # them on demand. Per-handler RPC latency (C4 instrumented-asio
        # parity) IS carried: the snapshot is a dozen small dict
        # entries, and the loop-lag flat keys below feed the per-node
        # Prometheus gauges (the RPC reservoirs ship separately in the
        # throttled rpc_telemetry beat key).
        from ray_tpu._private.rpc import handler_stats, telemetry
        out["rpc_handlers"] = handler_stats.snapshot()
        # this raylet loop's OWN probe (named: an in-process head's
        # driver loop stalls must never read as this node's lag)
        lp = telemetry.loop_probe("raylet").snapshot()
        lag = lp.get("lag") or {}
        out["loop_lag_p50_ms"] = lag.get("p50_ms", 0.0)
        out["loop_lag_p99_ms"] = lag.get("p99_ms", 0.0)
        out["loop_lag_max_ms"] = lp.get("lag_max_ms", 0.0)
        out["loop_slow_callbacks"] = lp.get("slow_callbacks", 0)
        out["loop_ticks"] = lp.get("ticks", 0)
        return out

    async def _heartbeat_loop(self):
        from ray_tpu._private import metrics as metrics_mod

        period = self.config.raylet_heartbeat_period_ms / 1000.0
        while not self._closing:
            try:
                # Memory watchdog rides the heartbeat cadence (interval
                # gate inside poll) — BEFORE the heartbeat-drop fault
                # seam: a partitioned node must still protect itself
                # from the kernel OOM killer. Shielded: a watchdog
                # error (an armed hook that raises, an exotic procfs)
                # must degrade to a missed poll, never take down the
                # heartbeat loop — that would convert memory pressure
                # into the node death the watchdog exists to prevent.
                # Loop-lag probe rides this existing cadence (the
                # instrumented_io_context tick): one call_soon, no new
                # thread/timer.
                rpc.telemetry.loop_probe("raylet").tick()
                try:
                    was_pressure = self.memory_monitor.pressure
                    self.memory_monitor.poll()
                    if was_pressure != self.memory_monitor.pressure:
                        # pressure transitions are cluster events (the
                        # per-reject counter rides the stats; emitting
                        # per reject would storm the bounded buffer)
                        if self.memory_monitor.pressure:
                            self.events.emit(
                                "WARNING", "MEMORY_PRESSURE",
                                f"memory pressure engaged at "
                                f"{self.memory_monitor.usage_fraction:.2f}"
                                f" usage; lease backpressure active",
                                node=self._nid12,
                                usage_fraction=round(
                                    self.memory_monitor.usage_fraction,
                                    4))
                        else:
                            self.events.emit(
                                "INFO", "MEMORY_PRESSURE_CLEARED",
                                "memory pressure cleared",
                                node=self._nid12,
                                backpressure_rejects=self.memory_monitor
                                .backpressure_rejects)
                    if was_pressure and not self.memory_monitor.pressure:
                        # pressure cleared: re-evaluate whatever the
                        # backpressure window parked (PG leases stay
                        # pending through it — nothing else ticks them)
                        self._schedule_tick()
                except Exception:  # noqa: BLE001 — missed poll < dead node
                    logger.exception("memory watchdog poll failed")
                # Streaming-lease window maintenance rides the same
                # beat, right after the watchdog poll: a pressure
                # crossing zeroes/revokes credit windows IMMEDIATELY —
                # before any lease backpressure decision — and stale
                # windows shrink here. Shielded like the watchdog: a
                # credit bug must cost a missed beat, not the node.
                try:
                    self._credit_beat()
                except Exception:  # noqa: BLE001 — missed beat < dead node
                    logger.exception("lease-credit beat failed")
                # Object-plane leak sweep rides the same beat (interval
                # gate inside) but runs as a BACKGROUND task: probing a
                # SIGKILLed owner costs a full refused-dial timeout,
                # and blocking the beat that long would make the GCS
                # declare this healthy node dead — the exact confusion
                # the detector exists to remove. Shielded like the
                # watchdog: a sweep bug costs a sweep, never the node.
                try:
                    self._maybe_start_leak_sweep()
                except Exception:  # noqa: BLE001 — missed sweep < dead node
                    logger.exception("object leak sweep failed to start")
                if faultpoints.armed:
                    # heartbeat-partition fault: ``drop`` suppresses the
                    # beat (fired BEFORE the event drain, so no task
                    # events are lost to a skipped beat); enough
                    # consecutive drops make the GCS declare this node
                    # dead — the re-registration path below must then
                    # resurrect it once beats resume.
                    act = await faultpoints.async_fire(
                        "raylet.heartbeat", node=self._nid12)
                    if act == "drop":
                        await asyncio.sleep(period)
                        continue
                beat = protocol.HeartbeatRequest(
                    node_id=self.node_id.binary(),
                    resources_available=self.resources_available,
                    stats=self._heartbeat_stats())
                # Task-lifecycle events piggyback on the heartbeat
                # (never their own RPC); a beat lost to a restarting
                # GCS is bounded event loss, by design.
                events, dropped = self.task_events.drain_wire()
                if events or dropped:
                    beat.task_events = events
                    beat.task_events_dropped = dropped
                # Object-lifecycle events ride the same beat into the
                # GCS object table (bounded loss on a dropped beat, by
                # design — same contract as task events).
                oevents, odropped = self.object_events.drain_wire()
                if oevents or odropped:
                    beat.object_events = oevents
                    beat.object_events_dropped = odropped
                # Cluster events (events.py plane) ride the beat too:
                # node-local emissions (worker death, OOM kills, leak
                # reclaims, zygote fallbacks...) reach the GCS table
                # without their own RPC.
                cevents, cdropped = self.cluster_events.drain()
                if cevents or cdropped:
                    beat.cluster_events = cevents
                    beat.cluster_events_dropped = cdropped
                if not metrics_mod.core_reporter():
                    # standalone raylet process (worker node / headless
                    # head): no CoreWorker ships this process's metric
                    # registry, so the heartbeat carries it — with the
                    # per-method RPC latency histograms merged in
                    snap = metrics_mod.global_registry().snapshot()
                    if rpc.telemetry.enabled:
                        snap.update(rpc.telemetry.prom_snapshot())
                    if snap:
                        beat.metrics = snap
                    # full flight-recorder snapshot + drained slow
                    # calls (an in-process head's CoreWorker ships the
                    # shared process snapshot via ReportRpcTelemetry
                    # instead — one reporter per process, never two)
                    if rpc.telemetry.enabled:
                        slow, sdropped = \
                            rpc.telemetry.drain_slow_calls()
                        beat.rpc_telemetry = {
                            "snapshot": rpc.telemetry.wire(
                                probe="raylet"),
                            "slow_calls": slow,
                            "slow_calls_dropped": sdropped}
                reply, _ = await self.gcs_conn.call(
                    "Heartbeat", beat.to_header())
                if not protocol.HeartbeatReply.from_header(reply).ok:
                    # A restarted GCS does not know this node: re-register
                    # over the live connection (reference: raylets
                    # re-register after GCS failover).
                    await self._register_with_gcs()
            except ConnectionError:
                logger.warning("GCS connection lost; raylet reconnecting")
                if not await self._reconnect_gcs():
                    logger.error("GCS unreachable for %.0fs; heartbeat "
                                 "loop exiting",
                                 self.config.gcs_reconnect_timeout_s)
                    return
            await asyncio.sleep(period)

    async def _register_with_gcs(self):
        reply, _ = await self.gcs_conn.call(
            "RegisterNode",
            protocol.RegisterNodeRequest(
                node_id=self.node_id.binary(),
                address=self.address,
                # peers learn the bulk-transfer endpoint through the
                # NODE channel; "" = data plane disabled (pulls from
                # this node use the control-plane chunk path)
                data_address=self.data_address,
                resources=self.resources_total,
                node_name=self.node_name,
                protocol_version=protocol.PROTOCOL_VERSION).to_header())
        # Version handshake: a pre-versioning GCS's reply decodes as
        # version 1 via the stub's compat defaults; everything this
        # node sends afterwards must fit the NEGOTIATED version.
        rep = protocol.RegisterNodeReply.from_header(reply)
        self.negotiated_protocol_version = \
            protocol.negotiate(rep.negotiated_protocol_version)
        self.gcs_conn.peer_protocol_version = \
            protocol.negotiate(rep.protocol_version)
        await self.gcs_conn.call("Subscribe", {"channel": "NODE"})

    async def _reconnect_gcs(self) -> bool:
        """Dial the (restarting) GCS until it answers, then re-register
        (reference: gcs_server_address_updater + raylet re-registration
        on GCS failover). Redials back off exponentially with jitter
        (backoff.py) instead of the old fixed 0.2 s spin — a cluster of
        raylets must not stampede a GCS mid-journal-replay in
        lockstep."""
        bo = backoff_mod.from_config(
            self.config, deadline_s=self.config.gcs_reconnect_timeout_s)
        while not self._closing and not bo.expired():
            try:
                conn = await rpc.connect(
                    self.gcs_address, handlers=self._handlers(),
                    peer_name="gcs", timeout=5.0)
                self.gcs_conn = conn
                await self._register_with_gcs()
                logger.info("raylet %s re-registered with restarted GCS",
                            self.node_id.hex()[:8])
                return True
            except ConnectionError:
                await bo.sleep()
        return False

    async def handle_published(self, conn, header, bufs):
        msg = header["msg"]
        if header["channel"] == "NODE":
            nid = msg["node_id"]
            if nid == self.node_id.binary():
                return {}
            if msg["event"] == "alive":
                self.remote_nodes[nid] = {
                    "address": msg["address"],
                    "data_address": msg.get("data_address", ""),
                    "resources_total": msg["resources"],
                    "resources_available": dict(msg["resources"]),
                }
                # a joining node may carry capacity a WAITING
                # (infeasible-so-far) request needs: spill it there now
                self._schedule_tick()
            elif msg["event"] == "dead":
                pub_info = self.remote_nodes.pop(nid, None)
                dir_info = self._node_directory.pop(nid, None)
                info = pub_info or dir_info
                if info:
                    # a restarted peer binds a fresh data port: the old
                    # address key would never be looked up again, so
                    # the stale client's stripe sockets must go now
                    ch = self._data_channels.pop(
                        info.get("data_address", ""), None)
                    if ch is not None:
                        await ch.close()
        return {}

    # ----------------------------------------------------------- worker pool

    def _start_worker_process(self, force: bool = False) -> None:
        # The pool cap tracks CPU slots for task workers. Actor leases
        # pass force=True: their admission is governed by the resource
        # accounting (a zero-cpu actor must not starve on the process
        # cap — reference: dedicated workers per actor, worker_pool.cc).
        if not force and (self._num_starting + self._alive_worker_count()
                          >= self.max_workers):
            return
        self._num_starting += 1
        log_dir = os.path.join(self.session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        worker_id = WorkerID.from_random()
        log_path = os.path.join(
            log_dir, f"worker-{worker_id.hex()[:12]}.log")
        handle = WorkerHandle(worker_id.binary(), 0, None)
        self.workers[worker_id.binary()] = handle
        if self._zygote_eligible():
            # Fast path: fork the pre-imported template (zygote.py) —
            # spawn-to-registered is milliseconds instead of a full
            # interpreter boot. The pid lands asynchronously; the
            # handle is already registered so the pool accounting and
            # RegisterWorker see one consistent STARTING worker.
            try:
                self._ensure_zygote()
            except (OSError, subprocess.SubprocessError) as e:
                # launch itself failed (fork pressure, bad log dir):
                # same contract as a death mid-session — cold Popen for
                # this spawn and all later ones
                self._zygote_failed = True
                self._zygote = None
                logger.warning("zygote launch failed (%r); cold-Popen "
                               "fallback engaged", e)
                self.events.emit(
                    "WARNING", "ZYGOTE_FALLBACK",
                    f"zygote launch failed ({e!r}); cold-Popen "
                    f"fallback engaged for the session",
                    node=self._nid12)
                self._popen_worker(handle, worker_id.hex(), log_path)
                return
            rpc.spawn_logged(
                self._spawn_via_zygote(handle, worker_id.hex(), log_path),
                "raylet-spawn-via-zygote")
        else:
            self._popen_worker(handle, worker_id.hex(), log_path)

    def _worker_env_overrides(
            self, worker_id_hex: str) -> Dict[str, Optional[str]]:
        """Per-spawn environment deltas (None = unset), shared by both
        spawn paths: applied onto this process's env for a cold Popen
        and onto the template's env by a zygote-forked child."""
        ov: Dict[str, Optional[str]] = {
            "RAY_TPU_WORKER_ID": worker_id_hex}
        # Workers default to CPU jax (RAY_TPU_WORKER_JAX_PLATFORMS="",
        # i.e. empty, keeps the inherited platform for TPU workers).
        # This must OVERRIDE any inherited JAX_PLATFORMS — and when the
        # worker is CPU-only, also drop the device-plugin trigger env
        # so a wedged TPU transport can never hang worker startup
        # (observed: device-backend bring-up blocking indefinitely,
        # which turns into actor-resolve timeouts).
        worker_platforms = os.environ.get(
            "RAY_TPU_WORKER_JAX_PLATFORMS", "cpu")
        if worker_platforms:
            ov["JAX_PLATFORMS"] = worker_platforms
            if "tpu" not in worker_platforms and \
                    "axon" not in worker_platforms:
                ov["PALLAS_AXON_POOL_IPS"] = None
        # Fault arming is per-SPAWN state: forward the env var's value
        # as of RIGHT NOW, so a schedule armed after node boot reaches
        # zygote-forked children too (the template's baked-in env may
        # predate the arming) and a disarmed var is unset, not stale.
        ov[faultpoints.ENV_VAR] = os.environ.get(faultpoints.ENV_VAR)
        return ov

    def _popen_worker(self, handle: WorkerHandle, worker_id_hex: str,
                      log_path: str) -> None:
        """Cold spawn: fresh interpreter via Popen (the pre-zygote path,
        kept as the universal fallback)."""
        env = dict(os.environ)
        for k, v in self._worker_env_overrides(worker_id_hex).items():
            if v is None:
                env.pop(k, None)
            else:
                env[k] = v
        out = open(log_path, "ab")
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu._private.worker_main",
                 "--raylet-address", self.address,
                 "--gcs-address", self.gcs_address,
                 "--node-id", self.node_id.hex(),
                 "--worker-id", worker_id_hex,
                 "--session-dir", self.session_dir],
                stdout=out, stderr=subprocess.STDOUT, env=env,
                start_new_session=True)
        finally:
            # Popen dup'd the fd into the child: the parent's copy used
            # to leak one fd per spawn for the raylet's lifetime
            # (pinned by the chaos fd bracket in run_task_schedule).
            out.close()
        handle.pid = proc.pid
        handle.proc = proc
        handle.spawned_via = "popen"

    # ------------------------------------------------------ zygote factory

    def _zygote_eligible(self) -> bool:
        """Whether spawns may ride the fork-fast path right now. Cold
        Popen covers everything else: knob off, template already
        failed, non-Linux, or accelerator-platform workers (an
        initialized accelerator client must never be forked; empty
        RAY_TPU_WORKER_JAX_PLATFORMS means the worker inherits the
        raylet's platform, so it must be assumed TPU)."""
        if not self.config.worker_zygote_enabled or self._zygote_failed:
            return False
        if not sys.platform.startswith("linux"):
            return False
        platforms = os.environ.get("RAY_TPU_WORKER_JAX_PLATFORMS", "cpu")
        if not platforms or "tpu" in platforms or "axon" in platforms:
            return False
        return True

    def _ensure_zygote(self) -> None:
        """Launch the template once. With prestarted workers (the
        default) this happens during ``start()``'s prestart loop, i.e.
        at node boot; the launch itself is just fork+exec — the
        template pays its import bill concurrently while early spawn
        requests queue in the socketpair buffer."""
        if self._zygote is not None:
            return
        from ray_tpu._private.zygote import ZygoteClient
        env = dict(os.environ)
        # The template imports the worker graph under the WORKER
        # platform env (cpu-only per _zygote_eligible), so nothing
        # accelerator-shaped can initialize pre-fork.
        for k, v in self._worker_env_overrides("").items():
            if k == "RAY_TPU_WORKER_ID":
                continue
            if v is None:
                env.pop(k, None)
            else:
                env[k] = v
        self._zygote = ZygoteClient.launch(
            session_dir=self.session_dir, env=env,
            preload=self.config.zygote_preload_modules,
            tag=self.node_id.hex()[:12])
        logger.info("zygote template launched (pid %s)",
                    self._zygote.proc.pid)

    async def _spawn_via_zygote(self, handle: WorkerHandle,
                                worker_id_hex: str, log_path: str) -> None:
        from ray_tpu._private.zygote import ZygoteError, ZygoteProc
        zygote = self._zygote
        if zygote is None:
            # a concurrent spawn failed and tore the factory down
            # between this task's creation and execution
            if not (self._closing or handle.state == WORKER_DEAD):
                self._popen_worker(handle, worker_id_hex, log_path)
            return
        try:
            pid = await asyncio.wait_for(
                zygote.spawn(
                    worker_id=worker_id_hex, log_path=log_path,
                    env_overrides=self._worker_env_overrides(worker_id_hex),
                    argv={"raylet_address": self.address,
                          "gcs_address": self.gcs_address,
                          "node_id": self.node_id.hex(),
                          "worker_id": worker_id_hex,
                          "session_dir": self.session_dir}),
                # strictly tighter than worker_register_timeout_s: the
                # actor-creation path waits that long for a registered
                # worker, so a wedged-but-alive template must fail over
                # to cold Popen with enough budget left for the Popen
                # worker to boot and register inside the same deadline
                timeout=max(2.0, self.config.worker_register_timeout_s / 3))
        except (ZygoteError, asyncio.TimeoutError, OSError) as e:
            # Zygote dead or wedged: engage the cold-Popen fallback for
            # this spawn and every later one (no template respawn —
            # deterministic behavior for the rest of the session).
            self._zygote_failed = True
            self._zygote = None
            logger.warning("zygote spawn failed (%r); cold-Popen "
                           "fallback engaged", e)
            self.events.emit(
                "WARNING", "ZYGOTE_FALLBACK",
                f"zygote spawn failed ({e!r}); cold-Popen fallback "
                f"engaged for the session", node=self._nid12)
            if zygote is not None:
                await zygote.close()
            if self._closing or handle.state == WORKER_DEAD or \
                    self.workers.get(handle.worker_id) is not handle:
                return
            self._popen_worker(handle, worker_id_hex, log_path)
            return
        handle.pid = pid
        handle.proc = ZygoteProc(pid)
        handle.spawned_via = "zygote"
        if handle.state == WORKER_DEAD:
            # torn down before the template reported the pid: the kill
            # that already ran had nothing to signal — finish it now
            handle.proc.kill()
            self._reap_proc_async(handle.proc)

    def _alive_worker_count(self) -> int:
        """Workers counted against the task-worker pool cap. Actor workers
        are excluded: an actor owns a dedicated process for its lifetime
        (reference: worker_pool.h dedicated workers), so a node with
        num_cpus task slots can still serve tasks while actors live.
        STARTING workers are excluded too — ``_num_starting`` already
        accounts for them, and double-counting halves the pool (every
        cap check is ``_num_starting + _alive_worker_count()``)."""
        return sum(1 for w in self.workers.values()
                   if w.state not in (WORKER_DEAD, WORKER_ACTOR,
                                      WORKER_STARTING))

    async def handle_register_worker(self, conn, header, bufs):
        wid = header["worker_id"]
        handle = self.workers.get(wid)
        if handle is None:
            # Externally started worker (tests / manual): adopt it.
            handle = WorkerHandle(wid, header.get("pid", 0), None)
            self.workers[wid] = handle
        else:
            self._num_starting = max(0, self._num_starting - 1)
            if not handle.pid:
                # zygote spawn whose pid report is still in flight on
                # the socketpair — the worker itself knows its pid
                handle.pid = header.get("pid", 0)
        handle.address = header["address"]
        handle.conn = conn
        handle.state = WORKER_IDLE
        conn.tags["worker_id"] = wid
        conn.on_disconnect.append(lambda c: self._on_worker_disconnect(wid))
        self._schedule_tick()
        # a fresh idle worker may fill a credit-window deficit
        self._schedule_credit_topup()
        return {"ok": True, "node_id": self.node_id.binary(),
                "config": self.config.to_json()}

    def _on_worker_disconnect(self, worker_id: bytes):
        handle = self.workers.get(worker_id)
        if handle is None or handle.state == WORKER_DEAD:
            return
        prev_state = handle.state
        handle.state = WORKER_DEAD
        self.events.emit(
            "WARNING", "WORKER_DIED",
            f"worker {worker_id.hex()[:12]} disconnected",
            pid=handle.pid, prev_state=prev_state,
            node=self.node_id.hex()[:12])
        logger.warning("worker %s (%s) disconnected", worker_id.hex()[:8], prev_state)
        if handle.lease_id is not None and handle.lease_id in self.leases:
            self._release_lease(handle.lease_id)
        if prev_state == WORKER_ACTOR:
            # Return the actor's resources (they're not lease-tracked).
            self._give_back(getattr(handle, "actor_resources", {}),
                            getattr(handle, "actor_pg_key", None))
            handle.actor_resources = {}
        # A worker that exited on its own (or was killed by something
        # else) still needs its status collected — _kill_worker never
        # ran for it.
        self._reap_proc_async(handle.proc)
        if prev_state == WORKER_ACTOR and handle.actor_id and not self._closing:
            async def _report():
                try:
                    await self.gcs_conn.call("ReportActorDeath", {
                        "actor_id": handle.actor_id,
                        "reason": "worker process died",
                        "cause": {"kind": "WORKER_DIED",
                                  "node_id": self.node_id.hex(),
                                  "worker_id": worker_id.hex()},
                        "expected": False})
                except ConnectionError:
                    pass
            rpc.spawn_logged(_report(), "raylet-report-worker-death")
        self.workers.pop(worker_id, None)
        self._schedule_tick()

    def _pop_idle_worker(self, env_hash: str = "") -> Optional[WorkerHandle]:
        fallback = None
        for w in self.workers.values():
            if w.state == WORKER_IDLE and w.conn is not None and not w.conn.closed:
                if w.env_hash == env_hash:
                    return w  # warm for this runtime env
                if fallback is None:
                    fallback = w
        return fallback

    def _kill_worker(self, handle: WorkerHandle):
        handle.state = WORKER_DEAD
        if handle.proc is not None:
            try:
                os.killpg(os.getpgid(handle.proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError, OSError):
                try:
                    handle.proc.kill()
                except OSError:
                    pass  # process already gone
            self._reap_proc_async(handle.proc)

    def _reap_proc_async(self, proc) -> None:
        """Collect a dead worker process's exit status: SIGKILLed and
        crashed workers were never wait()ed, so their zombies
        accumulated for the raylet's lifetime (pinned by the chaos
        worker_kill no-zombie invariant). ``Popen.poll()`` reaps
        raylet-parented children; a ``ZygoteProc``'s zombie belongs to
        — and is reaped by — the zygote template."""
        if proc is None or proc.poll() is not None:
            return
        if self._closing:
            return  # stop()'s _reap_procs sweep collects everything

        async def _reap(bound_s: float = 10.0):
            loop = asyncio.get_running_loop()
            deadline = loop.time() + bound_s
            while proc.poll() is None and loop.time() < deadline:
                await asyncio.sleep(0.05)
            if proc.poll() is None:
                logger.warning("worker pid %s still alive %.0fs after "
                               "kill/disconnect", proc.pid, bound_s)

        task = asyncio.get_event_loop().create_task(_reap())
        self._reap_tasks.add(task)
        task.add_done_callback(self._reap_tasks.discard)

    async def _reap_procs(self, procs: List[Any],
                          timeout_s: float = 2.0) -> None:
        """Bounded shutdown sweep: stop() tears the loop down right
        after, so the async reapers can't be trusted to finish — poll
        (= waitpid WNOHANG for Popen) until every proc is collected or
        the bound expires."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout_s
        pending = [p for p in procs if p is not None and p.poll() is None]
        while pending and loop.time() < deadline:
            await asyncio.sleep(0.02)
            pending = [p for p in pending if p.poll() is None]
        for p in pending:
            logger.warning("worker pid %s unreaped at raylet stop", p.pid)

    # -------------------------------------------------------------- leases

    async def handle_request_worker_lease(self, conn, header, bufs):
        summary = protocol.RequestWorkerLeaseRequest.from_header(
            header).summary
        req = PendingRequest(
            req_id=next(self._req_counter),
            scheduling_class=summary["scheduling_class"],
            resources=summary["resources"],
            strategy=summary.get("strategy", "DEFAULT"),
            pg_id=summary.get("pg_id") or b"",
            pg_bundle=summary.get("pg_bundle", -1),
            env_hash=runtime_env_mod.hash_runtime_env(
                summary.get("runtime_env")),
            arrival_ts=time.monotonic(),
            task_id=summary.get("task_id") or b"",
            retriable=bool(summary.get("retriable", False)),
        )
        if self.memory_monitor.pressure:
            # Lease backpressure (watchdog sequence step 3): above the
            # memory threshold this node admits NO new work — it would
            # only be killed. Spill to a node with capacity when one
            # exists (the existing spillback path drains work off the
            # hot node), else a typed retry-later the owner backs off
            # on (backoff.py pacing, core_worker._request_lease).
            # Credit windows were already zeroed/revoked by the
            # heartbeat's _credit_beat the moment pressure crossed —
            # revocation comes BEFORE rejection, never instead of it.
            return self._memory_backpressure_reply(req)
        if self.config.lease_credits_enabled and not req.pg_id:
            # The request's backlog opens/refreshes this owner's credit
            # window; the legacy grant below still proceeds (it IS the
            # bootstrap probe) and the topup books the remaining slots.
            self._note_credit_demand(conn, req,
                                     summary.get("backlog"))
        if self.task_events.enabled and req.task_id:
            # the lease request carries the SAMPLE task at the head of
            # the owner's queue — that task's lease wait starts here
            self.task_events.record(req.task_id, PENDING_LEASE,
                                    {"node": self._nid12})
        self._init_dep_state(req, summary.get("dep_info") or [])
        fut = asyncio.get_running_loop().create_future()
        fut.client = conn  # type: ignore[attr-defined]
        self._pending[req.req_id] = (req, fut)

        def _on_drop(c, rid=req.req_id):
            self._cancel_pending(rid)

        conn.on_disconnect.append(_on_drop)
        self._schedule_tick()
        try:
            return await fut
        finally:
            # Don't accumulate one closure per lease on a long-lived conn.
            if _on_drop in conn.on_disconnect:
                conn.on_disconnect.remove(_on_drop)

    def _init_dep_state(self, req: PendingRequest, dep_info: List[dict]):
        """Dependency manager role (reference: dependency_manager.h:51):
        build the per-node locality map from the owner-supplied replica
        index, and pre-pull missing plasma args so dispatch is gated on
        data being local (RequestTaskDependencies -> HandleObjectLocal)."""
        locality: Dict[bytes, int] = {}
        missing: List[Tuple[ObjectID, str, int]] = []
        for d in dep_info:
            oid = ObjectID(d["oid"])
            size = d.get("size", 0)
            if self.store.contains(oid):
                locality[self.node_id.binary()] = \
                    locality.get(self.node_id.binary(), 0) + size
                continue
            for nid in d.get("locations", []):
                locality[nid] = locality.get(nid, 0) + size
            if size > 0 and d.get("locations"):
                # A plasma object that lives elsewhere: prefetch it.
                missing.append((oid, d.get("owner", ""), size))
        req.locality = locality
        if missing:
            req.deps_ready = False
            rpc.spawn_logged(self._prefetch_deps(req, missing),
                             "raylet-prefetch-deps")

    async def _prefetch_deps(self, req: PendingRequest,
                             missing: List[Tuple[ObjectID, str, int]]):
        async def pull_one(oid, owner, size):
            try:
                reply = await self._ensure_local(oid, owner)
                return size if reply.get("ok") else 0
            except Exception:  # noqa: BLE001 — dispatch gating is advisory;
                return 0       # the executing worker re-resolves args itself

        pulled = sum(await asyncio.gather(
            *(pull_one(oid, owner, size) for oid, owner, size in missing)))
        req.deps_ready = True
        if pulled:
            # the prefetched bytes are now local: update the locality term
            req.locality[self.node_id.binary()] = \
                req.locality.get(self.node_id.binary(), 0) + pulled
        self._schedule_tick()

    def _cancel_pending(self, req_id: int):
        entry = self._pending.pop(req_id, None)
        if entry and not entry[1].done():
            entry[1].cancel()

    # ------------------------------------------------- memory watchdog seams

    def _backpressure_views(self) -> List[NodeView]:
        """Cluster view with the LOCAL node's availability zeroed: the
        scheduler's own spillback scoring then picks drain targets for
        backpressured leases exactly like an ordinary saturated-node
        spill."""
        views = self._node_views()
        for v in views:
            if v.is_local:
                v.available = {k: 0.0 for k in v.available}
        return views

    def _memory_backpressure_reply(self, req: PendingRequest,
                                   views: Optional[List[NodeView]] = None
                                   ) -> dict:
        """The reply for a lease request rejected under memory pressure.
        Reuses the real scheduler for target choice (see
        _backpressure_views; a tick-time flush passes the view list in
        so it is built once per tick, not once per request).
        PG-targeted requests can't move (the bundle's node was fixed at
        PG creation) — they always get retry-later."""
        self.memory_monitor.note_backpressure()
        if faultpoints.armed:
            faultpoints.fire("lease.backpressure", node=self._nid12)
        if not req.pg_id:
            if views is None:
                views = self._backpressure_views()
            decisions = self.backend.schedule(
                [req], views, self.config.scheduler_spread_threshold)
            if decisions and decisions[0].action == SPILL:
                self.num_spillbacks += 1
                if self.task_events.enabled and req.task_id:
                    self.task_events.record(
                        req.task_id, SPILLBACK,
                        {"node": self._nid12,
                         "target": decisions[0].spill_address,
                         "reason": "memory_pressure"})
                return {"granted": False,
                        "spill": decisions[0].spill_address}
        return {"granted": False, "retry_later": True,
                "reason": "node memory pressure"}

    def _oom_kill_worker(self, handle: WorkerHandle, cause: dict) -> None:
        """Watchdog kill (memory_monitor.py step 2), dispatched async:
        the SIGKILL must not land before the owner KNOWS this death is
        an OOM kill."""
        rpc.spawn_logged(self._oom_kill_worker_async(handle, cause),
                         "raylet-oom-kill-worker")

    async def _oom_kill_worker_async(self, handle: WorkerHandle,
                                     cause: dict) -> None:
        """Tell the lease's owner FIRST and wait for its ack — a
        fire-and-forget push races the worker-socket EOF the SIGKILL
        produces, and the owner's retry decision runs on whichever
        arrives first. Only once the owner has recorded the cause (so
        the death is retried under the dedicated task_oom_retries
        budget as OutOfMemoryError, not the generic worker-crash
        budget) does the SIGKILL go out. An unreachable/slow owner
        bounds the wait at 1 s: the kill proceeds and the death
        degrades honestly to a generic WorkerCrashedError retry."""
        lease_id = handle.lease_id
        if handle.state != WORKER_LEASED or lease_id is None or \
                self.workers.get(handle.worker_id) is not handle:
            handle.oom_kill_pending = False
            return  # died / returned / replaced since the poll selected it
        lease = self.leases.get(lease_id)
        if lease is not None and lease.client is not None and \
                not lease.client.closed:
            try:
                await asyncio.wait_for(lease.client.call(
                    "WorkerOOMKilled", protocol.WorkerOOMKilledRequest(
                        worker_id=handle.worker_id,
                        cause=cause).to_header()), timeout=1.0)
            # raylint: disable=exception-hygiene — best-effort notify: an owner that can't ack still gets a typed (generic) worker-crash retry
            except Exception:
                pass
        # Re-grant guard: the lease may have completed during the ack
        # wait and the worker gone idle — or been re-leased to a
        # DIFFERENT owner that was never notified. Killing now would
        # shoot an innocent task and burn its generic crash budget:
        # abort, let the next poll re-evaluate on fresh state.
        if handle.state != WORKER_LEASED or handle.lease_id != lease_id \
                or self.workers.get(handle.worker_id) is not handle:
            handle.oom_kill_pending = False
            return
        self.memory_monitor.note_kill()
        self.events.emit(
            "WARNING", "WORKER_OOM_KILLED",
            f"memory watchdog killed worker "
            f"{handle.worker_id.hex()[:12]}",
            pid=handle.pid, node=self._nid12,
            usage_fraction=cause.get("usage_fraction"),
            rss=cause.get("workers_rss", {}).get(
                handle.worker_id.hex()[:12]))
        # _kill_worker pre-sets WORKER_DEAD, which makes the later
        # socket-EOF hit _on_worker_disconnect's early return — so the
        # disconnect path would never reclaim this handle. Do the full
        # teardown here, like every other _kill_worker call site: lease
        # released (resources returned), handle dropped from the table.
        self._kill_worker(handle)
        if lease_id in self.leases:
            self._release_lease(lease_id, worker_alive=False)
        self.workers.pop(handle.worker_id, None)
        self._schedule_tick()

    def _schedule_tick(self):
        if self._tick_scheduled or self._closing:
            return
        self._tick_scheduled = True
        asyncio.get_event_loop().call_soon(self._run_tick)

    def _run_tick(self):
        self._tick_scheduled = False
        if self._closing or not self._pending:
            return
        if self.memory_monitor.pressure:
            # Backpressure covers requests queued BEFORE the threshold
            # crossing too: flush them with the same spill/retry-later
            # reply so they drain to other nodes instead of waiting to
            # be granted into a node that would kill them. PG-targeted
            # requests stay pending — their bundle is reserved HERE so
            # they can't move — but are NOT granted either: they park
            # until the pressure clears (the heartbeat loop ticks on
            # the pressure->clear transition).
            bp_views = self._backpressure_views()
            for rid in sorted(self._pending.keys()):
                req, fut = self._pending[rid]
                if req.pg_id or fut.done():
                    continue
                self._pending.pop(rid)
                self._note_latency(req)
                fut.set_result((self._memory_backpressure_reply(
                    req, views=bp_views), ()))
            return
        # PG-targeted requests bypass node scoring: the bundle's node was
        # fixed at PG creation (reference: placement-group scheduling
        # resources are node-local labels).
        nodes = self._node_views()
        ordered = sorted(self._pending.keys())
        reqs = []
        pg_grants = []
        for rid in ordered:
            req, fut = self._pending[rid]
            if req.pg_id:
                pg_grants.append((rid, req, fut))
            else:
                reqs.append(req)
        t_tick = time.monotonic()
        decisions = self.backend.schedule(
            reqs, nodes, self.config.scheduler_spread_threshold) if reqs else []
        if reqs:
            t_done = time.monotonic()
            self._tick_durations.append((len(reqs), t_done - t_tick))
            for req in reqs:
                if not req.first_decision_ts:
                    req.first_decision_ts = t_done
        for rid, req, fut in pg_grants:
            if not req.first_decision_ts:
                req.first_decision_ts = t_tick
        for d in decisions:
            req, fut = self._pending.get(d.req_id, (None, None))
            if req is None or fut.done():
                self._pending.pop(d.req_id, None)
                continue
            if d.action == GRANT:
                self._try_grant(d.req_id, req, fut)
            elif d.action == SPILL:
                self.num_spillbacks += 1
                self._pending.pop(d.req_id, None)
                self._note_latency(req)
                if self.task_events.enabled and req.task_id:
                    self.task_events.record(
                        req.task_id, SPILLBACK,
                        {"node": self._nid12,
                         "target": d.spill_address})
                fut.set_result(({"granted": False, "spill": d.spill_address}, ()))
            elif d.action == INFEASIBLE:
                if self.config.infeasible_task_policy == "wait":
                    continue  # stays pending until capacity appears
                self._pending.pop(d.req_id, None)
                self._note_latency(req)
                fut.set_result(({"granted": False, "infeasible": True}, ()))
            # WAIT: stays pending.
        for rid, req, fut in pg_grants:
            self._try_grant_pg(rid, req, fut)

    def _node_views(self) -> List[NodeView]:
        views = [NodeView(
            node_id=self.node_id.binary(), address=self.address,
            total=self.resources_total,
            available=dict(self.resources_available), is_local=True)]
        for nid, info in self.remote_nodes.items():
            views.append(NodeView(
                node_id=nid, address=info["address"],
                total=info["resources_total"],
                available=dict(info["resources_available"]), is_local=False))
        return views

    def _try_grant(self, req_id: int, req: PendingRequest, fut: asyncio.Future):
        worker = self._pop_idle_worker(req.env_hash)
        if worker is None:
            if self._alive_worker_count() + self._num_starting < self.max_workers:
                self._start_worker_process()
            return  # stays pending until a worker registers/frees
        worker.env_hash = req.env_hash
        self._pending.pop(req_id, None)
        self._note_latency(req)
        lease_id = next(self._lease_counter)
        for k, v in req.resources.items():
            self.resources_available[k] = self.resources_available.get(k, 0.0) - v
        worker.state = WORKER_LEASED
        worker.lease_id = lease_id
        worker.leased_at = time.monotonic()
        worker.lease_retriable = req.retriable
        client = getattr(fut, "client", None)
        lease = LeaseEntry(lease_id, worker, req.resources, client)
        self.leases[lease_id] = lease
        self._watch_lease_client(lease)
        self.num_leases_granted += 1
        self._note_lease_granted(req, worker)
        if faultpoints.armed and self._fault_lease_grant(lease):
            return
        fut.set_result(({"granted": True, "lease_id": lease_id,
                         "worker_address": worker.address,
                         "worker_id": worker.worker_id,
                         "node_id": self.node_id.binary()}, ()))

    def _fault_lease_grant(self, lease: LeaseEntry) -> bool:
        """Lease-grant crash window (point ``raylet.lease.grant``):
        the lease is fully booked but the reply never reaches the
        client. ``sever`` closes the client's connection — the
        owner-liveness watch must then reclaim the worker and the
        resources; ``kill``/``raise`` execute inside fire(). Returns
        True when the grant reply must not be sent."""
        act = faultpoints.fire("raylet.lease.grant",
                               lease_id=lease.lease_id, node=self._nid12)
        if act == "sever" and lease.client is not None:
            lease.client._mark_closed()
            return True
        if act == "drop":
            return True
        return False

    def _note_lease_granted(self, req, worker: WorkerHandle) -> None:
        if self.task_events.enabled and req.task_id:
            self.task_events.record(
                req.task_id, LEASE_GRANTED,
                {"node": self._nid12,
                 "worker": worker.worker_id.hex()[:12]})

    def _try_grant_pg(self, req_id: int, req: PendingRequest, fut: asyncio.Future):
        key = (req.pg_id, req.pg_bundle)
        bundle_avail = self._pg_available.get(key)
        if bundle_avail is None:
            self._pending.pop(req_id, None)
            fut.set_result(({"granted": False, "infeasible": True,
                             "reason": "no such placement group bundle here"}, ()))
            return
        if not all(bundle_avail.get(k, 0.0) + 1e-9 >= v
                   for k, v in req.resources.items() if v > 0):
            return  # wait for bundle capacity
        worker = self._pop_idle_worker(req.env_hash)
        if worker is None:
            if self._alive_worker_count() + self._num_starting < self.max_workers:
                self._start_worker_process()
            return
        worker.env_hash = req.env_hash
        self._pending.pop(req_id, None)
        self._note_latency(req)
        for k, v in req.resources.items():
            bundle_avail[k] = bundle_avail.get(k, 0.0) - v
        lease_id = next(self._lease_counter)
        worker.state = WORKER_LEASED
        worker.lease_id = lease_id
        worker.leased_at = time.monotonic()
        worker.lease_retriable = req.retriable
        lease = LeaseEntry(lease_id, worker, req.resources,
                           getattr(fut, "client", None))
        lease.pg_key = key  # type: ignore[attr-defined]
        self.leases[lease_id] = lease
        self._watch_lease_client(lease)
        self.num_leases_granted += 1
        self._note_lease_granted(req, worker)
        if faultpoints.armed and self._fault_lease_grant(lease):
            return
        fut.set_result(({"granted": True, "lease_id": lease_id,
                         "worker_address": worker.address,
                         "worker_id": worker.worker_id,
                         "node_id": self.node_id.binary()}, ()))

    async def handle_return_worker(self, conn, header, bufs):
        req = protocol.ReturnWorkerRequest.from_header(header)
        lease = self.leases.get(req.lease_id)
        if lease is not None and not req.get("worker_died", False):
            cw = getattr(lease, "credit_window", None)
            w = self._credit_windows.get(cw) if cw is not None else None
            if w is not None:
                # A VOLUNTARY credit return is the demand-decay signal:
                # the owner's queue drained (it never returns credit
                # workers while it has backlog), so the window must not
                # be topped back up from the just-freed worker — that
                # would churn grant/idle/return cycles until the demand
                # report went stale.
                w.demand = 0
                w.demand_ts = time.monotonic()
        self._release_lease(req.lease_id,
                            worker_alive=not req.get("worker_died", False))
        return protocol.ReturnWorkerReply(ok=True).to_header()

    async def handle_report_lease_demand(self, conn, header, bufs):
        """Owner -> raylet backlog refresh (one-way push, paced by the
        owner to ~2/stale-period per scheduling class): keeps a live
        window from going stale mid-drain and lets a queue that grew
        WITHOUT a legacy lease request still open a window."""
        if not self.config.lease_credits_enabled or \
                self.memory_monitor.pressure:
            return {}
        req = protocol.ReportLeaseDemandRequest.from_header(header)
        key = (id(conn), req.sched_class)
        w = self._credit_windows.get(key)
        if w is None:
            w = CreditWindow(conn, req.sched_class,
                             dict(req.get("resources") or {}),
                             req.get("env_hash", ""),
                             bool(req.get("retriable", False)))
            self._credit_windows[key] = w
            conn.on_disconnect.append(
                lambda c, k=key: self._credit_windows.pop(k, None))
        w.demand = int(req.get("backlog", 0))
        w.demand_ts = time.monotonic()
        # the refresh carries the CURRENT queue head's properties:
        # victim eligibility and env affinity must track the live
        # backlog, not whatever task bootstrapped the window
        # (scheduling classes key on (resources, fn_key) only —
        # max_retries and runtime_env vary within one class)
        w.env_hash = req.get("env_hash", w.env_hash)
        w.retriable = bool(req.get("retriable", w.retriable))
        self._schedule_credit_topup()
        return {}

    def _watch_lease_client(self, lease: LeaseEntry):
        """Reclaim a granted lease if its owner's connection drops.

        Without this a driver that exits while holding leases leaks the
        leased resources forever and every later lease WAITs — the
        reference ties worker leases to owner liveness the same way
        (node manager DisconnectClient → owned-worker teardown). The
        worker is killed, not recycled: it may be mid-task for the dead
        job, and a poisoned "idle" worker would stall its next lease."""
        conn = lease.client
        if conn is None:
            return

        def _on_client_drop(c, lid=lease.lease_id):
            entry = self.leases.get(lid)
            if entry is None:
                return
            logger.warning(
                "lease %d owner disconnected; reclaiming worker %s",
                lid, entry.worker.worker_id.hex()[:8])
            self._kill_worker(entry.worker)
            self._release_lease(lid, worker_alive=False)

        lease.on_client_drop = _on_client_drop  # type: ignore[attr-defined]
        conn.on_disconnect.append(_on_client_drop)

    def _release_lease(self, lease_id: int, worker_alive: bool = True):
        lease = self.leases.pop(lease_id, None)
        if lease is None:
            return
        cb = getattr(lease, "on_client_drop", None)
        if cb is not None and lease.client is not None and \
                cb in lease.client.on_disconnect:
            lease.client.on_disconnect.remove(cb)
        cw = getattr(lease, "credit_window", None)
        if cw is not None:
            win = self._credit_windows.get(cw)
            if win is not None:
                win.lease_ids.discard(lease_id)
        pg_key = getattr(lease, "pg_key", None)
        if pg_key is not None and pg_key in self._pg_available:
            for k, v in lease.resources.items():
                self._pg_available[pg_key][k] = \
                    self._pg_available[pg_key].get(k, 0.0) + v
        elif pg_key is None:
            for k, v in lease.resources.items():
                self.resources_available[k] = \
                    self.resources_available.get(k, 0.0) + v
        gang_id = getattr(lease, "gang_id", None)
        if gang_id is not None:
            # A member lease dying out from under a LIVE gang breaks the
            # whole incarnation (observability mirror of the owner-side
            # epoch fence: the owner sees the member conn drop and fails
            # the step; this keeps GetNodeStats truthful about it).
            rec = self.gangs.get(gang_id)
            if rec is not None and \
                    rec["epoch"] == getattr(lease, "gang_epoch", -1) \
                    and not worker_alive:
                rec["broken"] = True
                rec["dead_members"] += 1
            mem = self._gang_members.get(gang_id)
            if mem is not None:
                mem["lease_ids"].discard(lease_id)
                if not mem["lease_ids"]:
                    self._gang_members.pop(gang_id, None)
        w = lease.worker
        w.lease_id = None
        if worker_alive and w.state == WORKER_LEASED:
            w.state = WORKER_IDLE
        self._schedule_tick()
        # the freed slot may satisfy another window's deficit (no-op
        # when demand is stale or decayed — target tracks demand)
        self._schedule_credit_topup()

    # ------------------------------------------------- streaming leases

    def _note_credit_demand(self, conn, req: PendingRequest,
                            backlog) -> None:
        """Open/refresh the credit window a lease request's backlog
        describes. Old-protocol clients send no backlog — they simply
        never get a window (pure legacy behavior)."""
        if backlog is None or conn is None or conn.closed:
            return
        key = (id(conn), req.scheduling_class)
        w = self._credit_windows.get(key)
        if w is None:
            w = CreditWindow(conn, req.scheduling_class,
                             dict(req.resources), req.env_hash,
                             req.retriable)
            self._credit_windows[key] = w
            conn.on_disconnect.append(
                lambda c, k=key: self._credit_windows.pop(k, None))
        w.demand = int(backlog)
        w.demand_ts = time.monotonic()
        w.env_hash = req.env_hash
        w.retriable = req.retriable
        self._schedule_credit_topup()

    def _schedule_credit_topup(self) -> None:
        if self._credit_topup_scheduled or self._closing or \
                not self._credit_windows:
            return
        self._credit_topup_scheduled = True
        asyncio.get_event_loop().call_soon(self._credit_topup)

    def _credit_window_target(self, w: CreditWindow) -> Tuple[int, int]:
        """Window sizing from the REAL scheduler view. Returns
        ``(local, cluster)`` slot targets: the owner's breadth
        heuristic (~one worker per 8 queued tasks) clamped by the slot
        capacity for this resource shape on THIS node (what this raylet
        can stream) and across the whole cluster view (how many legacy
        requests the owner may park for spillback beyond the stream),
        both bounded by the per-window ceiling. Pressure or stale
        demand zeroes both — an owner that stopped reporting backlog
        must not keep slots."""
        if self.memory_monitor.pressure or w.demand <= 0:
            return 0, 0
        if time.monotonic() - w.demand_ts > \
                self.config.lease_credit_stale_s:
            return 0, 0

        def _slots(view: NodeView) -> int:
            per = None
            for k, need in w.resources.items():
                if need <= 0:
                    continue
                n = int(view.total.get(k, 0.0) / need + 1e-9)
                per = n if per is None else min(per, n)
            if per is None:  # zero-resource shape: CPU slots bound it
                per = int(view.total.get("CPU", 0.0)) or 1
            return per

        local = 0
        cluster = 0
        for v in self._node_views():
            n = _slots(v)
            cluster += n
            if v.is_local:
                local += n
        want = max(1, w.demand // 8)
        cap = self.config.lease_credit_window_max
        return (max(0, min(cap, want, local)),
                max(0, min(cap, want, cluster)))

    def _credit_topup(self) -> None:
        """Book credits up to each live window's target and stream them
        to the owner (one GrantLeaseCredits push per window per round,
        piggybacking the window target so the owner stops parking
        legacy lease requests beyond it). Every credit books a real
        worker + resources through the same accounting as _try_grant."""
        self._credit_topup_scheduled = False
        if self._closing or not self.config.lease_credits_enabled or \
                self.memory_monitor.pressure:
            return
        for key, w in list(self._credit_windows.items()):
            if w.conn is None or w.conn.closed:
                self._credit_windows.pop(key, None)
                continue
            target, cluster = self._credit_window_target(w)
            credits: List[dict] = []
            while len(w.lease_ids) < target:
                cr = self._grant_credit(w)
                if cr is None:
                    break
                credits.append(cr)
            deficit = target - len(w.lease_ids)
            if deficit > 0:
                # pool ramp-up parity with the legacy path (which
                # starts one worker per parked request): kick off a
                # spawn per unfilled slot NOW — _start_worker_process
                # no-ops at the cap, and each registration re-triggers
                # the topup. Serial one-spawn-per-beat ramping measured
                # 20% off the 1M-drain wall on a many-core box.
                for _ in range(deficit):
                    self._start_worker_process()
            if not credits and target == w.target:
                continue  # nothing new to announce
            w.target = target
            if faultpoints.armed and faultpoints.fire(
                    "lease.credit.grant", node=self._nid12,
                    sched_class=w.sched_class,
                    n=len(credits)) == "drop":
                # grant push lost: the leases stay booked against this
                # owner; the stale-revoke beat reconciles them (the
                # owner replies "released" for ids it never received)
                continue
            try:
                w.conn.push_nowait(
                    "GrantLeaseCredits",
                    protocol.GrantLeaseCreditsRequest(
                        sched_class=w.sched_class,
                        raylet_address=self.address,
                        window_target=target,
                        cluster_slots=cluster,
                        resources=w.resources,
                        credits=credits).to_header())
            except ConnectionError:
                pass  # disconnect callbacks reclaim the booked leases

    def _grant_credit(self, w: CreditWindow) -> Optional[dict]:
        """Book ONE credit: idle worker + resources -> LeaseEntry,
        exactly like _try_grant minus the pending request. Returns the
        wire credit dict, or None when the pool/capacity can't serve
        one right now (a worker spawn may be kicked off for later)."""
        for k, v in w.resources.items():
            if v > 0 and self.resources_available.get(k, 0.0) + 1e-9 < v:
                return None
        worker = self._pop_idle_worker(w.env_hash)
        if worker is None:
            if self._alive_worker_count() + self._num_starting < \
                    self.max_workers:
                self._start_worker_process()
            return None
        worker.env_hash = w.env_hash
        lease_id = next(self._lease_counter)
        for k, v in w.resources.items():
            self.resources_available[k] = \
                self.resources_available.get(k, 0.0) - v
        worker.state = WORKER_LEASED
        worker.lease_id = lease_id
        worker.leased_at = time.monotonic()
        worker.lease_retriable = w.retriable
        lease = LeaseEntry(lease_id, worker, dict(w.resources), w.conn)
        lease.credit_window = (id(w.conn), w.sched_class)  # type: ignore[attr-defined]
        self.leases[lease_id] = lease
        self._watch_lease_client(lease)
        w.lease_ids.add(lease_id)
        self.num_credit_grants += 1
        # Per-GRANT latency sample (credit grants included): how long
        # this window's current demand waited for the slot. Keeps the
        # grant_wait reservoirs reflecting the grant population instead
        # of the handful of legacy requests a credit-served drain makes.
        wait = time.monotonic() - w.demand_ts
        self._sched_latencies.append(wait)
        self._grant_waits.append(wait)
        return {"lease_id": lease_id,
                "worker_address": worker.address,
                "worker_id": worker.worker_id,
                "node_id": self.node_id.binary()}

    def _credit_beat(self) -> None:
        """Heartbeat-cadence window maintenance: prune dead-conn
        windows, zero + revoke everything under memory pressure (the
        watchdog's poll ran just before this), offer back the excess of
        over-target windows, and top up under-target ones."""
        if not self.config.lease_credits_enabled or \
                not self._credit_windows:
            return
        now = time.monotonic()
        pressure = self.memory_monitor.pressure
        for key, w in list(self._credit_windows.items()):
            if w.conn is None or w.conn.closed:
                self._credit_windows.pop(key, None)
                continue
            target = 0 if pressure else self._credit_window_target(w)[0]
            if pressure and w.target != 0:
                # tell the owner its window is gone so it falls back to
                # legacy requests (which get the typed backpressure
                # reply and spill/back off) instead of waiting on a
                # stream that will not flow
                w.target = 0
                # a pressure-driven window zeroing is a recovery action
                # worth a cluster event (per window, beat-paced —
                # routine stale-window resizes are not)
                self.events.emit(
                    "WARNING", "LEASE_CREDITS_REVOKED",
                    f"memory pressure zeroed a credit window "
                    f"({len(w.lease_ids)} credits outstanding)",
                    node=self._nid12, sched_class=w.sched_class,
                    outstanding=len(w.lease_ids))
                try:
                    w.conn.push_nowait(
                        "GrantLeaseCredits",
                        protocol.GrantLeaseCreditsRequest(
                            sched_class=w.sched_class,
                            raylet_address=self.address,
                            window_target=0,
                            cluster_slots=0,
                            resources=w.resources,
                            credits=[]).to_header())
                except ConnectionError:
                    continue
            excess = len(w.lease_ids) - target
            if w.lease_ids and not w.revoking and \
                    (pressure or now - w.last_revoke_ts >=
                     self.config.lease_credit_stale_s):
                # Offer the window's credits back on every stale
                # period — not just when over target. The owner keeps
                # what it is using; what comes back is the excess,
                # idle-with-no-backlog slots, AND any PHANTOM credits
                # a dropped grant push booked that the owner never
                # heard of (it confirms unknown ids as released) — the
                # reconciliation a lost push depends on, which a
                # demand-fresh at-target window would otherwise never
                # trigger.
                max_release = len(w.lease_ids) \
                    if (pressure or excess <= 0) else excess
                w.last_revoke_ts = now
                w.revoking = True
                rpc.spawn_logged(
                    self._revoke_credits(
                        w, list(w.lease_ids), max_release,
                        "memory_pressure" if pressure
                        else "window_resize"),
                    "raylet-revoke-credits")
            if excess < 0 and not pressure:
                self._schedule_credit_topup()

    async def _revoke_credits(self, w: CreditWindow, lease_ids: List[int],
                              max_release: int, reason: str) -> None:
        """Offer ``lease_ids`` back to the owner (which relinquishes up
        to ``max_release`` it is not using; under ``memory_pressure``
        it releases idle credits even with backlog — draining work off
        this node IS the recovery) and reclaim what came back. A lost
        or unanswered revoke is safe: the credits stay valid and a
        later beat re-offers them; a dead owner's credits come back
        through the lease-client liveness watch."""
        try:
            if faultpoints.armed and faultpoints.fire(
                    "lease.credit.revoke", node=self._nid12,
                    sched_class=w.sched_class, reason=reason,
                    n=len(lease_ids)) == "drop":
                return
            try:
                reply, _ = await w.conn.call(
                    "RevokeLeaseCredits",
                    protocol.RevokeLeaseCreditsRequest(
                        lease_ids=lease_ids,
                        max_release=max_release,
                        reason=reason).to_header(),
                    timeout=2.0)
            except (ConnectionError, asyncio.TimeoutError):
                return
            rep = protocol.RevokeLeaseCreditsReply.from_header(reply)
            for lid in rep.released:
                if lid in w.lease_ids and lid in self.leases:
                    self.num_credit_revoked += 1
                    self._release_lease(lid)
                else:
                    # an id the owner never received (dropped grant
                    # push) or already returned: reconcile the ledger
                    w.lease_ids.discard(lid)
                    if lid in self.leases:
                        self.num_credit_revoked += 1
                        self._release_lease(lid)
        finally:
            w.revoking = False

    def _credit_stats(self) -> dict:
        outstanding = sum(len(w.lease_ids)
                          for w in self._credit_windows.values())
        total = self.num_credit_grants + self.num_leases_granted
        return {
            "enabled": self.config.lease_credits_enabled,
            "windows": len(self._credit_windows),
            "outstanding": outstanding,
            "granted_total": self.num_credit_grants,
            "revoked_total": self.num_credit_revoked,
            "legacy_grants_total": self.num_leases_granted,
            # share of all lease grants that were streamed credits —
            # the raylet-side credit hit-rate (the owner-side per-TASK
            # dispatch split lives in CoreWorker.stats
            # credit_dispatches / legacy_dispatches)
            "credit_grant_rate": round(
                self.num_credit_grants / total, 4) if total else 0.0,
        }

    # ---------------------------------------------------- SPMD gang leases

    def _book_gang_local(self, gang_id: bytes, epoch: int, count: int,
                         resources: Dict[str, float], env_hash: str,
                         client) -> List[dict]:
        """Book up to ``count`` members from THIS node's idle pool —
        immediately, never waiting: gang placement is all-or-nothing,
        so a shortfall is reported (and rolled back) rather than parked.
        Each booking is an ordinary LeaseEntry (owner-liveness reclaim,
        ReturnWorker, the memory watchdog's victim ordering and the
        resource ledger all see it like any lease), tagged with the
        gang id + epoch so releases keep the gang record honest."""
        members: List[dict] = []
        while len(members) < count:
            if not all(self.resources_available.get(k, 0.0) + 1e-9 >= v
                       for k, v in resources.items() if v > 0):
                break
            worker = self._pop_idle_worker(env_hash)
            if worker is None:
                break
            worker.env_hash = env_hash
            lease_id = next(self._lease_counter)
            for k, v in resources.items():
                self.resources_available[k] = \
                    self.resources_available.get(k, 0.0) - v
            worker.state = WORKER_LEASED
            worker.lease_id = lease_id
            worker.leased_at = time.monotonic()
            # gang steps run with max_retries=0 (a dead member fails
            # the whole step) — never a watchdog retriable victim
            worker.lease_retriable = False
            lease = LeaseEntry(lease_id, worker, dict(resources), client)
            lease.gang_id = gang_id      # type: ignore[attr-defined]
            lease.gang_epoch = epoch     # type: ignore[attr-defined]
            self.leases[lease_id] = lease
            self._watch_lease_client(lease)
            self.num_leases_granted += 1
            members.append({"lease_id": lease_id,
                            "worker_address": worker.address,
                            "worker_id": worker.worker_id,
                            "node_id": self.node_id.binary()})
        return members

    async def _release_gang_remote(self, node_id: bytes, gang_id: bytes,
                                   epoch: int, lease_ids: List[int],
                                   kill: bool) -> None:
        info = await self._lookup_node(node_id)
        if info is None:
            return
        try:
            peer = await self._peer_conn(info["address"])
            await peer.call(
                "ReleaseGangMembers",
                protocol.ReleaseGangMembersRequest(
                    gang_id=gang_id, epoch=epoch,
                    lease_ids=lease_ids, kill=kill).to_header())
        # raylint: disable=exception-hygiene — best-effort: a dead peer's bookings die with it (owner-liveness reclaim)
        except Exception:
            pass

    async def _release_gang(self, gang_id: bytes, rec: dict,
                            kill: bool = False) -> None:
        """Release every member of one gang incarnation: local leases
        through _release_lease, remote bookings via ReleaseGangMembers
        fan-out. Pops the record first so a re-entrant release (owner
        drop racing an explicit ReleaseGangLease) is a no-op."""
        if self.gangs.get(gang_id) is rec:
            self.gangs.pop(gang_id, None)
        drop = rec.pop("owner_drop", None)
        conn = rec.pop("owner_conn", None)
        if drop is not None and conn is not None and \
                drop in conn.on_disconnect:
            conn.on_disconnect.remove(drop)
        me = self.node_id.binary()
        remote: Dict[bytes, List[int]] = {}
        for m in rec["members"]:
            if m["node_id"] == me:
                lease = self.leases.get(m["lease_id"])
                if lease is not None:
                    if kill:
                        self._kill_worker(lease.worker)
                    self._release_lease(m["lease_id"],
                                        worker_alive=not kill)
            else:
                remote.setdefault(m["node_id"], []).append(m["lease_id"])
        if remote:
            await asyncio.gather(*(
                self._release_gang_remote(nid, gang_id, rec["epoch"],
                                          lids, kill)
                for nid, lids in remote.items()))

    async def _rollback_gang_booking(self, gang_id: bytes, epoch: int,
                                     members: List[dict],
                                     peer_bookings: List[Tuple[bytes,
                                                               List[int]]]
                                     ) -> None:
        me = self.node_id.binary()
        for m in members:
            if m["node_id"] == me:
                self._release_lease(m["lease_id"], worker_alive=True)
        if peer_bookings:
            await asyncio.gather(*(
                self._release_gang_remote(nid, gang_id, epoch, lids,
                                          kill=False)
                for nid, lids in peer_bookings))

    async def handle_request_gang_lease(self, conn, header, bufs):
        """ONE lease round books N workers across the cluster, or none:
        the home raylet takes what its own pool serves, fans
        BookGangMembers out to peers for the remainder, and rolls the
        whole booking back on any shortfall (all-or-nothing — Tesserae-
        style gang placement on the PR11 lease machinery). Epoch-fenced
        like actor incarnations: a request at or below the live
        incarnation's epoch is rejected; a higher epoch releases the
        old incarnation before booking the new one."""
        req = protocol.RequestGangLeaseRequest.from_header(header)
        gang_id = req.gang_id
        epoch = int(req.epoch)
        count = int(req.count)
        resources = dict(req.get("resources") or {"CPU": 1.0})
        env_hash = runtime_env_mod.hash_runtime_env(
            req.get("runtime_env"))
        rec = self.gangs.get(gang_id)
        if rec is not None and epoch <= rec["epoch"]:
            self.num_gang_rejects += 1
            return {"granted": False, "stale_epoch": True,
                    "current_epoch": rec["epoch"]}
        if rec is not None:
            # re-formation: the new epoch invalidates the previous
            # incarnation BEFORE any booking, so a stale member can
            # never survive into the new gang
            await self._release_gang(gang_id, rec)
        if self.memory_monitor.pressure:
            return {"granted": False, "retry_later": True,
                    "reason": "node memory pressure"}
        members = self._book_gang_local(gang_id, epoch, count, resources,
                                        env_hash, conn)
        peer_bookings: List[Tuple[bytes, List[int]]] = []
        try:
            if len(members) < count:
                # widest-pool peers first: fewer fan-out hops per round
                candidates = sorted(
                    self.remote_nodes.items(),
                    key=lambda kv: -kv[1]["resources_available"].get(
                        "CPU", 0.0))
                for nid, info in candidates:
                    if len(members) >= count:
                        break
                    try:
                        peer = await self._peer_conn(info["address"])
                        reply, _ = await peer.call(
                            "BookGangMembers",
                            protocol.BookGangMembersRequest(
                                gang_id=gang_id, epoch=epoch,
                                count=count - len(members),
                                resources=resources,
                                env_hash=env_hash).to_header())
                    except (ConnectionError, asyncio.TimeoutError):
                        continue
                    got = reply.get("members") or []
                    if got:
                        peer_bookings.append(
                            (nid, [m["lease_id"] for m in got]))
                        members.extend(got)
        except asyncio.CancelledError:
            await self._rollback_gang_booking(gang_id, epoch, members,
                                              peer_bookings)
            raise
        if len(members) < count:
            deficit = count - len(members)
            await self._rollback_gang_booking(gang_id, epoch, members,
                                              peer_bookings)
            # prestart toward the deficit so a retry converges instead
            # of rediscovering the same empty pool
            for _ in range(deficit):
                if self._alive_worker_count() + self._num_starting < \
                        self.max_workers:
                    self._start_worker_process()
            self.num_gang_rejects += 1
            return {"granted": False, "retry_later": True,
                    "reason": f"booked {len(members)}/{count} workers"}
        for rank, m in enumerate(members):
            m["rank"] = rank
        rec = {"epoch": epoch, "members": members,
               "created": time.time(), "broken": False,
               "dead_members": 0}
        self.gangs[gang_id] = rec

        def _on_owner_drop(c, gid=gang_id, r=rec):
            if self.gangs.get(gid) is r:
                rpc.spawn_logged(self._release_gang(gid, r, kill=True),
                                 "raylet-release-gang")

        rec["owner_conn"] = conn
        rec["owner_drop"] = _on_owner_drop
        conn.on_disconnect.append(_on_owner_drop)
        self.num_gang_leases += 1
        _spmd_metrics()["gang_leases"].inc()
        self.events.emit(
            "INFO", "GANG_LEASE_GRANTED",
            f"gang {gang_id.hex()[:12]} epoch {epoch}: booked "
            f"{count} workers across "
            f"{len({m['node_id'] for m in members})} node(s)",
            node=self._nid12, size=count, epoch=epoch)
        return {"granted": True, "epoch": epoch,
                "members": [dict(m) for m in members]}

    async def handle_book_gang_members(self, conn, header, bufs):
        """Peer side of the gang fan-out: book what this node's idle
        pool serves RIGHT NOW (the home raylet enforces all-or-nothing
        globally and rolls back on shortfall). The booking's lease
        client is the home raylet's connection — a dead home raylet
        reclaims its bookings through the ordinary owner-liveness
        watch."""
        req = protocol.BookGangMembersRequest.from_header(header)
        gang_id = req.gang_id
        epoch = int(req.epoch)
        mem = self._gang_members.get(gang_id)
        if mem is not None and epoch < mem["epoch"]:
            return {"members": [], "stale_epoch": True}
        if self.memory_monitor.pressure:
            return {"members": [], "reason": "node memory pressure"}
        members = self._book_gang_local(
            gang_id, epoch, int(req.count),
            dict(req.get("resources") or {}),
            req.get("env_hash", ""), conn)
        if members:
            mem = self._gang_members.get(gang_id)
            if mem is None or epoch > mem["epoch"]:
                mem = self._gang_members[gang_id] = {
                    "epoch": epoch, "lease_ids": set()}
            mem["lease_ids"].update(m["lease_id"] for m in members)
        elif self._alive_worker_count() + self._num_starting < \
                self.max_workers:
            self._start_worker_process()  # converge a retry's shortfall
        return {"members": members}

    async def handle_release_gang_members(self, conn, header, bufs):
        req = protocol.ReleaseGangMembersRequest.from_header(header)
        gang_id = req.gang_id
        epoch = int(req.epoch)
        mem = self._gang_members.get(gang_id)
        if mem is not None and epoch < mem["epoch"]:
            # stale release from a previous incarnation must not tear
            # down a newer booking
            return {"ok": False, "stale_epoch": True}
        kill = bool(req.get("kill", False))
        for lid in req.get("lease_ids") or []:
            lease = self.leases.get(lid)
            if lease is None or \
                    getattr(lease, "gang_id", None) != gang_id:
                continue
            if kill:
                # broken-gang teardown: the member may be mid-step for
                # the dead incarnation — recycling it as "idle" would
                # poison its next lease
                self._kill_worker(lease.worker)
            self._release_lease(lid, worker_alive=not kill)
        return {"ok": True}

    async def handle_release_gang_lease(self, conn, header, bufs):
        """Owner -> home raylet gang teardown, epoch-fenced: a release
        carrying an older epoch than the live incarnation is the stale
        member's push after re-formation — rejected, never applied."""
        req = protocol.ReleaseGangLeaseRequest.from_header(header)
        gang_id = req.gang_id
        epoch = int(req.epoch)
        rec = self.gangs.get(gang_id)
        if rec is None:
            return {"ok": True, "already_released": True}
        if epoch < rec["epoch"]:
            self.num_gang_rejects += 1
            return {"ok": False, "stale_epoch": True,
                    "current_epoch": rec["epoch"]}
        await self._release_gang(gang_id, rec,
                                 kill=bool(req.get("kill", False)))
        return {"ok": True}

    def _gang_stats(self) -> dict:
        return {
            "homed": [{
                "gang_id": gid.hex(),
                "epoch": rec["epoch"],
                "size": len(rec["members"]),
                "nodes": sorted({m["node_id"].hex()[:12]
                                 for m in rec["members"]}),
                "broken": rec["broken"],
                "dead_members": rec["dead_members"],
                "created": rec["created"],
            } for gid, rec in self.gangs.items()],
            "member_bookings": [{
                "gang_id": gid.hex(),
                "epoch": mem["epoch"],
                "leases": len(mem["lease_ids"]),
            } for gid, mem in self._gang_members.items()],
            "num_gang_leases": self.num_gang_leases,
            "num_gang_rejects": self.num_gang_rejects,
        }

    # -------------------------------------------------------------- actors

    async def handle_schedule_actor_creation(self, conn, header, bufs):
        spec = header["spec"]
        # Idempotence by actor id: a GCS that restarted mid-creation may
        # re-send the request while the first worker is alive — a second
        # instance would split-brain the actor.
        for w in self.workers.values():
            if w.state == WORKER_ACTOR and w.actor_id == header["actor_id"]:
                return {"ok": True, "already_created": True}
        resources = spec.get("resources", {"CPU": 1.0})
        pg_key = None
        # Reserve resources BEFORE any await: concurrent creations must not
        # both pass the availability check and oversubscribe the node.
        if spec.get("pg_id"):
            pg_key = (spec["pg_id"], spec.get("pg_bundle", 0))
            bundle_avail = self._pg_available.get(pg_key)
            if bundle_avail is None or not all(
                    bundle_avail.get(k, 0.0) + 1e-9 >= v
                    for k, v in resources.items() if v > 0):
                return {"ok": False, "reason": "pg bundle unavailable"}
            for k, v in resources.items():
                bundle_avail[k] = bundle_avail.get(k, 0.0) - v
        else:
            if not all(self.resources_available.get(k, 0.0) + 1e-9 >= v
                       for k, v in resources.items() if v > 0):
                return {"ok": False, "reason": "insufficient resources"}
            for k, v in resources.items():
                self.resources_available[k] = \
                    self.resources_available.get(k, 0.0) - v
        worker = self._pop_idle_worker()
        if worker is None:
            self._start_worker_process(force=True)
            deadline = time.time() + self.config.worker_register_timeout_s
            while worker is None and time.time() < deadline:
                await asyncio.sleep(0.02)
                worker = self._pop_idle_worker()
            if worker is None:
                self._give_back(resources, pg_key)
                return {"ok": False, "reason": "no worker available"}
        worker.state = WORKER_ACTOR
        worker.actor_id = header["actor_id"]
        worker.actor_resources = resources  # type: ignore[attr-defined]
        worker.actor_pg_key = pg_key        # type: ignore[attr-defined]
        try:
            reply, _ = await worker.conn.call(
                "CreateActor",
                {"actor_id": header["actor_id"], "spec": spec,
                 "incarnation": header.get("incarnation", 0)},
                bufs=bufs)
        except ConnectionError:
            return {"ok": False, "reason": "worker died during actor creation"}
        if not reply.get("ok"):
            worker.state = WORKER_IDLE
            worker.actor_id = b""
            self._give_back(resources, pg_key)
            # Creation raised in __init__: actor is DEAD with the error.
            await self.gcs_conn.call("ReportActorDeath", {
                "actor_id": header["actor_id"],
                "reason": reply.get("error", "actor constructor failed"),
                "cause": {"kind": "CREATION_FAILED",
                          "node_id": self.node_id.hex(),
                          "worker_id": worker.worker_id.hex()},
                "expected": True})
            return {"ok": True}
        alive_reply, _ = await self.gcs_conn.call("ReportActorAlive", {
            "actor_id": header["actor_id"],
            "address": worker.address,
            "node_id": self.node_id.binary(),
            "incarnation": header.get("incarnation", 0)})
        if not alive_reply.get("ok"):
            # Superseded incarnation or killed-while-constructing: tear the
            # instance down instead of leaving a duplicate live actor.
            self._give_back(resources, pg_key)
            worker.actor_resources = {}
            self._kill_worker(worker)
            self.workers.pop(worker.worker_id, None)
            return {"ok": True}
        # Creation done: swap the hold to the actor's *lifetime* resources
        # (reference parity, python/ray/actor.py — default actors place
        # their creation with 1 CPU but hold 0 while alive). PG actors keep
        # the bundle reservation unchanged.
        lifetime = spec.get("lifetime_resources")
        if pg_key is None and lifetime is not None and lifetime != resources:
            self._give_back(resources, None)
            for k, v in lifetime.items():
                self.resources_available[k] = \
                    self.resources_available.get(k, 0.0) - v
            worker.actor_resources = lifetime
            self._schedule_tick()
        return {"ok": True}

    def _give_back(self, resources, pg_key):
        if pg_key is not None:
            # Bundle-scoped resources return to the bundle; if the PG was
            # removed meanwhile, ReturnPGBundle already returned the whole
            # bundle to the node pool — crediting it again would inflate
            # node capacity.
            if pg_key in self._pg_available:
                for k, v in resources.items():
                    self._pg_available[pg_key][k] = \
                        self._pg_available[pg_key].get(k, 0.0) + v
            return
        for k, v in resources.items():
            self.resources_available[k] = \
                self.resources_available.get(k, 0.0) + v

    async def handle_kill_actor_worker(self, conn, header, bufs):
        actor_id = header["actor_id"]
        for w in list(self.workers.values()):
            if w.actor_id == actor_id and w.state == WORKER_ACTOR:
                self._give_back(getattr(w, "actor_resources", {}),
                                getattr(w, "actor_pg_key", None))
                w.actor_resources = {}
                self._kill_worker(w)
                self.workers.pop(w.worker_id, None)
                return {"ok": True}
        return {"ok": False, "reason": "actor worker not found"}

    async def handle_actor_exited(self, conn, header, bufs):
        """Graceful actor exit from the worker itself."""
        wid = conn.tags.get("worker_id")
        handle = self.workers.get(wid) if wid else None
        if handle is not None:
            self._give_back(getattr(handle, "actor_resources", {}),
                            getattr(handle, "actor_pg_key", None))
            handle.actor_resources = {}
        try:
            await self.gcs_conn.call("ReportActorDeath", {
                "actor_id": header["actor_id"],
                "reason": header.get("reason", "actor exited"),
                "expected": True})
        except ConnectionError:
            pass
        return {"ok": True}

    # --------------------------------------------------------- object plane

    async def handle_seal_object(self, conn, header, bufs):
        req = protocol.SealObjectRequest.from_header(header)
        oid = ObjectID(req.object_id)
        # "shard": DistributedArray placement attrs (rank / mesh
        # coords), folded into the SEALED object-plane record so
        # state.list_objects() shows where each shard landed
        ok = self.store.seal(oid, req.segment, req.size,
                             attrs=req.get("shard"))
        if ok and req.get("pin", False):
            self.store.pin(oid)
        owner_address = req.get("owner_address")
        if ok and owner_address:
            # leak-detector owner index: the sweep probes this owner's
            # live references against the stored segment
            self._object_owners[oid.binary()] = owner_address
        return protocol.SealObjectReply(
            ok=ok, node_id=self.node_id.binary()).to_header()

    async def handle_alloc_segment(self, conn, header, bufs):
        """Lease a recycled warm segment to a writing client (zero-copy
        put pipeline): the client fills it and SealObject returns it to
        the accounted tables. No lease -> the client creates a fresh
        segment, exactly as before this RPC existed."""
        # raylint: disable=shm-lifecycle — lease ownership transfers to the remote writer: SealObject/AbortSegment (or the stale sweep) closes it
        got = self.store.take_recycled(int(header["size"]))
        if got is None:
            return {"found": False}
        return {"found": True, "segment": got[0], "size": got[1]}

    async def handle_abort_segment(self, conn, header, bufs):
        """Abort half of the lease protocol: a writer whose fill failed
        hands the segment straight back (one-way push) instead of
        leaving it parked in _lent until the 600 s stale sweep."""
        self.store.abort_lease(header["segment"])
        return {"ok": True}

    async def handle_get_object_info(self, conn, header, bufs):
        oid = ObjectID(header["object_id"])
        segment = self.store.lookup(oid)
        if segment is None:
            return {"found": False}
        self.store.mark_exposed(oid)  # caller may mmap the segment
        return {"found": True, "segment": segment}

    async def handle_pin_object(self, conn, header, bufs):
        self.store.pin(ObjectID(header["object_id"]))
        return {"ok": True}

    async def handle_free_object(self, conn, header, bufs):
        oid = ObjectID(header["object_id"])
        if faultpoints.armed and faultpoints.fire(
                "object.free", oid=oid.hex(), node=self._nid12) == "drop":
            # free fault: the FreeObject is LOST before any state
            # changes — the store keeps the segment, the owner believes
            # it freed. Exactly the orphan class the leak detector's
            # sweep exists to catch (and reclaim).
            return {"ok": True}
        self._drop_object_bookkeeping(oid)
        self._free_local_object(oid)

        # Owner-supplied location list: forward the free to every other node
        # holding a copy (the owner has no raylet connections of its own).
        async def _free_on(nid: bytes):
            # _lookup_node, not remote_nodes: a replica on a peer this
            # raylet never saw register (the pubsub late-join gap) must
            # still be freed, exactly like it can be pulled from
            info = await self._lookup_node(nid)
            if info is None:
                return
            try:
                peer = await self._peer_conn(info["address"])
                await peer.call("FreeObject", {"object_id": oid.binary()})
            # raylint: disable=exception-hygiene — best-effort per peer; owner re-frees on next GC pass
            except Exception:
                pass

        peers = [nid for nid in header.get("locations", [])
                 if nid != self.node_id.binary()]
        if peers:
            await asyncio.gather(*[_free_on(nid) for nid in peers])
        return {"ok": True}

    async def handle_fetch_object_meta(self, conn, header, bufs):
        """Size + bulk-transfer endpoint probe that opens a pull: the
        puller learns total_size for admission/segment sizing and the
        data-channel address chunk requests should go to (empty = this
        node serves chunks over the control plane only)."""
        oid = ObjectID(header["object_id"])
        entry = self.store.entry(oid)
        if entry is None:
            return {"found": False}
        # A remote raylet is about to read chunks of this segment: it
        # must never enter the recycle pool mid-pull (same pin as the
        # chunk serve paths).
        self.store.mark_exposed(oid)
        return {"found": True, "total_size": entry[1],
                "data_address": self.data_address}

    async def _attach_serve_segment(self, segment: str):
        """Cached shared-memory attachment of a LOCAL segment for read
        serving (control-plane chunk serves + gather local-source
        copies). _QuietSharedMemory: cache eviction may race an
        in-flight chunk send whose memoryview still pins the mapping —
        deferred release absorbs that instead of leaking the fd on
        BufferError. Attached in an executor: the MAP_POPULATE remap of
        a GiB-scale segment must not stall the raylet loop."""
        shm = self._serve_attachments.get(segment)
        if shm is not None:
            return shm
        from ray_tpu._private.shm_store import _QuietSharedMemory
        new_shm = await asyncio.get_running_loop().run_in_executor(
            None, _QuietSharedMemory, segment)
        shm = self._serve_attachments.get(segment)
        if shm is not None:  # raced a concurrent first attach
            try:
                new_shm.close()
            except BufferError:
                pass
            return shm
        shm = new_shm
        # bounded cache: drop the oldest attachment beyond 16
        while len(self._serve_attachments) >= 16:
            oldest = next(iter(self._serve_attachments))
            old = self._serve_attachments.pop(oldest)
            try:
                old.close()
            except BufferError:
                pass  # a concurrent chunk read still holds it
        self._serve_attachments[segment] = shm
        return shm

    async def handle_fetch_object_chunk(self, conn, header, bufs):
        """Serve one chunk of a remote raylet's pull over the CONTROL
        plane (reference: the chunked Push path,
        src/ray/object_manager/push_manager.h). Retained as the
        fallback for peers whose puller runs with the data plane
        disabled (data_plane_stripes=0); striped pulls use the raw
        data channel (data_channel.py) instead."""
        oid = ObjectID(header["object_id"])
        segment = self.store.lookup(oid)
        if segment is None:
            return {"found": False}
        # a remote raylet is mid-pull: its future chunk reads must see
        # this exact data, so the segment can never enter the recycle
        # pool (an owner-side free mid-pull would otherwise let a new
        # writer overwrite the still-linked file under the puller)
        self.store.mark_exposed(oid)
        offset = header["offset"]
        length = header["length"]
        shm = await self._attach_serve_segment(segment)
        entry = self.store._objects.get(oid)  # noqa: SLF001
        total = entry[1] if entry is not None else shm.size
        end = min(offset + length, total)
        # zero-copy: the chunk rides to the socket as a live view of the
        # mapped segment — node-to-node pushes never flatten to bytes
        data = shm.buf[offset:end] if end > offset else b""
        return {"found": True, "total_size": total}, [data]

    async def handle_ensure_object_local(self, conn, header, bufs):
        """Pull an object into the local store from wherever it lives
        (reference: PullManager admission + ObjectManager::Pull)."""
        return await self._ensure_local(
            ObjectID(header["object_id"]), header.get("owner_address", ""))

    async def _ensure_local(self, oid: ObjectID, owner_address: str) -> dict:
        if self.store.contains(oid):
            segment = self.store.lookup(oid)
            if segment is not None:
                # the caller will mmap this segment: it can never be
                # recycled (zero-copy views may outlive the free)
                self.store.mark_exposed(oid)
                return {"ok": True, "segment": segment}
        # Dedupe concurrent pulls of the same object (reference:
        # PullManager bundles many requests for one object into one pull).
        pull = self._active_pulls.get(oid)
        if pull is None:
            pull = asyncio.get_running_loop().create_task(
                self._pull_object(oid, owner_address))
            self._active_pulls[oid] = pull
            pull.add_done_callback(
                lambda _: self._active_pulls.pop(oid, None))
        return await asyncio.shield(pull)

    async def _pull_object(self, oid: ObjectID, owner_address: str) -> dict:
        reason = "object not found at any location"
        attempts = max(0, self.config.pull_location_refresh_attempts)
        # floor at 1 ms: pull_location_refresh_backoff_s = 0 ("refresh
        # immediately") was valid before the backoff policy and must
        # stay valid — Backoff itself rejects a non-positive base
        base = max(self.config.pull_location_refresh_backoff_s, 1e-3)
        bo = backoff_mod.Backoff(
            base_s=base,
            cap_s=max(self.config.retry_backoff_cap_s, base),
            multiplier=self.config.retry_backoff_multiplier)
        for round_no in range(1 + attempts):
            if round_no:
                if not owner_address:
                    break  # nobody to re-ask for locations
                # Every known location failed (peer death / replica
                # freed mid-pull). Refresh the owner's location index
                # after a backoff (exponential-jitter across rounds,
                # pull_location_refresh_attempts of them): a replica
                # added meanwhile (e.g. by a concurrent pull elsewhere)
                # is found instead of erroring the get.
                await bo.sleep()
            locations = await self._query_locations(oid, owner_address)
            sources = await self._pull_sources(locations)
            if not sources:
                continue
            pulled = await self._pull_chunked(oid, sources)
            if pulled is None:
                continue
            name, total = pulled
            if not self.store.seal(oid, name, total):
                # distinct reason: the transfer SUCCEEDED — pointing
                # the operator at replica locations would hide the
                # real (local capacity) cause
                reason = "local store refused seal (capacity)"
                break  # retrying cannot help
            # Report the replica to the owner so its location index
            # stays complete and FreeObject reaches this node too
            # (reference: ObjectDirectory location adds).
            if owner_address:
                # leak-detector owner index: pulled replicas are judged
                # against the same owner the seal path records
                self._object_owners[oid.binary()] = owner_address
                async def _report(addr=owner_address):
                    try:
                        owner = await self._owner_conn(addr)
                        r, _ = await owner.call(
                            "AddObjectLocation", {
                                "object_id": oid.binary(),
                                "node_id": self.node_id.binary()})
                        if not r.get("ok"):
                            # owner already released the object —
                            # drop our replica
                            self.store.free(oid)
                    # raylint: disable=exception-hygiene — owner may be gone; replica already dropped
                    except Exception:
                        pass
                rpc.spawn_logged(_report(), "raylet-report-replica")
            self.store.mark_exposed(oid)  # caller is about to mmap
            return {"ok": True, "segment": name}
        return {"ok": False, "reason": reason}

    async def _query_locations(self, oid: ObjectID,
                               owner_address: str) -> List[bytes]:
        if not owner_address:
            return []
        try:
            owner = await self._owner_conn(owner_address)
            reply, _ = await owner.call(
                "GetObjectLocations",
                protocol.GetObjectLocationsRequest(
                    object_id=oid.binary()).to_header())
            return reply.get("locations", [])
        except ConnectionError:
            return []

    async def _lookup_node(self, nid: bytes) -> Optional[dict]:
        """Node info for the PULL/free path: the pubsub view first,
        then a GCS directory lookup for nodes that registered before
        this raylet subscribed (the late-join gap) — a pull must reach
        EVERY replica holder, not just peers whose alive event this
        raylet happened to see. Deliberately not fed into remote_nodes:
        the scheduler's spillback view stays pubsub-driven. Concurrent
        cache misses (a fan-out pull probing N locations at once) share
        ONE in-flight GetAllNodeInfo instead of stampeding the GCS."""
        info = self.remote_nodes.get(nid) or self._node_directory.get(nid)
        if info is not None:
            return info
        if self._node_dir_refresh is None or self._node_dir_refresh.done():
            self._node_dir_refresh = asyncio.get_running_loop() \
                .create_task(self._refresh_node_directory())
        # shield: this caller's cancellation must not kill the refresh
        # other concurrent lookups are waiting on
        await asyncio.shield(self._node_dir_refresh)
        return self.remote_nodes.get(nid) or self._node_directory.get(nid)

    async def _refresh_node_directory(self) -> None:
        try:
            reply, _ = await self.gcs_conn.call("GetAllNodeInfo", {})
        except ConnectionError:
            return
        for n in reply.get("nodes", []):
            if not n.get("alive") or n["node_id"] == self.node_id.binary():
                continue
            self._node_directory.setdefault(n["node_id"], {
                "address": n["address"],
                "data_address": n.get("data_address", ""),
                "resources_total": n.get("resources_total", {}),
                "resources_available": dict(
                    n.get("resources_available", {})),
            })

    @staticmethod
    async def _first_plus_grace(coros, grace: float = 0.5) -> list:
        """Run coroutines concurrently and return the truthy results —
        but once ANY of them yields one, give the stragglers only
        ``grace`` seconds before abandoning (cancelling) them. This is
        how every pull-setup fan-out is bounded: a dead peer's connect
        timeout must never gate the work the live peers can already do
        (it costs at most ``grace`` on top of the fastest success)."""
        tasks = [asyncio.ensure_future(c) for c in coros]
        results: list = []
        try:
            pending = set(tasks)
            while pending and not any(results):
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED)
                for t in done:  # all done: these awaits return at once
                    results.append(await t)
            if pending:
                done, _ = await asyncio.wait(pending, timeout=grace)
                for t in done:
                    results.append(await t)
        finally:
            for t in tasks:
                t.cancel()
            # shield: if THIS task is cancelled during the reap, the
            # children must still be awaited — an abandoned gather
            # leaves their CancelledErrors unretrieved and any
            # half-open connections unreaped
            await asyncio.shield(
                asyncio.gather(*tasks, return_exceptions=True))
        return [r for r in results if r]

    async def _pull_sources(self, locations: List[bytes]
                            ) -> List[Tuple[rpc.Connection, str]]:
        """Reachable replica holders as (control conn, data_address).
        Connects run CONCURRENTLY, first success + grace: one dead peer
        never delays pulling from the live replicas."""
        async def _one(nid: bytes):
            info = await self._lookup_node(nid)
            if info is None:
                return None
            try:
                conn = await self._peer_conn(info["address"])
            except ConnectionError:
                return None
            return conn, info.get("data_address", "")

        candidates = [nid for nid in locations
                      if nid != self.node_id.binary()]
        if not candidates:
            return []
        return await self._first_plus_grace(_one(n) for n in candidates)

    def _pull_chunk_size(self, total: int, num_peers: int) -> int:
        """Adaptive data-plane chunk size. object_manager_chunk_size
        stays the FLOOR (and the exact size with the data plane off);
        large objects raise it toward data_plane_max_chunk_size so the
        transfer is copy-bound, not request-round-trip-bound — while
        keeping ~8 chunks per stripe so fan-out still balances."""
        floor = self.config.object_manager_chunk_size
        if self.config.data_plane_stripes <= 0:
            return floor
        lanes = self.config.data_plane_stripes * max(1, num_peers)
        target = -(-total // (8 * lanes))  # ceil div
        return min(max(floor, target),
                   max(floor, self.config.data_plane_max_chunk_size))

    async def _admit_pull(self, total: int, chunk: int) -> None:
        """Pull admission control (reference: pull_manager.h:47): wait
        — parked on the Condition, notified at every pull completion,
        no sleep-polling — until the in-flight byte budget has room.

        HONEST BUDGET: a single object LARGER than the whole budget can
        never fit under it, so it is admitted exactly when nothing else
        is in flight (``_pull_inflight_bytes == 0``) — oversized pulls
        serialize with everything else instead of deadlocking the
        admission queue (waiting for room that can never appear) or
        stampeding the store alongside admitted pulls."""
        budget = max(self.store.capacity // 4, chunk)
        async with self._pull_cond:
            await self._pull_cond.wait_for(
                lambda: self._pull_inflight_bytes == 0 or
                self._pull_inflight_bytes + total <= budget)
            self._pull_inflight_bytes += total

    def _notify_pull_done(self) -> None:
        """Wake admission waiters after ``_pull_inflight_bytes``
        dropped. The decrement itself runs synchronously in the
        caller's ``finally`` (a cancelled task must never leak budget);
        the Condition notify needs its lock held, so it rides a fresh
        task that cannot be cancelled with the pull."""
        async def _notify():
            async with self._pull_cond:
                self._pull_cond.notify_all()
        rpc.spawn_logged(_notify(), "raylet-pull-done-notify")

    async def _data_channel(self, address: str):
        """Cached striped data-channel client for one peer (reference:
        ObjectManager's per-peer transfer connections). Stripes dropped
        by failures or cancelled pulls are topped back up here, so a
        transient error never leaves the channel permanently degraded."""
        from ray_tpu._private.data_channel import DataChannelClient
        ch = self._data_channels.get(address)
        if ch is not None and ch.alive and \
                len(ch.stripes) < ch.num_stripes:
            await ch.ensure_stripes()
        if ch is None or not ch.alive:
            fresh = await DataChannelClient(
                address, self.config.data_plane_stripes).connect()
            ch = self._data_channels.get(address)
            if ch is not None and ch.alive:
                # raced a concurrent pull's connect during the await:
                # keep the cached client, close the loser's sockets
                await fresh.close()
            else:
                self._data_channels[address] = ch = fresh
        return ch

    async def _pull_fetchers(self, oid: ObjectID, found, chunk: int,
                             total: int, buf) -> list:
        """One fetch coroutine per transfer lane: every stripe of every
        replica-holding peer's data channel — chunk bytes land DIRECTLY
        in ``buf`` (the destination mapping) via the data plane's
        recv_into, one copy per chunk — or, for peers without a data
        channel, a window of control-plane FetchObjectChunk slots
        (socket -> bytes -> copy_into, the pre-data-plane path)."""
        from ray_tpu._private import native
        oid_b = oid.binary()

        async def _source_fetchers(conn, data_address):
            channel = None
            if data_address and self.config.data_plane_stripes > 0:
                try:
                    channel = await self._data_channel(data_address)
                except ConnectionError:
                    channel = None  # data port dead; control conn lives
            fetchers = []
            if channel is not None:
                for stripe in channel.stripes:
                    async def _fetch(off, _s=stripe, _ch=channel):
                        await _ch.fetch_chunk(
                            _s, oid_b, off, min(chunk, total - off),
                            buf, off)
                    fetchers.append(_fetch)
            else:
                async def _legacy(off, _conn=conn):
                    from ray_tpu._private.data_channel import \
                        note_control_chunk
                    # Control-plane lane: these frames SHARE the RPC
                    # stream with heartbeats and lease grants, so the
                    # adaptive data-plane chunk must never inflate them
                    # — sub-fetch at the fixed control-plane size,
                    # keeping the pre-data-plane bound (8 lanes x
                    # object_manager_chunk_size bytes in flight).
                    floor = self.config.object_manager_chunk_size
                    end = min(off + chunk, total)
                    sub = off
                    while sub < end:
                        want = min(floor, end - sub)
                        r, bufs2 = await _conn.call("FetchObjectChunk", {
                            "object_id": oid_b, "offset": sub,
                            "length": want})
                        if not r.get("found"):
                            raise ConnectionError(
                                "object vanished mid-pull")
                        if len(bufs2[0]) != want:
                            raise ConnectionError(
                                "short chunk from divergent replica")
                        native.copy_into(buf, sub, bufs2[0])
                        # counts the one intermediate bytes copy (the
                        # recv loop materialized this sub-chunk before
                        # copy_into) in pull_stats AND the Prometheus
                        # tier counters
                        note_control_chunk(want)
                        sub += want
                # the old pull window: 8 in-flight chunks per peer
                fetchers.extend([_legacy] * 8)
            return fetchers

        # Per-peer channel setup runs CONCURRENTLY, first success +
        # grace: a black-holed data port's stripe-dial timeout never
        # holds back lanes the reachable peers already have up —
        # stragglers are abandoned (their cancelled dials close their
        # own sockets) and the pull starts on the ready lanes.
        per_source = await self._first_plus_grace(
            _source_fetchers(c, d) for c, d in found)
        return [f for lanes in per_source for f in lanes]

    def _segment_reaper(self, alloc):
        """Done-callback for a segment-mapping executor future whose
        awaiter was cancelled. run_in_executor work cannot be
        interrupted: the thread still maps (and holds the recycled
        lease on) the segment after the cancel unwinds, so the
        eventual result is reaped HERE — close the mapping, re-park a
        recycled lease, unlink a fresh segment. Runs on the loop
        thread (executor futures schedule callbacks there), so store
        state is safe to touch."""
        from ray_tpu._private.shm_store import _close_segment_owner

        def _reap(fut):
            if fut.cancelled() or fut.exception() is not None:
                if alloc is not None:
                    self.store.abort_lease(alloc[0])
                return
            name, owner, buf = fut.result()
            _close_segment_owner(owner, buf)
            if alloc is not None and name == alloc[0]:
                self.store.abort_lease(name)
            else:
                self._unlink_segment(name)
        return _reap

    async def _pull_chunked(self, oid: ObjectID,
                            sources: List[Tuple[rpc.Connection, str]]
                            ) -> Optional[Tuple[str, int]]:
        """Striped, flow-controlled pull into a fresh local segment;
        returns (segment_name, total_size) or None when no source could
        serve the object. Chunk offsets fan out across every stripe of
        every replica-holding peer (data_channel.run_striped); a failed
        stripe hands its chunk to the survivors, so the pull outlives
        anything short of every source dying (reference: PushManager's
        chunk window + ObjectBufferPool chunk writes). Admission: total
        in-flight pull bytes are bounded so concurrent pulls cannot
        overcommit the store (reference: pull_manager.h:47)."""
        from collections import deque

        from ray_tpu._private import data_channel
        from ray_tpu._private.shm_store import (
            RECYCLE_MIN_BYTES, _close_segment_owner, acquire_segment)

        # Probe every source for size + bulk endpoint (concurrently,
        # first success + grace — a wedged-but-connected peer whose
        # call never answers must not park the pull); unreachable or
        # object-less sources drop out here.
        async def _probe(conn, data_address):
            try:
                reply, _ = await conn.call(
                    "FetchObjectMeta", protocol.FetchObjectMetaRequest(
                        object_id=oid.binary()).to_header())
            except ConnectionError:
                return None
            if not reply.get("found"):
                return None
            return (conn, reply.get("data_address") or data_address,
                    reply["total_size"])

        probes = await self._first_plus_grace(
            _probe(c, d) for c, d in sources)
        found: List[Tuple[rpc.Connection, str]] = []
        total = 0
        for conn, data_address, t in probes:
            if found and t != total:
                # divergent replica (size disagrees with the first
                # holder): chunks from it could seal wrong bytes —
                # drop the source, the strict per-chunk length check
                # is the backstop
                continue
            total = t
            found.append((conn, data_address))
        if not found:
            return None
        chunk = self._pull_chunk_size(total, len(found))
        await self._admit_pull(total, chunk)
        t_pull = time.monotonic()
        try:
            # Destination: a recycled warm segment when the local store
            # has one (page allocation dominates cold pull writes), else
            # a fresh MAP_POPULATE mapping; chunk payloads are received
            # straight into it.
            alloc = self.store.take_recycled(total) \
                if total >= RECYCLE_MIN_BYTES else None
            loop = asyncio.get_running_loop()
            # executor: a fresh multi-GiB MAP_POPULATE create would
            # otherwise stall the raylet loop for the whole zero-fill.
            # Shielded: the mapping thread cannot be interrupted, so a
            # cancel at this await must hand the eventual segment (and
            # the recycled lease) to the reaper instead of leaking both.
            fut = loop.run_in_executor(
                None, acquire_segment, alloc, max(total, 1))
            try:
                name, owner, buf = await asyncio.shield(fut)
            except asyncio.CancelledError:
                fut.add_done_callback(self._segment_reaper(alloc))
                raise

            def _discard():
                # run_striped cancelled AND awaited every in-flight
                # sibling before raising, so the segment can go away
                # now without an orphan receive landing in a closed
                # mmap.
                _close_segment_owner(owner, buf)
                self.store.release_lease(name)
                self._unlink_segment(name)

            try:
                offsets = deque(range(0, total, chunk))
                fetchers = await self._pull_fetchers(
                    oid, found, chunk, total, buf)
                if offsets:
                    await data_channel.run_striped(offsets, fetchers)
            except asyncio.CancelledError:
                # cancellation must UNWIND (a swallowed cancel would
                # roll into the location-refresh round and restart the
                # whole transfer on a cancelled task)
                _discard()
                raise
            except ConnectionError:
                _discard()
                return None
            _close_segment_owner(owner, buf)
            self.store.release_lease(name)  # sealed by the caller next
            wall = time.monotonic() - t_pull
            self._pull_rates.append(total / max(wall, 1e-9) / 1e9)
            data_channel.observe_pull(total, wall)
            if self.task_events.enabled:
                # timeline record: the pull interval on the wall clock
                # (ts = start), merged by ray_tpu.state.timeline() with
                # task states and tracing spans
                self.task_events.record(
                    b"", TRANSFER,
                    {"object_id": oid.hex(), "bytes": total,
                     "dur": wall, "node": self._nid12,
                     "sources": len(found)},
                    ts=time.time() - wall)
            if self.object_events.enabled:
                # object-plane twin of the TRANSFER record: this node
                # pulled a replica in (the seal that follows stamps
                # SEALED; PULLED carries the transfer shape)
                self.object_events.record(
                    oid.binary(), PULLED,
                    {"bytes": total, "dur": wall, "node": self._nid12,
                     "sources": len(found)},
                    ts=time.time() - wall)
            return name, total
        finally:
            self._pull_inflight_bytes -= total
            self._notify_pull_done()

    # ---------------------------------------------- shard collectives

    async def handle_gather_shards(self, conn, header, bufs):
        """Build ONE destination shard locally by scatter-gathering byte
        runs out of source shards cluster-wide — the collective data
        path behind DistributedArray reshard / all-gather / all-reduce.
        The header carries only the plan (per-source ``runs`` are
        [src_off, dst_off, length] triples relative to each shard's raw
        data frame); the bulk bytes ride the striped data plane with
        ``recv_into`` landing every chunk DIRECTLY in the destination
        segment — zero intermediate copies end to end. Local sources
        are GIL-releasing memcpys in the executor. Shares the pull
        path's admission budget, chunk sizing and discard discipline."""
        from ray_tpu._private.distributed_array import frame_plan
        from ray_tpu._private.shm_store import (
            RECYCLE_MIN_BYTES, _U32, _close_segment_owner, acquire_segment)

        req = protocol.GatherShardsRequest.from_header(header)
        oid = ObjectID(req.object_id)
        if self.store.contains(oid):
            segment = self.store.lookup(oid)
            if segment is not None:  # idempotent retry: already built
                self.store.mark_exposed(oid)
                return {"ok": True, "segment": segment,
                        "node_id": self.node_id.binary()}
        meta = req.meta
        payload = req.payload
        data_nbytes = int(req.data_nbytes)
        sources = req.sources
        # destination layout from sizes alone: [payload frame, data
        # frame], byte-identical to what plan_segment would produce
        hdr, offsets, total = frame_plan(
            meta, [len(payload), data_nbytes])
        me = self.node_id.binary()
        n_remote = len({s["node_id"] for s in sources
                        if s["node_id"] != me})
        chunk = self.config.reshard_chunk_bytes or \
            self._pull_chunk_size(data_nbytes, max(1, n_remote))
        await self._admit_pull(total, chunk)
        t0 = time.monotonic()
        try:
            alloc = self.store.take_recycled(total) \
                if total >= RECYCLE_MIN_BYTES else None
            loop = asyncio.get_running_loop()
            # shielded for the same reason as _pull_chunked: the
            # mapping thread survives the cancel, so its result must
            # be reaped, not dropped
            fut = loop.run_in_executor(
                None, acquire_segment, alloc, max(total, 1))
            try:
                name, owner, buf = await asyncio.shield(fut)
            except asyncio.CancelledError:
                fut.add_done_callback(self._segment_reaper(alloc))
                raise

            def _discard():
                _close_segment_owner(owner, buf)
                self.store.release_lease(name)
                self._unlink_segment(name)

            try:
                buf[0:4] = _U32.pack(len(hdr))
                buf[4:4 + len(hdr)] = hdr
                buf[offsets[0]:offsets[0] + len(payload)] = payload
                reduce_spec = req.get("reduce")
                if reduce_spec:
                    moved = await self._gather_reduce(
                        buf, offsets[1], data_nbytes, chunk, sources,
                        reduce_spec)
                else:
                    moved = await self._gather_runs(
                        buf, offsets[1], chunk, sources)
            except asyncio.CancelledError:
                # every gather job was cancelled AND awaited before the
                # re-raise reached here (see _gather_runs), so no
                # orphan receive can land in the unlinked mapping
                _discard()
                raise
            except (ConnectionError, OSError, ValueError) as e:
                # typed failure back to the driver: it falls back to
                # the naive get+assemble+put path (fallback matrix)
                _discard()
                return {"ok": False, "reason": str(e)}
            _close_segment_owner(owner, buf)
            self.store.release_lease(name)
            if not self.store.seal(oid, name, total,
                                   attrs=req.get("shard")):
                return {"ok": False,
                        "reason": "local store refused seal (capacity)"}
            if req.get("owner_address"):
                # leak-detector owner index, same as the seal/pull paths
                self._object_owners[oid.binary()] = \
                    req.owner_address
            self.store.mark_exposed(oid)  # a sibling gather may read it
            _spmd_metrics()["reshard_bytes"].inc(moved)
            if reduce_spec:
                # the fold twin of the ring path's per-step counter:
                # the two labels together make the bandwidth claim
                # assertable from telemetry alone
                _spmd_metrics()["collective_bytes"].inc(
                    moved, {"algo": "fold"})
                self._recent_collectives.append({
                    "collective": oid.hex()[:12], "rank": 0,
                    "algo": "fold", "op": reduce_spec.get("op", "sum"),
                    "wire_bytes": moved, "steps": len(sources),
                    "folds": max(0, len(sources) - 1), "ok": True})
            wall = time.monotonic() - t0
            if self.object_events.enabled:
                self.object_events.record(
                    oid.binary(), PULLED,
                    {"bytes": moved, "dur": wall, "node": self._nid12,
                     "sources": len(sources), "gather": True},
                    ts=time.time() - wall)
            return {"ok": True, "segment": name,
                    "node_id": self.node_id.binary()}
        finally:
            self._pull_inflight_bytes -= total
            self._notify_pull_done()

    async def _gather_runs(self, buf, data_off: int, chunk: int,
                           sources: List[dict]) -> int:
        """Execute a gather plan into ``buf``: per-source byte runs
        rebased to segment-absolute on the source side (``data_offset +
        src_off``) and destination-buffer-absolute on ours (``data_off
        + dst_off``). Remote nodes stream concurrently over every
        stripe of their data channel (or the legacy control lane);
        failure unwinds with every sibling job cancelled AND awaited,
        so the caller may unlink the destination mapping immediately.
        Returns total bytes moved."""
        from collections import deque

        from ray_tpu._private import data_channel, native

        me = self.node_id.binary()
        local: List[dict] = []
        by_node: Dict[bytes, List[dict]] = {}
        moved = 0
        for src in sources:
            for run in src["runs"]:
                moved += run[2]
            if src["node_id"] == me:
                local.append(src)
            else:
                by_node.setdefault(src["node_id"], []).append(src)
        loop = asyncio.get_running_loop()

        async def _local_job():
            for src in local:
                s_oid = ObjectID(src["oid"])
                segment = self.store.lookup(s_oid)
                if segment is None:
                    raise ConnectionError(
                        f"local shard {s_oid.hex()[:12]} vanished")
                # the gather reads this segment via a foreign-style
                # mapping: it must never enter the recycle pool mid-copy
                self.store.mark_exposed(s_oid)
                shm = await self._attach_serve_segment(segment)
                base = src["data_offset"]

                def _copy(runs=src["runs"], base=base, sbuf=shm.buf):
                    for s, d, ln in runs:
                        native.copy_into(buf, data_off + d,
                                         sbuf[base + s:base + s + ln])
                # one executor batch per source: GIL-releasing memcpys
                # off the raylet loop
                await loop.run_in_executor(None, _copy)

        async def _remote_job(nid: bytes, srcs: List[dict]):
            info = await self._lookup_node(nid)
            if info is None:
                raise ConnectionError(
                    f"shard holder node {nid.hex()[:12]} unknown")
            peer = await self._peer_conn(info["address"])
            work: deque = deque()
            data_address = ""
            for src in srcs:
                # the meta probe pins the source segment serve-side
                # (mark_exposed) and yields the bulk endpoint
                reply, _ = await peer.call(
                    "FetchObjectMeta", protocol.FetchObjectMetaRequest(
                        object_id=src["oid"]).to_header())
                if not reply.get("found"):
                    raise ConnectionError(
                        "source shard "
                        f"{src['oid'].hex()[:12]} not found on holder")
                data_address = reply.get("data_address") or \
                    info.get("data_address", "")
                base = src["data_offset"]
                for s, d, ln in src["runs"]:
                    off = 0
                    while off < ln:
                        n = min(chunk, ln - off)
                        work.append((src["oid"], base + s + off,
                                     data_off + d + off, n))
                        off += n
            channel = None
            if data_address and self.config.data_plane_stripes > 0:
                try:
                    channel = await self._data_channel(data_address)
                except ConnectionError:
                    channel = None  # data port dead; control conn lives
            fetchers = []
            if channel is not None:
                for stripe in channel.stripes:
                    async def _fetch(item, _s=stripe, _ch=channel):
                        ob, s_abs, d_abs, n = item
                        await _ch.fetch_chunk(_s, ob, s_abs, n,
                                              buf, d_abs)
                    fetchers.append(_fetch)
            else:
                async def _legacy(item, _conn=peer):
                    ob, s_abs, d_abs, n = item
                    floor = self.config.object_manager_chunk_size
                    sub = 0
                    while sub < n:
                        want = min(floor, n - sub)
                        r, bufs2 = await _conn.call(
                            "FetchObjectChunk", {
                                "object_id": ob, "offset": s_abs + sub,
                                "length": want})
                        if not r.get("found") or len(bufs2[0]) != want:
                            raise ConnectionError(
                                "short/missing chunk from shard holder")
                        native.copy_into(buf, d_abs + sub, bufs2[0])
                        data_channel.note_control_chunk(want)
                        sub += want
                fetchers.extend([_legacy] * 8)
            if work:
                await data_channel.run_striped(work, fetchers)

        jobs = []
        if local:
            jobs.append(loop.create_task(_local_job()))
        jobs.extend(loop.create_task(_remote_job(nid, srcs))
                    for nid, srcs in by_node.items())
        try:
            await asyncio.gather(*jobs)
        except BaseException:
            # cancel-and-AWAIT every sibling before unwinding: the
            # caller unlinks the destination mapping right after, and
            # an orphan recv_into must not land in a closed mmap
            for j in jobs:
                j.cancel()
            await asyncio.gather(*jobs, return_exceptions=True)
            raise
        return moved

    async def _gather_reduce(self, buf, data_off: int, data_nbytes: int,
                             chunk: int, sources: List[dict],
                             reduce_spec: dict) -> int:
        """All-reduce destination build, fold algorithm: the first
        source streams straight into the destination data frame; each
        further source streams into ONE reused scratch buffer and is
        folded in by the GIL-releasing ``native.reduce_into`` kernel in
        an executor — peak extra memory is one shard regardless of
        fan-in. The ring path (handle_ring_*) supersedes this for
        P >= 3; this stays as the ``collective_algorithm="fold"`` /
        2-rank / ring-failure fallback."""
        import numpy as np

        from ray_tpu._private import native

        op = reduce_spec.get("op", "sum")
        if op not in ("sum", "min", "max"):
            raise ValueError(f"unsupported reduce op: {op!r}")
        dtype = np.dtype(reduce_spec["dtype"])
        count = data_nbytes // dtype.itemsize

        def _fold(sbuf):
            # reduce_into's buffer exports live only inside this
            # executor call — an array view passed through (or returned
            # from) run_in_executor lingers in the work-item/future
            # plumbing and makes the caller's _close_segment_owner
            # fail with BufferError
            native.reduce_into(buf, data_off, sbuf, dtype, op)

        moved = await self._gather_runs(buf, data_off, chunk,
                                        sources[:1])
        if len(sources) > 1:
            scratch = np.empty(count, dtype=dtype)
            sbuf = memoryview(scratch).cast("B")
            loop = asyncio.get_running_loop()
            for src in sources[1:]:
                moved += await self._gather_runs(sbuf, 0, chunk, [src])
                await loop.run_in_executor(None, _fold, sbuf)
        return moved

    # ------------------------------------------------ ring collectives
    #
    # Bandwidth-optimal ring reduce-scatter + all-gather over the
    # striped data plane (plan math: distributed_array.ring_segments /
    # ring_reduce_schedule). The DRIVER orchestrates: one RingInit per
    # member, then one RingStep RPC per (member, schedule step) with a
    # barrier between rounds — so a step only ever reads peer segment
    # bytes its peer finished in the previous round — then RingFinish
    # seals every accumulator as the same result object. Per-rank wire
    # traffic: 2*(P-1)/P * N bytes (vs the fold path's (P-1)*N).
    #
    # A member's accumulator segment is store-LEASED (never sealed)
    # while the collective runs; ring peers read it mid-collective via
    # the data server's extra_entries side table, keyed by the 28-byte
    # member id. Admission: RingInit deliberately does NOT take the
    # pull-admission budget for the whole accumulator — P members of
    # one collective may share a node (single-driver runs), and the
    # driver's round barrier would deadlock against a held budget;
    # capacity is enforced at RingFinish's seal instead. Each RingStep
    # admits only its own segment's bytes (steps within a round are
    # mutually independent, so they serialize at worst, never
    # deadlock).

    def _discard_ring_member(self, member_id: bytes, rec: dict,
                             reason: str = "") -> None:
        """Release everything a ring member holds: the data-server
        serve entry, the segment mapping, the store lease and the
        /dev/shm file. Idempotent per member (callers pop the record
        first)."""
        from ray_tpu._private.shm_store import _close_segment_owner
        if self.data_server is not None:
            self.data_server.extra_entries.pop(member_id, None)
        try:
            _close_segment_owner(rec["owner"], rec["buf"])
        except BufferError:
            pass  # a straggling serve view closes with its unpin
        self.store.release_lease(rec["name"])
        self._unlink_segment(rec["name"])
        if reason:
            self._recent_collectives.append({
                "collective": rec["collective_id"].hex()[:12],
                "rank": rec["rank"], "algo": "ring", "op": rec["op"],
                "wire_bytes": rec["wire_bytes"], "steps": rec["steps"],
                "folds": rec["folds"], "ok": False, "reason": reason})

    def _sweep_ring_members(self) -> None:
        """Opportunistic TTL sweep (rides RingInit, no periodic task):
        discard members whose driver stopped stepping them — a crashed
        driver cannot send RingAbort, and a leaked lease would pin
        store capacity forever."""
        ttl = self.config.collective_member_ttl_s
        if ttl <= 0 or not self._ring_members:
            return
        now = time.monotonic()
        for mid, rec in list(self._ring_members.items()):
            if now - rec["touched"] > ttl:
                self._ring_members.pop(mid, None)
                self._discard_ring_member(mid, rec, reason="ttl expired")

    async def handle_ring_init(self, conn, header, bufs):
        """Create one ring member: lease + lay out the accumulator
        segment (same frame math as GatherShards), stream this rank's
        OWN source shard into it, and publish it to ring peers through
        the data server's side table. Replies with this node's data
        address so the driver can point the member's neighbours at it."""
        from ray_tpu._private.distributed_array import frame_plan
        from ray_tpu._private.shm_store import (
            RECYCLE_MIN_BYTES, _U32, _close_segment_owner, acquire_segment)

        self._sweep_ring_members()
        req = protocol.RingInitRequest.from_header(header)
        member_id = req.member_id
        rec = self._ring_members.get(member_id)
        if rec is not None:  # idempotent retry: member already built
            rec["touched"] = time.monotonic()
            return {"ok": True, "data_address": self.data_address,
                    "node_id": self.node_id.binary()}
        meta = req.meta
        payload = req.payload
        data_nbytes = int(req.data_nbytes)
        source = req.source
        hdr, offsets, total = frame_plan(
            meta, [len(payload), data_nbytes])
        chunk = self.config.reshard_chunk_bytes or \
            self._pull_chunk_size(data_nbytes, 1)
        alloc = self.store.take_recycled(total) \
            if total >= RECYCLE_MIN_BYTES else None
        loop = asyncio.get_running_loop()
        # shielded like the gather path: the mapping thread survives a
        # cancel, so its result must be reaped, not dropped
        fut = loop.run_in_executor(None, acquire_segment, alloc,
                                   max(total, 1))
        try:
            name, owner, buf = await asyncio.shield(fut)
        except asyncio.CancelledError:
            fut.add_done_callback(self._segment_reaper(alloc))
            raise

        def _discard():
            _close_segment_owner(owner, buf)
            self.store.release_lease(name)
            self._unlink_segment(name)

        try:
            buf[0:4] = _U32.pack(len(hdr))
            buf[4:4 + len(hdr)] = hdr
            buf[offsets[0]:offsets[0] + len(payload)] = payload
            await self._gather_runs(buf, offsets[1], chunk, [source])
        except asyncio.CancelledError:
            _discard()
            raise
        except (ConnectionError, OSError, ValueError) as e:
            _discard()
            return {"ok": False, "reason": str(e)}
        now = time.monotonic()
        self._ring_members[member_id] = {
            "collective_id": req.collective_id,
            "rank": int(req.rank),
            "nranks": int(req.nranks),
            "name": name, "owner": owner, "buf": buf,
            "total": total, "data_off": offsets[1],
            "data_nbytes": data_nbytes,
            "dtype": req.dtype, "op": req.get("op"),
            "oid": req.object_id,
            "owner_address": req.get("owner_address") or "",
            "shard": req.get("shard"),
            "chunk": chunk, "scratch": None,
            "wire_bytes": 0, "steps": 0, "folds": 0,
            "created": now, "touched": now,
        }
        if self.data_server is not None:
            self.data_server.extra_entries[member_id] = (name, total)
        return {"ok": True, "data_address": self.data_address,
                "node_id": self.node_id.binary()}

    async def handle_ring_step(self, conn, header, bufs):
        """Execute ONE ring step for one member: pull the named segment
        from the ring predecessor over the striped data plane and
        either fold it into the accumulator (reduce-scatter phase,
        pipelined through double-buffered scratch windows) or land it
        verbatim in the destination frame (all-gather phase — chunks
        recv_into the segment directly, zero intermediate copies).
        Layouts are identical on every rank, so the peer's absolute
        segment offsets equal this member's own."""
        from collections import deque

        from ray_tpu._private import data_channel

        req = protocol.RingStepRequest.from_header(header)
        rec = self._ring_members.get(req.member_id)
        if rec is None:
            return {"ok": False, "reason": "unknown ring member"}
        rec["touched"] = time.monotonic()
        seg_off = int(req.seg_off)
        seg_len = int(req.seg_len)
        step = int(req.get("step") or 0)
        if seg_len <= 0:  # P > element count: empty segment, no wire
            rec["steps"] += 1
            return {"ok": True}
        if seg_off < 0 or seg_off + seg_len > rec["data_nbytes"]:
            return {"ok": False,
                    "reason": f"ring segment out of bounds at step "
                              f"{step}"}
        peer_key = req.peer_member_id
        peer_addr = req.peer_data_address
        abs_off = rec["data_off"] + seg_off
        chunk = min(rec["chunk"], seg_len)
        await self._admit_pull(seg_len, chunk)
        try:
            try:
                channel = await self._data_channel(peer_addr)
                if req.get("reduce"):
                    rec["folds"] += await self._ring_reduce_fold(
                        rec, channel, peer_key, abs_off, seg_len, chunk)
                else:
                    buf = rec["buf"]
                    work: deque = deque()
                    off = 0
                    while off < seg_len:
                        n = min(chunk, seg_len - off)
                        work.append((abs_off + off, n))
                        off += n
                    fetchers = []
                    for stripe in channel.stripes:
                        async def _fetch(item, _s=stripe, _ch=channel):
                            o, n = item
                            await _ch.fetch_chunk(_s, peer_key, o, n,
                                                  buf, o)
                        fetchers.append(_fetch)
                    await data_channel.run_striped(work, fetchers)
            except asyncio.CancelledError:
                raise
            except (ConnectionError, OSError, ValueError) as e:
                # typed failure to the driver: it RingAborts every
                # member and falls back (fold, then naive)
                return {"ok": False, "reason": str(e)}
            rec["wire_bytes"] += seg_len
            rec["steps"] += 1
            _spmd_metrics()["collective_bytes"].inc(
                seg_len, {"algo": "ring"})
            return {"ok": True}
        finally:
            self._pull_inflight_bytes -= seg_len
            self._notify_pull_done()

    async def _ring_reduce_fold(self, rec: dict, channel, peer_key: bytes,
                                abs_off: int, seg_len: int,
                                chunk: int) -> int:
        """Pipelined recv+reduce for one reduce-scatter step: the
        segment streams through two scratch windows so window k folds
        (GIL-releasing ``native.reduce_into`` in an executor) while
        window k+1 is on the wire. Window reuse is safe by
        construction: the fetch into a window starts only after the
        previous fold FROM that window was awaited. Returns the number
        of window folds executed."""
        from collections import deque

        from ray_tpu._private import data_channel, native

        win = min(max(self.config.collective_scratch_bytes, chunk),
                  seg_len)
        scratch = rec.get("scratch")
        if scratch is None or len(scratch[0]) < win:
            scratch = rec["scratch"] = [bytearray(win), bytearray(win)]
        loop = asyncio.get_running_loop()
        nwin = -(-seg_len // win)
        dtype, op = rec["dtype"], rec["op"] or "sum"
        buf = rec["buf"]

        async def _fetch_window(w_idx: int, sbuf) -> int:
            w_off = w_idx * win
            w_len = min(win, seg_len - w_off)
            work: deque = deque()
            off = 0
            while off < w_len:
                n = min(chunk, w_len - off)
                work.append((w_off + off, n))
                off += n
            fetchers = []
            for stripe in channel.stripes:
                async def _fetch(item, _s=stripe, _ch=channel,
                                 _w=w_off):
                    o, n = item
                    await _ch.fetch_chunk(_s, peer_key, abs_off + o, n,
                                          sbuf, o - _w)
                fetchers.append(_fetch)
            await data_channel.run_striped(work, fetchers)
            return w_len

        folds = 0
        fold_fut: List[Any] = [None, None]
        fetch_task = loop.create_task(_fetch_window(0, scratch[0]))
        try:
            for k in range(nwin):
                w_len = await fetch_task
                if k + 1 < nwin:
                    nb = (k + 1) % 2
                    if fold_fut[nb] is not None:
                        # the window we are about to overwrite must be
                        # done folding before new bytes land in it
                        await fold_fut[nb]
                        fold_fut[nb] = None
                    fetch_task = loop.create_task(
                        _fetch_window(k + 1, scratch[nb]))

                def _fold(_sbuf=scratch[k % 2], _off=abs_off + k * win,
                          _n=w_len):
                    # views live only inside the executor call (the
                    # same BufferError discipline as _gather_reduce)
                    native.reduce_into(buf, _off,
                                       memoryview(_sbuf)[:_n],
                                       dtype, op)
                fold_fut[k % 2] = loop.run_in_executor(None, _fold)
                folds += 1
            for f in fold_fut:
                if f is not None:
                    await f
        except BaseException:
            # cancel-and-AWAIT before unwinding: an orphan recv/fold
            # must not land in buffers the abort path is about to
            # close (run_striped already awaits its own workers)
            fetch_task.cancel()
            await asyncio.gather(
                fetch_task, *(f for f in fold_fut if f is not None),
                return_exceptions=True)
            raise
        return folds

    async def handle_ring_finish(self, conn, header, bufs):
        """Seal one member's accumulator as the collective's result
        object and return its per-rank telemetry (wire bytes / steps /
        folds — the bench's bandwidth bound asserts on these)."""
        member_id = protocol.RingFinishRequest.from_header(header).member_id
        rec = self._ring_members.pop(member_id, None)
        if rec is None:
            return {"ok": False, "reason": "unknown ring member"}
        from ray_tpu._private.shm_store import _close_segment_owner
        if self.data_server is not None:
            self.data_server.extra_entries.pop(member_id, None)
        oid = ObjectID(rec["oid"])
        _close_segment_owner(rec["owner"], rec["buf"])
        self.store.release_lease(rec["name"])
        if not self.store.seal(oid, rec["name"], rec["total"],
                               attrs=rec["shard"]):
            self._unlink_segment(rec["name"])
            return {"ok": False,
                    "reason": "local store refused seal (capacity)"}
        if rec["owner_address"]:
            self._object_owners[oid.binary()] = rec["owner_address"]
        self.store.mark_exposed(oid)  # ring peers/gathers may read it
        self.num_ring_collectives += 1
        self._recent_collectives.append({
            "collective": rec["collective_id"].hex()[:12],
            "rank": rec["rank"], "algo": "ring", "op": rec["op"],
            "wire_bytes": rec["wire_bytes"], "steps": rec["steps"],
            "folds": rec["folds"], "ok": True})
        wall = time.monotonic() - rec["created"]
        if self.object_events.enabled:
            self.object_events.record(
                oid.binary(), PULLED,
                {"bytes": rec["wire_bytes"], "dur": wall,
                 "node": self._nid12, "sources": rec["nranks"],
                 "ring": True},
                ts=time.time() - wall)
        return {"ok": True, "node_id": self.node_id.binary(),
                "wire_bytes": rec["wire_bytes"], "steps": rec["steps"],
                "folds": rec["folds"]}

    async def handle_ring_abort(self, conn, header, bufs):
        """Tear one member down without sealing (driver-side failure
        fan-out, or cleanup after a peer died mid-collective).
        Idempotent: aborting an unknown/already-finished member is ok."""
        req = protocol.RingAbortRequest.from_header(header)
        rec = self._ring_members.pop(req.member_id, None)
        if rec is not None:
            self._discard_ring_member(
                req.member_id, rec,
                reason=req.get("reason") or "aborted")
        return {"ok": True}

    @staticmethod
    def _unlink_segment(name: str):
        from multiprocessing import shared_memory
        try:
            shm = shared_memory.SharedMemory(name=name)
            shm.close()
            shm.unlink()
        except OSError:
            pass  # segment already unlinked

    async def _peer_conn(self, address: str) -> rpc.Connection:
        conn = self._peer_raylets.get(address)
        if conn is None or conn.closed:
            conn = await rpc.connect(
                address, peer_name=f"raylet@{address}",
                timeout=self.config.rpc_connect_timeout_s)
            self._peer_raylets[address] = conn
        return conn

    async def _owner_conn(self, address: str) -> rpc.Connection:
        conn = self._owner_conns.get(address)
        if conn is None or conn.closed:
            conn = await rpc.connect(address, peer_name=f"owner@{address}")
            self._owner_conns[address] = conn
        return conn

    # ----------------------------------------------------- placement groups

    async def handle_prepare_pg_bundle(self, conn, header, bufs):
        key = (header["pg_id"], header["bundle_index"])
        resources = header["resources"]
        if not all(self.resources_available.get(k, 0.0) + 1e-9 >= v
                   for k, v in resources.items() if v > 0):
            return {"ok": False, "reason": "insufficient resources"}
        for k, v in resources.items():
            self.resources_available[k] = self.resources_available.get(k, 0.0) - v
        self._pg_prepared[key] = dict(resources)
        return {"ok": True}

    async def handle_commit_pg_bundle(self, conn, header, bufs):
        key = (header["pg_id"], header["bundle_index"])
        if key not in self._pg_prepared:
            return {"ok": False}
        self._pg_committed.add(key)
        self._pg_available[key] = dict(self._pg_prepared[key])
        return {"ok": True}

    async def handle_return_pg_bundle(self, conn, header, bufs):
        key = (header["pg_id"], header["bundle_index"])
        resources = self._pg_prepared.pop(key, None)
        self._pg_committed.discard(key)
        self._pg_available.pop(key, None)
        if resources:
            for k, v in resources.items():
                self.resources_available[k] = \
                    self.resources_available.get(k, 0.0) + v
        self._schedule_tick()
        return {"ok": True}

    # -------------------------------------------------------------- stats

    def _note_latency(self, req) -> None:
        now = time.monotonic()
        arrival = getattr(req, "arrival_ts", 0.0)
        if arrival:
            self._sched_latencies.append(now - arrival)
            first = getattr(req, "first_decision_ts", 0.0)
            if first:
                self._decision_latencies.append(first - arrival)
                self._grant_waits.append(now - first)

    @staticmethod
    def _pct_block(samples) -> dict:
        from ray_tpu._private.metrics import percentile

        lat = sorted(samples)
        if not lat:
            return {"count": 0}
        return {
            "count": len(lat),
            "p50_ms": round(percentile(lat, 0.50) * 1e3, 3),
            "p90_ms": round(percentile(lat, 0.90) * 1e3, 3),
            "p99_ms": round(percentile(lat, 0.99) * 1e3, 3),
            "max_ms": round(lat[-1] * 1e3, 3),
        }

    @staticmethod
    def _rate_block(samples) -> dict:
        """Percentile summary of a rate reservoir (units preserved —
        unlike _pct_block there is no seconds->ms scaling). Guards the
        empty case: metrics.percentile raises on empty input."""
        from ray_tpu._private.metrics import percentile

        rates = sorted(samples)
        if not rates:
            return {"count": 0}
        return {"count": len(rates),
                "p50": round(percentile(rates, 0.50), 3),
                "p90": round(percentile(rates, 0.90), 3),
                "max": round(rates[-1], 3)}

    def _latency_percentiles(self) -> dict:
        from ray_tpu._private.metrics import percentile

        out = self._pct_block(self._sched_latencies)
        # grant-population split: streamed credit grants vs legacy
        # request/grant round-trips (both feed the reservoirs above, so
        # the percentiles reflect the whole grant population — not just
        # the handful of legacy requests a credit-served drain makes)
        out["credit_grants"] = self.num_credit_grants
        out["legacy_grants"] = self.num_leases_granted
        if not out["count"]:
            return out
        # arrival->first-decision (kernel responsiveness) vs
        # first-decision->grant (resource/queue wait): reported apart so
        # a saturated node's backlog can't mask kernel regressions.
        out["decision"] = self._pct_block(self._decision_latencies)
        out["grant_wait"] = self._pct_block(self._grant_waits)
        ticks = list(self._tick_durations)
        if ticks:
            durs = sorted(t for _, t in ticks)
            out["tick"] = {
                "count": len(ticks),
                "p50_ms": round(percentile(durs, 0.50) * 1e3, 3),
                "p99_ms": round(percentile(durs, 0.99) * 1e3, 3),
                "max_queue": max(n for n, _ in ticks),
                "max_ms": round(durs[-1] * 1e3, 3),
            }
        return out

    async def handle_set_resource(self, conn, header, bufs):
        """Dynamic custom resources (reference:
        experimental/dynamic_resources.py set_resource → raylet-side
        capacity update): adjust total AND available by the same delta
        so in-flight leases keep their accounting; capacity 0 deletes.
        The next tick dispatches anything the new capacity unblocks."""
        name = header["name"]
        capacity = float(header["capacity"])
        if name == "CPU":
            return {"ok": False, "reason": "CPU capacity is fixed"}
        old_total = self.resources_total.get(name, 0.0)
        delta = capacity - old_total
        new_avail = self.resources_available.get(name, 0.0) + delta
        if capacity <= 0.0:
            self.resources_total.pop(name, None)
            # available moves by the SAME delta (possibly negative:
            # in-flight leases still owe their release), so a later
            # re-create can never oversubscribe
            if new_avail == 0.0:
                self.resources_available.pop(name, None)
            else:
                self.resources_available[name] = new_avail
        else:
            self.resources_total[name] = capacity
            self.resources_available[name] = new_avail
        self._schedule_tick()
        return {"ok": True, "total": self.resources_total.get(name, 0.0)}

    async def handle_dump_worker_stacks(self, conn, header, bufs):
        """Aggregate all-thread stack dumps from every live worker on
        this node (reference: `ray stack`, scripts.py:1393 — py-spy
        over local pids; here each worker self-reports over RPC)."""
        out = []
        for w in list(self.workers.values()):
            if w.conn is None or w.conn.closed or w.state == WORKER_DEAD:
                continue
            try:
                reply, _ = await w.conn.call("DumpStack", {}, timeout=5.0)
                reply["worker_id"] = w.worker_id.hex() \
                    if isinstance(w.worker_id, bytes) else w.worker_id
                out.append(reply)
            except (ConnectionError, asyncio.TimeoutError):
                out.append({"pid": w.pid, "error": "unreachable"})
        return {"node_id": self.node_id.binary(), "workers": out}

    async def handle_get_logs(self, conn, header, bufs):
        """List / tail this node's session log files (reference:
        dashboard log module, dashboard/modules/log — per-node file
        serving; here the raylet serves its own session dir)."""
        log_dir = os.path.join(self.session_dir, "logs")
        name = header.get("name") or ""
        try:
            tail = int(header.get("tail") or 200)
        except (TypeError, ValueError):
            tail = 200
        try:
            files = sorted(os.listdir(log_dir))
        except OSError:
            files = []
        if not name:
            out = []
            for fname in files:
                try:
                    out.append({"name": fname, "size": os.path.getsize(
                        os.path.join(log_dir, fname))})
                except OSError:
                    continue
            return {"files": out}
        matches = [f for f in files if name in f]
        if not matches:
            return {"error": f"no log file matching {name!r}",
                    "files": [{"name": f} for f in files]}
        path = os.path.join(log_dir, matches[0])
        try:
            data = await asyncio.get_running_loop().run_in_executor(
                None, _read_file_tail, path, 256 * 1024)
            lines = data.decode("utf-8", errors="replace") \
                .splitlines()[-tail:]
        except OSError as e:
            return {"error": str(e)}
        return {"name": matches[0], "lines": lines}

    # ----------------------------------------------------- leak detector

    def _free_local_object(self, oid: ObjectID) -> None:
        """Free a store-held object AND release this raylet's serving
        state for its segment (cached serve attachment, data-plane
        source) — a free that skips the attachment close leaves the
        unlinked segment's pages pinned by the open mmap."""
        entry = self.store._objects.get(oid)  # noqa: SLF001
        if entry is not None:
            att = self._serve_attachments.pop(entry[0], None)
            if att is not None:
                try:
                    att.close()
                except BufferError:
                    pass
            if self.data_server is not None:
                self.data_server.drop_source(entry[0])
        self.store.free(oid)

    def _drop_object_bookkeeping(self, oid: ObjectID) -> None:
        """An object legitimately left this store (FreeObject, owner
        release): forget its owner entry and any leak verdict — a
        late-but-arrived free is a recovery, and the leaked gauge must
        drop with it."""
        k = oid.binary()
        self._object_owners.pop(k, None)
        self._leak_suspects.pop(k, None)
        self._leaked.discard(k)

    def _maybe_start_leak_sweep(self) -> None:
        """Interval gate + single-flight spawn for the leak sweep: the
        heartbeat loop calls this every beat; an actual sweep runs as
        its own task so slow/dead-owner probes never delay a beat. A
        sweep still in flight (wedged owner) is simply not doubled."""
        interval = self.config.leak_sweep_interval_s
        if interval <= 0 or self._closing:
            return
        now = time.monotonic()
        if now - self._last_leak_sweep < interval:
            return
        if self._leak_sweep_task is not None and \
                not self._leak_sweep_task.done():
            return
        self._last_leak_sweep = now
        self.leak_sweeps += 1
        self._leak_sweep_task = asyncio.get_running_loop().create_task(
            self._leak_sweep())

    async def _leak_sweep(self) -> None:
        """Cross-check store-held segments against live owner
        references (reference intent: the plasma store's unreferenced-
        object accounting, surfaced as `ray memory`'s LOST_OBJECT
        class; here it is an active probe because the owner table IS
        the ground truth in ownership-based memory management).

        Cadence: ``leak_sweep_interval_s`` (0 disables), spawned off
        the heartbeat loop by _maybe_start_leak_sweep. Verdict
        protocol: an object older than one interval whose owner says
        ``live=False`` (or whose owner is GONE — dial refused/timed
        out) accumulates one dead vote per sweep — the SECOND vote
        flags it LEAKED (objects_leaked gauge, leaked=True in
        list_objects(), a LEAKED event), the THIRD reclaims it
        (store.free -> FREED + LEAK_RECLAIMED, gauge back to 0). A
        live verdict at any point clears the votes and retracts an
        already-raised flag (LEAK_CLEARED). Owners that cannot
        be judged (probe unsupported, or a CONNECTED owner whose call
        times out — a GIL-stalled driver must never be judged dead)
        are skipped — never a verdict.
        """
        interval = self.config.leak_sweep_interval_s
        try:
            await self._leak_sweep_inner(interval)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — missed sweep < broken raylet
            logger.exception("object leak sweep failed")

    async def _leak_sweep_inner(self, interval: float) -> None:
        cutoff = time.time() - interval
        held: Set[bytes] = set()
        by_owner: Dict[str, List[ObjectID]] = {}
        for oid, sealed_ts in self.store.held_objects():
            held.add(oid.binary())
            if sealed_ts > cutoff:
                continue  # too young to judge (seal/free may be racing)
            owner = self._object_owners.get(oid.binary())
            if owner:
                by_owner.setdefault(owner, []).append(oid)
        # prune bookkeeping for objects that left the store sideways
        # (eviction, watchdog relief) so the index can't grow unbounded
        for k in [k for k in self._object_owners if k not in held]:
            self._object_owners.pop(k, None)
            self._leak_suspects.pop(k, None)
            self._leaked.discard(k)
        for owner, oids in by_owner.items():
            if self._closing:
                return
            try:
                # wait_for caps the dial: rpc.connect retries a refused
                # socket for its full 10s budget, and a dead owner must
                # cost this background sweep seconds, not the default
                # timeout per owner per sweep
                conn = await asyncio.wait_for(
                    self._owner_conn(owner), timeout=5.0)
            except (ConnectionError, asyncio.TimeoutError):
                # owner GONE (SIGKILLed driver — refused dial, or a
                # black-holed endpoint): every object it owned here
                # gets a dead vote. Reclaim still needs the multi-sweep
                # confirmation, so a restarting owner's transient
                # outage never costs data by itself.
                for o in oids:
                    self._judge_object(o, False, owner)
                continue
            try:
                reply, _ = await conn.call(
                    "ProbeObjectLiveness",
                    {"object_ids": [o.binary() for o in oids]},
                    timeout=5.0)
                live = reply.get("live") or []
            except ConnectionError:
                for o in oids:  # conn dropped mid-call: owner gone
                    self._judge_object(o, False, owner)
                continue
            except asyncio.TimeoutError:
                # CONNECTED but slow (a GIL-stalled driver under
                # load): cannot be judged — never a dead vote
                continue
            except Exception:  # noqa: BLE001 — probe-incapable owner: no verdict
                logger.debug("leak probe to %s failed; skipping verdict",
                             owner, exc_info=True)
                continue
            for o, alive in zip(oids, live):
                self._judge_object(o, bool(alive), owner)

    def _judge_object(self, oid: ObjectID, alive: bool,
                      owner: str) -> None:
        k = oid.binary()
        if alive:
            self._leak_suspects.pop(k, None)
            if k in self._leaked:
                self._leaked.discard(k)
                # retract the flag in the GCS table too — without this
                # the record's current state stays LEAKED and
                # summary_objects()["leaked"] reports a phantom leak
                # for as long as the (healthy) owner keeps its reference
                if self.object_events.enabled:
                    self.object_events.record(
                        k, LEAK_CLEARED,
                        {"node": self._nid12, "owner": owner})
            return
        if k not in self._object_owners:
            # a legitimate FreeObject landed while the probe was in
            # flight (_drop_object_bookkeeping cleared the entry): the
            # verdict is stale — re-creating a suspect entry here would
            # leak it forever (nothing prunes keys outside the index)
            return
        votes = self._leak_suspects.get(k, 0) + 1
        self._leak_suspects[k] = votes
        if votes == 2 and k not in self._leaked:
            self._leaked.add(k)
            logger.warning(
                "leak detector: object %s held in store but owner %s "
                "has no reference (lost FreeObject?)", oid.hex()[:16],
                owner)
            if self.object_events.enabled:
                self.object_events.record(
                    k, LEAKED, {"node": self._nid12, "owner": owner})
        elif votes >= 3:
            # flagged a full sweep ago and still dead: reclaim. free()
            # stamps FREED; LEAK_RECLAIMED names the cause.
            self._free_local_object(oid)
            self._drop_object_bookkeeping(oid)
            self.leak_reclaims += 1
            if self.object_events.enabled:
                self.object_events.record(
                    k, LEAK_RECLAIMED,
                    {"node": self._nid12, "owner": owner})
            self.events.emit(
                "WARNING", "OBJECT_LEAK_RECLAIMED",
                f"leak detector reclaimed object {oid.hex()[:16]} "
                f"(owner {owner} held no reference)",
                node=self._nid12, object_id=oid.hex()[:16])

    def object_plane_stats(self) -> dict:
        """Public object-plane snapshot — the chaos invariants assert
        on THIS (lent leases drained, admission budget at zero, nothing
        leaked) instead of peeking private fields."""
        s = self.store.stats()
        return {
            "lent_segments": s["recycle_lent_segments"],
            "pull_inflight_bytes": self._pull_inflight_bytes,
            "leaked": len(self._leaked),
            "leak_suspects": len(self._leak_suspects),
            "leak_reclaims": self.leak_reclaims,
            "leak_sweeps": self.leak_sweeps,
            "owners_tracked": len(self._object_owners),
        }

    async def handle_get_node_stats(self, conn, header, bufs):
        from ray_tpu._private import native
        from ray_tpu._private.data_channel import pull_stats, serve_stats
        from ray_tpu._private.rpc import handler_stats, telemetry
        return {
            "data_plane": {
                "data_address": self.data_address,
                "stripes": self.config.data_plane_stripes,
                "pull": dict(pull_stats),
                "serve": dict(serve_stats),
                "recv_tiers": dict(native.recv_stats),
                "pull_inflight_bytes": self._pull_inflight_bytes,
                # per-pull throughput percentiles (GB/s) from the
                # bounded reservoir; {"count": 0} before any pull
                "pull_throughput_gb_per_s": self._rate_block(
                    self._pull_rates),
            },
            "schedule_latency": self._latency_percentiles(),
            "rpc_handlers": handler_stats.snapshot(),
            # the full flight recorder: per-method server/client
            # reservoir percentiles, queue-vs-exec split, bytes,
            # errors, in-flight — plus THIS raylet loop's lag probe
            "rpc": telemetry.snapshot(probe="raylet"),
            "node_id": self.node_id.binary(),
            "address": self.address,
            "resources_total": self.resources_total,
            "resources_available": self.resources_available,
            "num_workers": self._alive_worker_count(),
            "workers": [{
                "worker_id": w.worker_id, "pid": w.pid, "state": w.state,
                "actor_id": w.actor_id,
                # last watchdog poll's RSS sample (0 before any poll)
                "rss_bytes": self.memory_monitor.workers_rss.get(
                    w.worker_id.hex()[:12], 0),
            } for w in self.workers.values()],
            "num_pending_leases": len(self._pending),
            "num_leases_granted": self.num_leases_granted,
            "num_spillbacks": self.num_spillbacks,
            # streaming-lease window state + credit hit-rate
            "lease_credits": self._credit_stats(),
            # SPMD gang leases: incarnations homed here + member
            # bookings this node holds for gangs homed elsewhere
            "gangs": self._gang_stats(),
            # ring collectives: members currently accumulating on this
            # node + the bounded per-member finish/abort history (wire
            # bytes, steps, folds — the bench asserts its 2*(P-1)/P*N
            # bandwidth bound from these, not from timing)
            "collectives": {
                "active_members": len(self._ring_members),
                "finished": self.num_ring_collectives,
                "recent": list(self._recent_collectives),
            },
            "store": self.store.stats(),
            # per-process writer mapping cache (zero-copy put tier;
            # meaningful where writers share this process, i.e. the
            # in-process head)
            "writer_map_cache": _map_cache_stats(),
            # leak detector + lease/admission truth, public form
            "object_plane": self.object_plane_stats(),
            # watchdog state: per-worker RSS, pressure flag, cumulative
            # kill/backpressure counts + last-64 action history
            "memory_monitor": self.memory_monitor.snapshot(),
        }
