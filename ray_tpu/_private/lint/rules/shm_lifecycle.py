"""shm-lifecycle: every AllocSegment lease ends in seal-or-abort.

The zero-copy put pipeline (PR 1) leases recycled warm segments from
the store (``AllocSegment`` RPC / ``take_recycled()``). A lease that is
neither sealed (``SealObject`` / ``store.seal``) nor aborted
(``AbortSegment`` / ``release_lease`` / ``abort_lease``) parks tmpfs
pages in the store's ``_lent`` table until the 600 s stale-lease sweep
— under put churn that is real memory pressure, and a writer that
errors between lease and seal used to do exactly that.

For every function that ACQUIRES a lease — a literal
``call("AllocSegment", ...)`` or a ``take_recycled(...)`` call — the
rule requires, in the same function:

  * some reference to the seal-or-abort machinery: ``SealObject`` /
    ``AbortSegment`` method strings, or ``seal`` / ``release_lease`` /
    ``abort_lease`` / ``_unlink`` / the ``write_segment*`` pipeline
    (which adopts the lease and whose callers own the seal); AND
  * a ``try`` statement, so the abort half actually covers the error
    exit paths, not just the straight line.

Handing the lease to a remote writer (the raylet's AllocSegment
handler returns it over RPC) is a deliberate transfer of the
obligation — annotate such sites with a pragma naming the new owner.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ray_tpu._private.lint.engine import (
    Module, Rule, Violation, body_nodes, dotted_name, first_str_arg,
    register, walk_functions,
)

_ACQUIRE_STRINGS = {"AllocSegment"}
_ACQUIRE_ATTRS = {"take_recycled"}
_SEAL_STRINGS = {"SealObject", "AbortSegment"}
_SEAL_NAMES = {"seal", "release_lease", "abort_lease", "_unlink",
               "write_segment", "write_segment_sync", "acquire_segment",
               "_acquire_segment_fd"}


@register
class ShmLifecycleRule(Rule):
    name = "shm-lifecycle"
    description = ("AllocSegment/take_recycled lease sites must pair "
                   "with seal-or-abort on all exit paths")

    def collect(self, module: Module) -> Iterable[Violation]:
        out: List[Violation] = []
        for func, qualname, _cls in walk_functions(module.tree):
            acquires = []
            has_seal = False
            has_try = False
            for node in body_nodes(func):
                if isinstance(node, ast.Try):
                    has_try = True
                elif isinstance(node, ast.Constant) and \
                        node.value in _SEAL_STRINGS:
                    has_seal = True
                elif isinstance(node, (ast.Name, ast.Attribute)):
                    terminal = dotted_name(node).rsplit(".", 1)[-1]
                    if terminal in _SEAL_NAMES:
                        has_seal = True
                if not isinstance(node, ast.Call):
                    continue
                fname = dotted_name(node.func)
                terminal = fname.rsplit(".", 1)[-1]
                if terminal in _ACQUIRE_ATTRS or (
                        terminal in {"call", "call_nowait"} and
                        first_str_arg(node) in _ACQUIRE_STRINGS):
                    acquires.append(node)
            for node in acquires:
                if not has_seal:
                    out.append(Violation(
                        self.name, module.path, node.lineno,
                        node.col_offset,
                        "segment lease acquired here but no seal "
                        "(SealObject/seal) or abort (AbortSegment/"
                        "release_lease/abort_lease) in this function — "
                        "a failed write parks the lease until the "
                        "stale sweep"))
                elif not has_try:
                    out.append(Violation(
                        self.name, module.path, node.lineno,
                        node.col_offset,
                        "segment lease acquired without a try block: "
                        "the seal-or-abort must also cover the ERROR "
                        "exit paths (wrap the fill in try/except and "
                        "abort the lease on failure)"))
        return out
