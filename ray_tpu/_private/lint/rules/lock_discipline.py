"""lock-discipline: what may happen while a threading lock is held.

The stores (shm_store, memory_store, metrics) guard shared state with
``threading.Lock`` while the control plane is asyncio: an ``await``
under a held sync lock parks the coroutine WITH the lock taken, so
every other thread (store writers, metrics scrapers) wedges until the
event loop happens to resume it — a latent priority inversion that
only ever surfaces as a flaky timeout. Sleeping under a lock is the
same bug with a fixed duration.

Checks (sync ``with <lock>:`` blocks only — ``async with`` an asyncio
lock is the normal way to await under mutual exclusion):

  * no ``await`` anywhere in the guarded block;
  * no ``time.sleep`` / known-blocking call in the guarded block;
  * nested acquisition of the SAME lock name (threading.Lock is not
    reentrant — this deadlocks immediately);
  * the cross-module lock acquisition graph (edges from syntactic
    nesting ``with A: ... with B:``) must be acyclic. Lock identity is
    ``module.Class.attr`` so the ordering that today lives as tribal
    knowledge (shm_store holds 2 locks, metrics 3) is machine-checked.

A with-item counts as a lock when its terminal name contains "lock" or
"mutex" (``self._lock``, ``_zombie_lock``, ``_GLOBAL_LOCK``).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Set, Tuple

from ray_tpu._private.lint.engine import (
    Module, Rule, Violation, dotted_name, register, walk_functions,
)
from ray_tpu._private.lint.rules.async_blocking import BLOCKING_CALLS

_LOCKY = ("lock", "mutex")


def _lock_name(expr: ast.AST) -> str:
    """'' unless the with-item expression looks like a lock."""
    name = dotted_name(expr)
    terminal = name.rsplit(".", 1)[-1].lower()
    if any(t in terminal for t in _LOCKY):
        return name
    return ""


# Generic lock attribute names: presumed class-local (every store has a
# `self._lock`), so their identity is scoped to module.Class. Anything
# more distinctive (`_zombie_lock`, `_GLOBAL_LOCK`, `_attached_lock`)
# names ONE conceptual lock wherever it is referenced — that unification
# is what makes the acquisition graph cross-module.
_GENERIC = {"lock", "_lock", "mutex", "_mutex"}


def _lock_identity(module: Module, cls: str, name: str) -> str:
    attr = name.rsplit(".", 1)[-1]
    if attr not in _GENERIC:
        return attr
    mod = os.path.basename(module.path)[:-3]
    if name.startswith("self.") and cls:
        return f"{mod}.{cls}.{attr}"
    return f"{mod}.{attr}"


@register
class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = ("await/blocking calls under a held threading lock; "
                   "reentrant self-acquisition; lock-order cycles "
                   "across the package")

    def __init__(self):
        # identity -> identity -> (path, line) witness of A held while
        # taking B; cycles judged in finalize() over all modules.
        self.edges: Dict[str, Dict[str, Tuple[str, int]]] = {}

    def collect(self, module: Module) -> Iterable[Violation]:
        out: List[Violation] = []
        for func, qualname, cls in walk_functions(module.tree):
            for node in ast.iter_child_nodes(func):
                self._scan(module, cls, qualname, node, held=[], out=out)
        return out

    def _scan(self, module, cls, qualname, node, held, out):
        """DFS that tracks the stack of held lock identities; stops at
        nested function/class boundaries (new execution context)."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, ast.Await) and held:
            out.append(Violation(
                self.name, module.path, node.lineno, node.col_offset,
                f"await while holding `{held[-1][0]}` in `{qualname}`: "
                "the coroutine parks with the threading lock taken, "
                "wedging every other thread that needs it"))
        if isinstance(node, ast.Call) and held:
            name = dotted_name(node.func)
            for pat, _why in BLOCKING_CALLS.items():
                if name == pat or name.endswith("." + pat):
                    out.append(Violation(
                        self.name, module.path, node.lineno,
                        node.col_offset,
                        f"blocking `{name}` while holding "
                        f"`{held[-1][0]}` in `{qualname}`"))
                    break
        if isinstance(node, ast.With):
            acquired = []
            for item in node.items:
                lname = _lock_name(item.context_expr)
                if not lname:
                    continue
                ident = _lock_identity(module, cls, lname)
                if any(h[1] == ident for h in held):
                    out.append(Violation(
                        self.name, module.path, node.lineno,
                        node.col_offset,
                        f"nested acquisition of `{lname}` in "
                        f"`{qualname}`: threading.Lock is not reentrant "
                        "— this deadlocks"))
                for _hname, hident, _hpath, _hline in held:
                    if hident != ident:  # self-edges are the reentrancy
                        self.edges.setdefault(hident, {}).setdefault(
                            ident, (module.path, node.lineno))
                acquired.append((lname, ident, module.path, node.lineno))
            held = held + acquired
            for child in node.body:
                self._scan(module, cls, qualname, child, held, out)
            return
        for child in ast.iter_child_nodes(node):
            self._scan(module, cls, qualname, child, held, out)

    def finalize(self) -> Iterable[Violation]:
        out: List[Violation] = []
        # DFS cycle detection over the acquisition graph.
        WHITE, GREY, BLACK = 0, 1, 2
        color = {n: WHITE for n in self.edges}
        stack_trace: List[str] = []

        def visit(n) -> List[str]:
            color[n] = GREY
            stack_trace.append(n)
            for succ in self.edges.get(n, {}):
                c = color.get(succ, WHITE)
                if c == GREY:
                    return stack_trace[stack_trace.index(succ):] + [succ]
                if c == WHITE:
                    cyc = visit(succ)
                    if cyc:
                        return cyc
            stack_trace.pop()
            color[n] = BLACK
            return []

        for n in list(self.edges):
            if color.get(n, WHITE) == WHITE:
                del stack_trace[:]
                cyc = visit(n)
                if cyc:
                    a, b = cyc[0], cyc[1]
                    path, line = self.edges[a][b]
                    out.append(Violation(
                        self.name, path, line, 0,
                        "lock acquisition cycle: "
                        + " -> ".join(cyc)
                        + " — a consistent cross-module lock order is "
                        "required (see RULES.md)"))
        return out
