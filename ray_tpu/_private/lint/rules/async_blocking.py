"""async-blocking: the control-plane event loop must never block.

Everything in gcs.py/raylet.py/core_worker.py/serve runs on ONE asyncio
loop per process; a single ``time.sleep`` or synchronous I/O call inside
an ``async def`` stalls every heartbeat, lease grant, and object
transfer sharing that loop (this is exactly the hidden-blocking class
"Runtime vs Scheduler" measures dominating Dask task latency).

Flags, inside ``async def`` bodies (nested sync defs/lambdas excluded —
they may legitimately run on executor threads):

  * known blocking calls: ``time.sleep``, subprocess spawns/waits,
    ``os.system``, blocking socket/DNS helpers, ``urllib`` fetches;
  * ``<x>.result()`` with no args — a concurrent.futures-style blocking
    join (asyncio futures want ``await``);
  * builtin ``open()`` — synchronous file I/O on the loop;
  * ``pickle/cloudpickle.dumps/loads`` — serialization is unbounded in
    the argument size and runs under the GIL on the loop.

Also flags ``time.sleep`` inside a loop body of a SYNC function (a
sleep-poll): such helpers are routinely reachable from async contexts
(async actors calling driver APIs), where they stall the actor's loop.
Intentional driver-thread polls carry a pragma with a justification.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ray_tpu._private.lint.engine import (
    Module, Rule, Violation, body_nodes, dotted_name, register,
    walk_functions,
)

# Dotted-name suffixes that always block the calling thread.
BLOCKING_CALLS = {
    "time.sleep": "blocks the event loop; use asyncio.sleep",
    "subprocess.run": "blocking subprocess wait on the loop",
    "subprocess.call": "blocking subprocess wait on the loop",
    "subprocess.check_call": "blocking subprocess wait on the loop",
    "subprocess.check_output": "blocking subprocess wait on the loop",
    "subprocess.getoutput": "blocking subprocess wait on the loop",
    "os.system": "blocking subprocess wait on the loop",
    "os.waitpid": "blocking process wait on the loop",
    "socket.create_connection": "blocking connect on the loop",
    "socket.gethostbyname": "blocking DNS resolution on the loop",
    "socket.getaddrinfo": "blocking DNS resolution on the loop",
    "urllib.request.urlopen": "blocking HTTP fetch on the loop",
}

SERIALIZE_CALLS = {
    "pickle.dumps", "pickle.loads", "pickle.load", "pickle.dump",
    "cloudpickle.dumps", "cloudpickle.loads",
}


@register
class AsyncBlockingRule(Rule):
    name = "async-blocking"
    description = ("blocking calls (sleep/subprocess/IO/.result()/pickle) "
                   "inside async def bodies, and sleep-polls in sync code")

    def collect(self, module: Module) -> Iterable[Violation]:
        out: List[Violation] = []
        for func, qualname, _cls in walk_functions(module.tree):
            is_async = isinstance(func, ast.AsyncFunctionDef)
            loop_depth_nodes = _loop_body_nodes(func)
            for node in body_nodes(func):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if is_async:
                    v = self._check_async_call(module, qualname, node, name)
                    if v is not None:
                        out.append(v)
                elif name.endswith("time.sleep") or name == "sleep" and \
                        _imported_from_time(module):
                    if id(node) in loop_depth_nodes:
                        out.append(Violation(
                            self.name, module.path, node.lineno,
                            node.col_offset,
                            f"sleep-poll loop in sync `{qualname}`: "
                            "time.sleep in a loop stalls any async caller "
                            "— convert to asyncio.sleep on the IO loop or "
                            "annotate why the blocking is intentional"))
        return out

    def _check_async_call(self, module, qualname, node, name):
        for pat, why in BLOCKING_CALLS.items():
            if name == pat or name.endswith("." + pat):
                return Violation(
                    self.name, module.path, node.lineno, node.col_offset,
                    f"`{name}` inside async def `{qualname}`: {why}")
        if name in SERIALIZE_CALLS or \
                any(name.endswith("." + s) for s in SERIALIZE_CALLS):
            return Violation(
                self.name, module.path, node.lineno, node.col_offset,
                f"`{name}` inside async def `{qualname}`: pickling holds "
                "the GIL on the loop for time unbounded in the payload "
                "size — move to an executor or bound the payload")
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "result" and not node.args \
                and not node.keywords:
            base = dotted_name(node.func.value)
            return Violation(
                self.name, module.path, node.lineno, node.col_offset,
                f"`{base}.result()` inside async def `{qualname}`: a "
                "blocking future join on the loop deadlocks if the "
                "result is produced by this same loop — await it")
        if isinstance(node.func, ast.Name) and node.func.id == "open":
            return Violation(
                self.name, module.path, node.lineno, node.col_offset,
                f"`open()` inside async def `{qualname}`: synchronous "
                "file I/O on the loop — move to an executor")
        return None


def _loop_body_nodes(func) -> set:
    """ids of nodes that sit inside a while/for loop body of ``func``
    (not crossing nested function boundaries)."""
    ids = set()
    for node in body_nodes(func):
        if isinstance(node, (ast.While, ast.For)):
            stack = list(node.body) + list(node.orelse)
            while stack:
                n = stack.pop()
                ids.add(id(n))
                if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda, ast.ClassDef)):
                    stack.extend(ast.iter_child_nodes(n))
    return ids


def _imported_from_time(module: Module) -> bool:
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time" and \
                any(a.name == "sleep" for a in node.names):
            return True
    return False
