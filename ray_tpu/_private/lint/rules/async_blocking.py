"""async-blocking: the control-plane event loop must never block.

Everything in gcs.py/raylet.py/core_worker.py/serve runs on ONE asyncio
loop per process; a single ``time.sleep`` or synchronous I/O call inside
an ``async def`` stalls every heartbeat, lease grant, and object
transfer sharing that loop (this is exactly the hidden-blocking class
"Runtime vs Scheduler" measures dominating Dask task latency).

Flags, inside ``async def`` bodies (nested sync defs/lambdas excluded —
they may legitimately run on executor threads):

  * known blocking calls: ``time.sleep``, subprocess spawns/waits,
    ``os.system``, blocking socket/DNS helpers, ``urllib`` fetches;
  * ``<x>.result()`` with no args — a concurrent.futures-style blocking
    join (asyncio futures want ``await``);
  * builtin ``open()`` — synchronous file I/O on the loop;
  * ``pickle/cloudpickle.dumps/loads`` — serialization is unbounded in
    the argument size and runs under the GIL on the loop.

Also flags ``time.sleep`` inside a loop body of a SYNC function (a
sleep-poll): such helpers are routinely reachable from async contexts
(async actors calling driver APIs), where they stall the actor's loop.
Intentional driver-thread polls carry a pragma with a justification.

Since v2 the rule also walks the shared call-graph substrate: an
``async def`` that CALLS a sync function whose body (or a sync callee's
body, up to 3 hops) contains a hard-blocking call — sleep, subprocess,
socket/DNS, no-arg ``.result()`` — is flagged at the async call site,
with the chain to the blocking line in the message. Executor hops
(``run_in_executor(None, f)``, ``Thread(target=f)``) pass the function
as an argument rather than calling it, so they create no edge and
never trip the check. A pragma on the blocking line itself clears
every async caller at once — the justification lives where the
blocking is.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ray_tpu._private.lint.engine import (
    Module, Rule, Violation, body_nodes, dotted_name, register,
    walk_functions,
)

# Dotted-name suffixes that always block the calling thread.
BLOCKING_CALLS = {
    "time.sleep": "blocks the event loop; use asyncio.sleep",
    "subprocess.run": "blocking subprocess wait on the loop",
    "subprocess.call": "blocking subprocess wait on the loop",
    "subprocess.check_call": "blocking subprocess wait on the loop",
    "subprocess.check_output": "blocking subprocess wait on the loop",
    "subprocess.getoutput": "blocking subprocess wait on the loop",
    "os.system": "blocking subprocess wait on the loop",
    "os.waitpid": "blocking process wait on the loop",
    "socket.create_connection": "blocking connect on the loop",
    "socket.gethostbyname": "blocking DNS resolution on the loop",
    "socket.getaddrinfo": "blocking DNS resolution on the loop",
    "urllib.request.urlopen": "blocking HTTP fetch on the loop",
}

SERIALIZE_CALLS = {
    "pickle.dumps", "pickle.loads", "pickle.load", "pickle.dump",
    "cloudpickle.dumps", "cloudpickle.loads",
}


@register
class AsyncBlockingRule(Rule):
    name = "async-blocking"
    description = ("blocking calls (sleep/subprocess/IO/.result()/pickle) "
                   "inside async def bodies, sleep-polls in sync code, and "
                   "async calls into sync functions that block (call-graph "
                   "reachability)")

    def __init__(self):
        self._program = None
        # id(fi) -> body-scan result; helpers called from many async
        # sites would otherwise be rescanned once per edge
        self._body_cache: dict = {}

    def setup(self, program) -> None:
        self._program = program

    def collect(self, module: Module) -> Iterable[Violation]:
        out: List[Violation] = []
        for func, qualname, _cls in walk_functions(module.tree):
            is_async = isinstance(func, ast.AsyncFunctionDef)
            loop_depth_nodes = _loop_body_nodes(func)
            for node in body_nodes(func):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if is_async:
                    v = self._check_async_call(module, qualname, node, name)
                    if v is not None:
                        out.append(v)
                elif name.endswith("time.sleep") or name == "sleep" and \
                        _imported_from_time(module):
                    if id(node) in loop_depth_nodes:
                        out.append(Violation(
                            self.name, module.path, node.lineno,
                            node.col_offset,
                            f"sleep-poll loop in sync `{qualname}`: "
                            "time.sleep in a loop stalls any async caller "
                            "— convert to asyncio.sleep on the IO loop or "
                            "annotate why the blocking is intentional"))
        return out

    def _check_async_call(self, module, qualname, node, name):
        for pat, why in BLOCKING_CALLS.items():
            if name == pat or name.endswith("." + pat):
                return Violation(
                    self.name, module.path, node.lineno, node.col_offset,
                    f"`{name}` inside async def `{qualname}`: {why}")
        if name in SERIALIZE_CALLS or \
                any(name.endswith("." + s) for s in SERIALIZE_CALLS):
            return Violation(
                self.name, module.path, node.lineno, node.col_offset,
                f"`{name}` inside async def `{qualname}`: pickling holds "
                "the GIL on the loop for time unbounded in the payload "
                "size — move to an executor or bound the payload")
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "result" and not node.args \
                and not node.keywords:
            base = dotted_name(node.func.value)
            return Violation(
                self.name, module.path, node.lineno, node.col_offset,
                f"`{base}.result()` inside async def `{qualname}`: a "
                "blocking future join on the loop deadlocks if the "
                "result is produced by this same loop — await it")
        if isinstance(node.func, ast.Name) and node.func.id == "open":
            return Violation(
                self.name, module.path, node.lineno, node.col_offset,
                f"`open()` inside async def `{qualname}`: synchronous "
                "file I/O on the loop — move to an executor")
        return None

    # --------------------------------------------- call-graph reachability

    def finalize(self) -> Iterable[Violation]:
        """Async defs calling sync functions that hard-block. Only the
        sharp blocking set (sleep/subprocess/socket/no-arg .result())
        counts here — open()/pickle stay direct-body-only, or every
        config-reading helper would light up."""
        out: List[Violation] = []
        if self._program is None:
            return out
        for fi in self._program.functions.values():
            if not fi.is_async:
                continue
            for call_node, callee in fi.calls:
                if callee.is_async:
                    continue        # awaited coroutine — the loop is fine
                hit = self._find_blocking(
                    callee, depth=3,
                    visited={(fi.path, fi.qualname): 99})
                if hit is None:
                    continue
                site_path, site_line, desc, chain = hit
                via = " -> ".join(chain)
                out.append(Violation(
                    self.name, fi.path, call_node.lineno,
                    call_node.col_offset,
                    f"async def `{fi.qualname}` calls sync `{via}` which "
                    f"blocks: {desc} at {site_path}:{site_line} runs on "
                    f"the event loop — await an async variant, hop to an "
                    f"executor, or pragma the blocking line"))
        return out

    def _find_blocking(self, fi, depth: int, visited: dict):
        """First hard-blocking site reachable from ``fi`` through sync
        call edges: (path, line, description, qualname chain) or None.

        ``visited`` maps node -> largest remaining-depth budget it has
        been explored with: a node first reached deep in one branch
        must be re-entered when another branch reaches it with budget
        to spare, or whether a within-bound chain is found would depend
        on statement order. Cycles still terminate (re-entry always
        carries a strictly smaller budget)."""
        key = (fi.path, fi.qualname)
        if visited.get(key, 0) >= depth:
            return None
        visited[key] = depth
        hit = self._body_blocking(fi)
        if hit is not None:
            return hit
        if depth <= 1:
            return None
        for _node, callee in fi.calls:
            if callee.is_async:
                continue
            hit = self._find_blocking(callee, depth - 1, visited)
            if hit is not None:
                path, line, desc, chain = hit
                return path, line, desc, [fi.qualname] + chain
        return None

    def _body_blocking(self, fi):
        """First hard-blocking call directly in ``fi``'s body whose line
        is NOT pragma-suppressed in its own module (so one pragma at the
        blocking line clears every async caller), memoized."""
        if id(fi) in self._body_cache:
            return self._body_cache[id(fi)]
        module = self._program.modules.get(fi.path)
        result = None
        for node in body_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            desc = None
            if any(name == pat or name.endswith("." + pat)
                   for pat in BLOCKING_CALLS):
                desc = f"`{name}`"
            elif name == "sleep" and module is not None and \
                    _imported_from_time(module):
                desc = "`time.sleep`"
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "result" and not node.args \
                    and not node.keywords:
                desc = (f"`{dotted_name(node.func)}()` "
                        f"(blocking future join)")
            if desc is None:
                continue
            if self._site_suppressed(fi.path, node.lineno):
                continue
            result = (fi.path, node.lineno, desc, [fi.qualname])
            break
        self._body_cache[id(fi)] = result
        return result

    def _site_suppressed(self, path: str, line: int) -> bool:
        """A pragma on the blocking line (in its own module) clears all
        async callers — the engine only sees the caller-side location,
        so the callee-side pragma is honoured here."""
        module = self._program.modules.get(path)
        if module is None:
            return False
        probe = Violation(self.name, path, line, 0, "")
        return module.suppressed(probe)


def _loop_body_nodes(func) -> set:
    """ids of nodes that sit inside a while/for loop body of ``func``
    (not crossing nested function boundaries)."""
    ids = set()
    for node in body_nodes(func):
        if isinstance(node, (ast.While, ast.For)):
            stack = list(node.body) + list(node.orelse)
            while stack:
                n = stack.pop()
                ids.add(id(n))
                if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda, ast.ClassDef)):
                    stack.extend(ast.iter_child_nodes(n))
    return ids


def _imported_from_time(module: Module) -> bool:
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time" and \
                any(a.name == "sleep" for a in node.names):
            return True
    return False
