"""cancel-safety: cancellation must not leak resources or be swallowed.

Every control-plane process is one asyncio loop; ``Task.cancel`` can
land at ANY await point. Three hazard classes, all shipped as real
bugs before this rule existed:

1. **Acquire-then-await without cleanup** (the PR6 admission-budget
   leak class). A resource acquired — admission bytes, a recycled
   segment lease, an mmap, remote gang bookings — followed by an await
   with no protecting ``try`` that releases it: cancellation at that
   await leaks the resource forever. Acquire/release pairs live in the
   documented tables below (``ACQUIRES`` / ``RPC_ACQUIRES`` /
   ``LEDGERS``), seeded from the real seams. An await after an acquire
   is *protected* when an enclosing ``try`` releases on the
   cancellation path: its ``finally`` — or an ``except`` catching
   CancelledError/BaseException that RE-RAISES — references one of the
   pair's release markers. ``during=True`` entries (strictly-ordered
   exchange streams: a cancel mid-read desyncs request/reply framing,
   the PR9 wrong-pid class) additionally require the acquiring await
   itself to sit inside the protecting ``try``.

2. **``await`` inside ``finally`` without ``asyncio.shield``**.
   Cancellation during cleanup cancels the cleanup: the first await in
   a ``finally`` raises CancelledError and everything after it is
   skipped. Wrap the awaited cleanup in ``asyncio.shield(...)`` (or do
   it synchronously).

3. **``except CancelledError`` that doesn't re-raise**. Swallowing
   CancelledError detaches the task from its canceller —
   ``task.cancel(); await task`` hangs or the task "succeeds" while
   half-done. Handlers may clean up, but must ``raise``.

Scope: ``_private/`` control-plane paths. Deliberate exceptions carry
a pragma with the reason.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, List, Optional, Set, Tuple

from ray_tpu._private.lint.engine import (
    Module, Rule, Violation, body_nodes, dotted_name, first_str_arg,
    register, walk_functions,
)

CLIENT_METHODS = {"call", "push", "call_nowait", "push_nowait", "_gcs_call"}


@dataclasses.dataclass(frozen=True)
class Pair:
    markers: Tuple[str, ...]    # names a releasing cleanup block mentions
    leaks: str                  # what a cancelled task leaks
    during: bool = False        # acquire await itself must be protected


# Callable terminal name -> acquire/release pair. A call to one of
# these (directly, or passed by reference into run_in_executor) is an
# acquire; the markers are the function/attribute names whose presence
# in a protecting finally / re-raising cancel-handler proves release.
ACQUIRES = {
    "_admit_pull": Pair(
        ("_pull_inflight_bytes", "_notify_pull_done"),
        "pull admission budget — every later pull queues behind bytes "
        "that will never drain"),
    "take_recycled": Pair(
        ("release_lease", "abort_lease", "_discard", "_segment_reaper"),
        "recycled segment lease (a store._lent entry pinned until the "
        "600 s stale sweep)"),
    "acquire_segment": Pair(
        ("_close_segment_owner", "release_lease", "_discard",
         "_segment_reaper"),
        "shm segment mapping + lease (fd, mmap pages and the segment "
        "file all outlive the pull)"),
    "_read_frame": Pair(
        ("_broken", "close"),
        "strictly-ordered zygote exchange — a cancelled read desyncs "
        "request/reply framing and the next caller adopts a stale "
        "reply", during=True),
}

# RPC methods that BOOK remote state: conn.call("Method", ...) is the
# acquire, release is proven the same way.
RPC_ACQUIRES = {
    "BookGangMembers": Pair(
        ("_rollback_gang_booking", "ReleaseGangMembers"),
        "remote gang lease bookings on peer raylets"),
}

# Paired counters: += before an await needs a protecting block that
# references the same attribute (the -= lives there).
LEDGERS = {
    "_pull_inflight_bytes": "pull admission budget",
    "pending_lease": "per-class pending-lease ledger",
    "_num_starting": "starting-worker ledger",
}

_CANCELISH = {"CancelledError", "BaseException"}


def _catches_cancel(type_node: Optional[ast.AST]) -> bool:
    if type_node is None:
        return True
    if isinstance(type_node, ast.Tuple):
        return any(_catches_cancel(e) for e in type_node.elts)
    return dotted_name(type_node).rsplit(".", 1)[-1] in _CANCELISH


def _subtree(stmts) -> Iterable[ast.AST]:
    """Walk statements without descending into nested defs."""
    stack = list(stmts)
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            stack.extend(ast.iter_child_nodes(n))


def _refs(stmts) -> Set[str]:
    """Terminal names referenced in ``stmts`` (not crossing defs)."""
    out: Set[str] = set()
    for n in _subtree(stmts):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


def _has_raise(stmts) -> bool:
    return any(isinstance(n, ast.Raise) for n in _subtree(stmts))


def _block_range(stmts) -> Tuple[int, int]:
    return (stmts[0].lineno,
            max(getattr(s, "end_lineno", None) or s.lineno for s in stmts))


@register
class CancelSafetyRule(Rule):
    name = "cancel-safety"
    description = ("resources acquired before an await with no "
                   "releasing try/finally, awaits inside finally "
                   "without asyncio.shield, and except CancelledError "
                   "that doesn't re-raise")

    def collect(self, module: Module) -> Iterable[Violation]:
        if "_private" not in module.path.replace("\\", "/"):
            return ()
        out: List[Violation] = []
        for func, qualname, _cls in walk_functions(module.tree):
            nodes = list(body_nodes(func))
            self._check_finally_awaits(module, qualname, nodes, out)
            self._check_cancel_swallow(module, qualname, nodes, out)
            if isinstance(func, ast.AsyncFunctionDef):
                self._check_acquires(module, qualname, nodes, out)
        return out

    # ------------------------------------------ (1) acquire-then-await

    def _check_acquires(self, module, qualname, nodes, out):
        awaits = sorted((n.lineno, n) for n in nodes
                        if isinstance(n, ast.Await))
        if not awaits:
            return
        tries = [n for n in nodes if isinstance(n, ast.Try)]
        # (body_start, body_end, marker names proven released on the
        # cancellation path)
        try_infos = []
        # (start, end, refs) of handler/finally blocks: release refs
        # THERE protect, they don't end the live window — and an await
        # INSIDE a releasing cleanup block (awaiting the rollback
        # itself) is the release, not a new hazard
        cleanup_blocks: List[Tuple[int, int, Set[str]]] = []
        for t in tries:
            prot: Set[str] = set()
            if t.finalbody:
                prot |= _refs(t.finalbody)
                a, b = _block_range(t.finalbody)
                cleanup_blocks.append((a, b, _refs(t.finalbody)))
            for h in t.handlers:
                a, b = _block_range(h.body)
                cleanup_blocks.append((a, b, _refs(h.body)))
                if _catches_cancel(h.type) and _has_raise(h.body):
                    prot |= _refs(h.body)
            start, end = _block_range(t.body)
            try_infos.append((start, end, prot))

        def in_cleanup(line: int) -> bool:
            return any(a <= line <= b for a, b, _r in cleanup_blocks)

        # every (line, marker-name) reference outside cleanup blocks —
        # the first one after an acquire closes its hazard window (the
        # code released / consumed the resource on the success path)
        ref_lines: List[Tuple[int, str]] = []
        for n in nodes:
            name = None
            if isinstance(n, ast.Name):
                name = n.id
            elif isinstance(n, ast.Attribute):
                name = n.attr
            if name is not None and not in_cleanup(n.lineno):
                ref_lines.append((n.lineno, name))

        events = self._acquire_events(nodes)
        for line, end_line, what, pair in events:
            markers = set(pair.markers)
            window_end = min(
                (ln for ln, nm in ref_lines if ln > end_line
                 and nm in markers), default=10 ** 9)
            hazard_from = line if pair.during else end_line + 1
            for aline, _anode in awaits:
                if not (hazard_from <= aline < window_end):
                    continue
                protected = any(
                    s <= aline <= e and (prot & markers)
                    for s, e, prot in try_infos)
                if not protected:
                    # awaiting the rollback inside a releasing cleanup
                    # block IS the release
                    protected = any(
                        a <= aline <= b and (r & markers)
                        for a, b, r in cleanup_blocks)
                if protected:
                    continue
                need = "covering the acquire itself and " \
                    if pair.during else ""
                out.append(Violation(
                    self.name, module.path, line, 0,
                    f"`{what}` acquired in `{qualname}` but the await "
                    f"at line {aline} has no protecting try "
                    f"{need}releasing it (finally or re-raising "
                    f"CancelledError handler referencing one of "
                    f"{sorted(markers)}): cancellation there leaks "
                    f"{pair.leaks}"))
                break

    def _acquire_events(self, nodes):
        """(line, end_line, description, Pair) for every acquire in
        the body: direct calls, function references handed to an
        executor, booking RPCs, and ledger increments."""
        events = []
        for n in nodes:
            if isinstance(n, ast.Call):
                term = dotted_name(n.func).rsplit(".", 1)[-1]
                end = getattr(n, "end_lineno", None) or n.lineno
                if term in ACQUIRES:
                    events.append((n.lineno, end, term, ACQUIRES[term]))
                    continue
                if term in CLIENT_METHODS:
                    m = first_str_arg(n)
                    if m in RPC_ACQUIRES:
                        events.append((n.lineno, end, f'call("{m}")',
                                       RPC_ACQUIRES[m]))
                        continue
                for arg in n.args:
                    if isinstance(arg, (ast.Name, ast.Attribute)):
                        ref = dotted_name(arg).rsplit(".", 1)[-1]
                        if ref in ACQUIRES:
                            events.append((n.lineno, end, ref,
                                           ACQUIRES[ref]))
            elif isinstance(n, ast.AugAssign) and \
                    isinstance(n.op, ast.Add) and \
                    isinstance(n.target, ast.Attribute) and \
                    n.target.attr in LEDGERS:
                attr = n.target.attr
                events.append((
                    n.lineno, n.lineno, f"{attr} += ...",
                    Pair((attr,), f"the {LEDGERS[attr]} (never "
                         f"decremented)")))
        return events

    # ------------------------------------------ (2) await in finally

    def _check_finally_awaits(self, module, qualname, nodes, out):
        for t in nodes:
            if not (isinstance(t, ast.Try) and t.finalbody):
                continue
            for n in _subtree(t.finalbody):
                if not isinstance(n, ast.Await):
                    continue
                shielded = any(
                    isinstance(c, ast.Call) and
                    dotted_name(c.func).rsplit(".", 1)[-1] == "shield"
                    for c in ast.walk(n))
                if shielded:
                    continue
                out.append(Violation(
                    self.name, module.path, n.lineno, n.col_offset,
                    f"await inside finally in `{qualname}`: "
                    "cancellation during cleanup cancels the cleanup "
                    "and skips everything after this line — wrap in "
                    "asyncio.shield(...) or clean up synchronously"))

    # --------------------------------- (3) swallowed CancelledError

    def _check_cancel_swallow(self, module, qualname, nodes, out):
        for t in nodes:
            if not isinstance(t, ast.Try):
                continue
            for h in t.handlers:
                if h.type is None:
                    continue        # bare except: exception-hygiene's
                names = [h.type] if not isinstance(h.type, ast.Tuple) \
                    else list(h.type.elts)
                if not any(dotted_name(e).rsplit(".", 1)[-1] ==
                           "CancelledError" for e in names):
                    continue
                if _has_raise(h.body):
                    continue
                out.append(Violation(
                    self.name, module.path, h.lineno, h.col_offset,
                    f"except CancelledError in `{qualname}` does not "
                    "re-raise: the task reports success to its "
                    "canceller while half-done — clean up, then "
                    "`raise`"))
