"""exception-flow: raise-set inference findings on real error paths.

Five checks, all driven by the ``excflow`` substrate (whole-program
raise-set inference + per-RPC error contracts) and all tuned to the
same discipline as the rest of raylint: a finding must be PROVABLE
from the static program, so ambiguity silences the check rather than
widening it.

* **dead-handler** — ``except T`` where T is a project typed error,
  the try body's raise sources are fully resolved, and nothing the
  body can raise is caught by T. The classic shape is a renamed or
  re-homed exception: the handler compiles, matches nothing, and the
  recovery path it used to guard silently stops existing.
* **unknown-exc-attr** — ``exc.X`` where ``exc`` is an alias of the
  public ``exceptions`` module and X is not defined there: an
  AttributeError at the exact moment the code is trying to handle a
  real failure.
* **swallowed-retriable** — a broad ``except``/``except Exception``
  clause provably reached by a typed RETRIABLE error
  (OutOfMemoryError, ObjectLostError, WorkerCrashedError,
  GangBrokenError) whose body neither re-raises nor classifies the
  exception. This is the retry-budget-bypass class: the caller's
  retry accounting never sees the failure.
* **unconsumed-retry-signal** — an awaited ``conn.call`` of a method
  whose error contract includes an in-band backpressure key
  (``retry_later`` / ``stale_epoch``) in a function that never reads
  any reply-signal key and does not pass the reply on. Unlike an
  exception, an in-band signal propagates NOWHERE by default —
  dropping the dict drops the signal.
* **unexported-raise** — a ``raise`` of a tree-defined RayTpuError
  subclass that ``exceptions.py`` does not export: callers cannot
  name it in an ``except`` clause without importing private modules.

The error contracts themselves are frozen by schemagen into
``error_contracts_golden.json`` and drift-checked in CI; this rule
family consumes them, it does not gate them.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from ray_tpu._private.lint.engine import (
    Module, Rule, Violation, body_nodes, dotted_name, register,
)
from ray_tpu._private.lint import excflow

# Typed errors whose whole point is to be SEEN by retry accounting:
# swallowing one in a broad except bypasses the budget that makes the
# failure recoverable.
RETRIABLE = frozenset({
    "OutOfMemoryError", "ObjectLostError", "WorkerCrashedError",
    "GangBrokenError",
})

# In-band reply keys that carry a backpressure/fencing signal the
# caller must consume (an ignored reply dict silently drops them).
_SIGNAL_KEYS = frozenset({"retry_later", "stale_epoch", "granted"})

_EXC_MODULE_BASENAME = "exceptions"


def _exceptions_exports(program) -> Optional[Set[str]]:
    """Names defined at top level of the public exceptions module(s):
    class defs plus alias assignments (``RayActorError =
    ActorDiedError``). None when no exceptions module was scanned —
    every check keyed on it goes silent rather than flagging the
    world."""
    paths = program.by_basename.get(_EXC_MODULE_BASENAME, [])
    exports: Set[str] = set()
    found = False
    for path in paths:
        module = program.modules.get(path)
        if module is None or module.tree is None:
            continue
        found = True
        for st in module.tree.body:
            if isinstance(st, ast.ClassDef):
                exports.add(st.name)
            elif isinstance(st, ast.Assign):
                for t in st.targets:
                    if isinstance(t, ast.Name):
                        exports.add(t.id)
    return exports if found else None


def _exc_aliases(program, path: str) -> Set[str]:
    """Local names that refer to the exceptions module in ``path``
    (``from ray_tpu import exceptions as exc`` / ``import
    exceptions``)."""
    out = set()
    for local, target in program.import_modules.get(path, {}).items():
        if target.rsplit(".", 1)[-1] == _EXC_MODULE_BASENAME:
            out.add(local)
    return out


def _handler_classifies(meta: excflow.HandlerMeta,
                        hierarchy: excflow.Hierarchy) -> bool:
    """True when the handler body does anything that routes the typed
    error onward: re-raises (bound or otherwise), isinstance-checks
    the bound exception, or names a retriable type at all."""
    if meta.can_reraise:
        return True
    for node in ast.walk(meta.node):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id == "isinstance" and meta.bound_name and \
                any(isinstance(a, ast.Name) and a.id == meta.bound_name
                    for a in node.args):
            return True
        if isinstance(node, (ast.Name, ast.Attribute)):
            term = dotted_name(node).rsplit(".", 1)[-1]
            if term in RETRIABLE:
                return True
    return False


@register
class ExceptionFlowRule(Rule):
    name = "exception-flow"
    description = ("dead typed handlers, swallowed retriable errors, "
                   "dropped in-band retry signals, unexported raises "
                   "(whole-program raise-set inference)")

    def setup(self, program) -> None:
        self.program = program
        self.hierarchy = excflow.excflow_hierarchy(program)
        self.infos = excflow.infer_raise_sets(program)
        self.exports = _exceptions_exports(program)
        self.contracts = excflow.error_contracts(program)

    # ------------------------------------------------------------ per-module

    def collect(self, module: Module) -> Iterable[Violation]:
        out: List[Violation] = []
        if self.exports is not None:
            aliases = _exc_aliases(self.program, module.path)
            if aliases:
                for node in ast.walk(module.tree):
                    if isinstance(node, ast.Attribute) and \
                            isinstance(node.value, ast.Name) and \
                            node.value.id in aliases and \
                            node.attr[0:1].isupper() and \
                            node.attr not in self.exports:
                        out.append(Violation(
                            self.name, module.path, node.lineno,
                            node.col_offset,
                            f"[unknown-exc-attr] `{node.value.id}."
                            f"{node.attr}` does not exist in the "
                            f"exceptions module — this handler dies "
                            f"with AttributeError the moment it fires"))
        return out

    # ------------------------------------------------------- whole-program

    def finalize(self) -> Iterable[Violation]:
        out: List[Violation] = []
        for key in sorted(self.program.functions):
            fi = self.program.functions[key]
            out.extend(self._check_handlers(fi))
            out.extend(self._check_unexported(fi))
        out.extend(self._check_retry_signals())
        return out

    def _check_handlers(self, fi) -> Iterable[Violation]:
        out: List[Violation] = []
        for meta, reach, complete in excflow.handler_reach(
                self.program, fi):
            if meta.dynamic:
                continue
            if complete and not meta.broad:
                for t in meta.types:
                    if not self.hierarchy.project_typed(t):
                        continue
                    if not any(self.hierarchy.catches(t, r)
                               for r in reach):
                        out.append(Violation(
                            self.name, fi.path, meta.node.lineno,
                            meta.node.col_offset,
                            f"[dead-handler] `except {t}` can never "
                            f"fire: the try body provably cannot "
                            f"raise it (raise-set: "
                            f"{sorted(reach) or 'empty'}) — renamed "
                            f"exception or stale recovery path"))
            if meta.catches_broadly():
                swallowed = sorted(
                    r for r in reach
                    if self.hierarchy.ancestors(r) & RETRIABLE)
                if swallowed and not _handler_classifies(
                        meta, self.hierarchy):
                    out.append(Violation(
                        self.name, fi.path, meta.node.lineno,
                        meta.node.col_offset,
                        f"[swallowed-retriable] broad except swallows "
                        f"{', '.join(swallowed)} — the caller's retry "
                        f"accounting never sees the failure; re-raise "
                        f"or classify typed retriable errors"))
        return out

    def _check_unexported(self, fi) -> Iterable[Violation]:
        if self.exports is None:
            return ()
        out: List[Violation] = []
        events = getattr(self.program, "_excflow_events", {}).get(
            (fi.path, fi.qualname), [])
        for ev in events:
            if ev.kind != "raise":
                continue
            for name in sorted(ev.names):
                if name == excflow._PROJECT_ROOT_EXC:
                    continue
                if not self.hierarchy.project_typed(name):
                    continue
                if name in self.hierarchy.parents and \
                        name not in self.exports:
                    out.append(Violation(
                        self.name, fi.path, ev.node.lineno,
                        ev.node.col_offset,
                        f"[unexported-raise] raises project-typed "
                        f"`{name}` which exceptions.py does not "
                        f"export — callers cannot catch it by name"))
        return out

    def _check_retry_signals(self) -> Iterable[Violation]:
        out: List[Violation] = []
        for cc in self.program.rpc.client_calls:
            if cc.kind != "call" or not cc.awaited or \
                    cc.in_function is None:
                continue
            contract = self.contracts.get(cc.method)
            if contract is None or "retry_later" not in \
                    contract["error_reply_keys"]:
                continue
            fi = cc.in_function
            if self._consumes_signal(fi, cc):
                continue
            out.append(Violation(
                self.name, cc.path, cc.lineno, cc.col,
                f"[unconsumed-retry-signal] `{cc.method}` can reply "
                f"retry_later (lease backpressure) but "
                f"{fi.qualname} never reads a reply signal key and "
                f"drops the reply — the backpressure signal is lost"))
        return out

    def _consumes_signal(self, fi, cc) -> bool:
        """The enclosing function reads SOME in-band signal key, or
        visibly hands the reply onward (returns/yields an expression
        containing the call)."""
        for node in body_nodes(fi.node):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    node.value in _SIGNAL_KEYS:
                return True
        for node in ast.walk(fi.node):
            if isinstance(node, (ast.Return, ast.Yield)) and \
                    node.value is not None:
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Call) and \
                            getattr(sub, "lineno", None) == cc.lineno \
                            and sub.col_offset == cc.col:
                        return True
        return False
