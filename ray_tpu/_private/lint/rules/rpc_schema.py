"""rpc-schema: whole-program RPC header-schema inference and checking.

rpc-contract (v1) proves every client method *string* has a handler;
this rule proves the *payload* matches what the handler actually reads.
The wire protocol is schemaless — ``call("Method", {header dict})`` —
so a missing or typo'd header key is invisible until the handler raises
``KeyError`` at runtime (surfacing as an opaque error reply) or, worse,
silently takes a ``.get()`` default the caller never intended.

Inference runs on the shared call-graph substrate (callgraph.Program):

  server side — each registration's handler expression is resolved to
  its ``def``; the body's uses of the header parameter (3rd positional:
  ``(self, conn, header, bufs)``) are classified:

    * ``header["k"]``                        -> k required
    * ``header.get("k", ...)`` / ``"k" in header`` / ``header.pop("k", d)``
                                             -> k optional
    * truthiness / ``is None`` guards        -> ignored (benign)
    * anything dynamic (iteration, ``header[var]``, passing ``header``
      on, ``**header``, ``.items()``...)     -> schema OPEN: required
      keys still hold, unknown-key checking is disabled

  reply side — the same handler bodies yield a reply schema from their
  ``return {...}`` literals (``return {...}, bufs`` counts): keys a
  return path *can* produce (union) and keys *every* return produces
  (intersection). Any non-literal return (a forwarded value, a Future
  from a sync fast-path handler) marks the reply OPEN and reply checks
  go out of scope for that method.

  client side — every ``call/push/call_nowait/push_nowait/_gcs_call``
  whose header is a dict literal with constant keys is checked:

    * a key required by EVERY handler of that method but absent from
      the literal (or the call sends no header at all) -> violation;
    * a key no handler knows, when every handler's schema is closed
      -> violation (with a did-you-mean suggestion).

  and every ``reply["k"]`` read through a ``reply, bufs = await
  conn.call(...)`` tuple binding — including sync bridges like
  ``self._run(self._gcs_call(...))`` / ``run_until_complete`` /
  ``wait_for``; a name bound from several reply calls (branches) is
  judged against the union of their reply keys, and rebinding to a
  non-reply value kills checking — is checked against the reply
  union: a key NO return path ever produces is a guaranteed KeyError
  when the reply lands.

  Registrations that provably dangle (``self.x`` with no ``x`` on any
  class and no bases to inherit it) and handlers whose signature
  cannot accept ``(conn, header, bufs)`` are flagged at the def site —
  both dispatch failures the string check alone cannot see.

Methods with several handlers (e.g. "Published" served by raylet AND
core worker) use union semantics: required = intersection, known =
union, closed = all closed — a key is only an error when it is wrong
for every server the call could reach.
"""

from __future__ import annotations

import ast
import difflib
from typing import Dict, Iterable, List, Optional, Set

from ray_tpu._private.lint.engine import (
    Rule, Violation, body_nodes, dotted_name, first_str_arg, register,
)

# header.<method>(...) calls that keep the schema closed.
_GET_LIKE = {"get", "pop"}
# header.<method>(...) calls that open the schema (dynamic key use).
_OPEN_ATTRS = {"items", "keys", "values", "update", "copy", "setdefault"}
# Client-side calls whose result is a reply future/tuple (push/push_
# nowait are one-way: no reply to check).
_REPLYING = {"call", "_gcs_call"}
# Wrappers a reply flows through unchanged on sync or timeout bridges:
# reply, _ = self._run(self._gcs_call(...)) / wait_for(conn.call(), t).
_BRIDGES = {"_run", "run_until_complete", "wait_for"}


class HandlerSchema:
    __slots__ = ("fi", "required", "optional", "open",
                 "reply_keys", "reply_guaranteed", "reply_open",
                 "compat_defaults")

    def __init__(self, fi, required: Set[str], optional: Set[str],
                 open_: bool, reply_keys: Set[str],
                 reply_guaranteed: Set[str], reply_open: bool,
                 compat_defaults: Optional[dict] = None):
        self.fi = fi
        self.required = required
        self.optional = optional
        self.open = open_
        self.reply_keys = reply_keys
        self.reply_guaranteed = reply_guaranteed
        self.reply_open = reply_open
        # required keys a generated stub decodes with a default when a
        # pre-deprecation-window peer omits them (see schemagen.py)
        self.compat_defaults = dict(compat_defaults or {})

    @property
    def known(self) -> Set[str]:
        return self.required | self.optional


class MethodSchema:
    __slots__ = ("method", "handlers")

    def __init__(self, method: str, handlers: List[HandlerSchema]):
        self.method = method
        self.handlers = handlers

    @property
    def required(self) -> Set[str]:
        """Keys required by EVERY handler — the only ones a client can
        be proven to be missing."""
        req = None
        for h in self.handlers:
            req = h.required if req is None else req & h.required
        return req or set()

    @property
    def known(self) -> Set[str]:
        out: Set[str] = set()
        for h in self.handlers:
            out |= h.known
        return out

    @property
    def closed(self) -> bool:
        return bool(self.handlers) and all(not h.open for h in self.handlers)

    @property
    def reply_keys(self) -> Set[str]:
        """Keys SOME return path of SOME handler can produce."""
        out: Set[str] = set()
        for h in self.handlers:
            out |= h.reply_keys
        return out

    @property
    def reply_guaranteed(self) -> Set[str]:
        """Keys EVERY return path of EVERY handler produces."""
        guar = None
        for h in self.handlers:
            guar = h.reply_guaranteed if guar is None \
                else guar & h.reply_guaranteed
        return guar or set()

    @property
    def reply_open(self) -> bool:
        return any(h.reply_open for h in self.handlers) or \
            not self.handlers

    @property
    def compat_defaults(self) -> dict:
        out: dict = {}
        for h in self.handlers:
            out.update(h.compat_defaults)
        return out

    def where(self) -> str:
        return ", ".join(sorted(
            f"{h.fi.path}:{h.fi.node.lineno}" for h in self.handlers))


def _stub_of_call(program, call: ast.Call, attr: str):
    """The StubClassInfo behind ``<Class>.<attr>(...)`` — e.g.
    ``protocol.HeartbeatRequest.from_header(h)`` — or None."""
    if program is None:
        return None
    dotted = dotted_name(call.func)
    parts = dotted.split(".")
    if len(parts) < 2 or parts[-1] != attr:
        return None
    return program.stub_class(parts[-2])


def infer_handler_schema(fi, program=None) -> HandlerSchema:
    """Classify every use of the handler's header parameter."""
    pos = fi.positional_params()
    if len(pos) < 2:
        return HandlerSchema(fi, set(), set(), True,
                             *_infer_reply(fi, program))
    header_name = pos[1]
    required: Set[str] = set()
    optional: Set[str] = set()
    # contributions read off generated stub classes the header is
    # decoded through (X.from_header(header)): the stub's declared
    # schema IS the handler's schema for those keys
    stub_required: Set[str] = set()
    stub_optional: Set[str] = set()
    stub_compat: Dict[str, object] = {}
    open_ = False
    # First source line of each constant-key subscript, load vs store:
    # a write demotes a key to optional ONLY when it precedes every
    # read — `header["k"] = default(); use(header["k"])` needs nothing
    # from the caller, but `use(header["k"]); header["k"] = x` still
    # KeyErrors on the first read, so the key stays required.
    sub_loads: Dict[str, int] = {}
    sub_stores: Dict[str, int] = {}
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(fi.node):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    for node in ast.walk(fi.node):
        if not (isinstance(node, ast.Name) and node.id == header_name):
            continue
        parent = parents.get(id(node))
        if isinstance(parent, ast.Subscript) and parent.value is node:
            sl = parent.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                tab = sub_loads if isinstance(parent.ctx, ast.Load) \
                    else sub_stores
                tab[sl.value] = min(tab.get(sl.value, parent.lineno),
                                    parent.lineno)
            else:
                open_ = True                 # header[variable]
        elif isinstance(parent, ast.Attribute) and parent.value is node:
            call = parents.get(id(parent))
            is_call = isinstance(call, ast.Call) and call.func is parent
            if is_call and parent.attr in _GET_LIKE:
                k = call.args[0] if call.args else None
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    if parent.attr == "pop" and len(call.args) == 1 \
                            and not call.keywords:
                        required.add(k.value)
                    else:
                        optional.add(k.value)
                else:
                    open_ = True
            elif is_call and parent.attr in _OPEN_ATTRS:
                open_ = True
            else:
                open_ = True                 # header.foo / bound method ref
        elif isinstance(parent, ast.Compare) and node in parent.comparators:
            ops = parent.ops
            if len(ops) == 1 and isinstance(ops[0], (ast.In, ast.NotIn)) \
                    and isinstance(parent.left, ast.Constant) \
                    and isinstance(parent.left.value, str):
                optional.add(parent.left.value)
            elif all(isinstance(o, (ast.Is, ast.IsNot, ast.Eq, ast.NotEq))
                     for o in ops):
                pass                         # `header is None` guards
            else:
                open_ = True
        elif isinstance(parent, (ast.BoolOp, ast.UnaryOp)):
            pass                             # `header or {}` / `not header`
        elif isinstance(parent, ast.If) and parent.test is node:
            pass                             # bare truthiness test
        elif isinstance(parent, (ast.Assign, ast.AugAssign)) \
                and isinstance(node.ctx, ast.Store):
            pass                             # rebinding (`header = ...`)
        elif isinstance(parent, ast.arguments):
            pass                             # the parameter itself
        elif isinstance(parent, ast.Call) and parent.args and \
                parent.args[0] is node and \
                (stub := _stub_of_call(program, parent,
                                       "from_header")) is not None:
            # `X.from_header(header)`: the generated stub's declared
            # schema speaks for the handler — a stub-migrated handler
            # stays CLOSED instead of degrading to open on "escape".
            stub_required |= stub.required
            stub_optional |= stub.optional
            stub_compat.update(stub.compat_defaults)
            if stub.open:
                open_ = True
        else:
            open_ = True                     # escaped: passed on, returned...
    required.update(sub_loads)
    for k, store_line in sub_stores.items():
        if k not in sub_loads or store_line < sub_loads[k]:
            optional.add(k)                  # write-first (or write-only)
    # A guarded read (`if "k" in header: header["k"]`) is optional, not
    # required — the membership test wins.
    required -= optional
    # Stub-declared keys merge LAST, and the stub's required set wins
    # over a literal optional access of the same key: the generated
    # class is the source of truth for the keys it declares.
    required |= stub_required
    optional = (optional | stub_optional) - required
    if not required and not optional and not open_:
        # Handler never touches its header: nothing to infer — treat as
        # open rather than flagging every caller's keys as unknown.
        open_ = True
    reply_keys, reply_guaranteed, reply_open = _infer_reply(fi, program)
    return HandlerSchema(fi, required, optional, open_,
                         reply_keys, reply_guaranteed, reply_open,
                         stub_compat)


class _DictBuild:
    """One local name bound (exactly once) to a dict literal and grown
    by constant-key subscript stores — ``reply = {}; reply["k"] = v;
    return reply``. Tracks which keys EVERY return sees (the literal's
    keys plus unconditional stores that precede the first return) vs
    keys some path can add."""
    __slots__ = ("binds", "keys", "guaranteed", "open",
                 "first_return_line", "escaped")

    def __init__(self):
        self.binds = 0
        self.keys: Set[str] = set()
        self.guaranteed: Set[str] = set()
        self.open = False
        self.first_return_line = None
        self.escaped = False


def _return_value(node: ast.Return):
    value = node.value
    if isinstance(value, ast.Tuple) and len(value.elts) == 2:
        value = value.elts[0]   # (reply_header, bufs)
    return value


def _incremental_dicts(fi) -> Dict[str, _DictBuild]:
    """Names provably holding an incrementally-built reply dict (see
    _DictBuild). Conservative: any rebinding, deletion, or use beyond
    subscripts / ``.get`` / membership tests / the return itself drops
    the name — the old behavior (reply OPEN) takes over."""
    builds: Dict[str, _DictBuild] = {}

    def visit(st, conditional):
        if isinstance(st, ast.Assign) and len(st.targets) == 1:
            t = st.targets[0]
            if isinstance(t, ast.Name):
                if isinstance(st.value, ast.Dict):
                    rec = builds.setdefault(t.id, _DictBuild())
                    rec.binds += 1
                    for k in st.value.keys:
                        if isinstance(k, ast.Constant) and \
                                isinstance(k.value, str):
                            rec.keys.add(k.value)
                            rec.guaranteed.add(k.value)
                        else:
                            rec.open = True   # {**spread} / computed key
                elif t.id in builds:
                    builds[t.id].binds += 1   # rebound away: kill below
            elif isinstance(t, ast.Subscript) and \
                    isinstance(t.value, ast.Name) and \
                    t.value.id in builds:
                rec = builds[t.value.id]
                sl = t.slice
                if isinstance(sl, ast.Constant) and \
                        isinstance(sl.value, str):
                    rec.keys.add(sl.value)
                    if not conditional and (
                            rec.first_return_line is None or
                            st.lineno < rec.first_return_line):
                        rec.guaranteed.add(sl.value)
                else:
                    rec.open = True           # reply[var] = ...
        elif isinstance(st, ast.Assign):
            # multi-target (`reply = other = {}`) aliases the dict:
            # every Name target counts as an un-provable binding
            for t in st.targets:
                if isinstance(t, ast.Name):
                    builds.setdefault(t.id, _DictBuild()).binds += 2
        elif isinstance(st, ast.AnnAssign) and \
                isinstance(st.target, ast.Name) and \
                st.target.id in builds:
            builds[st.target.id].binds += 1   # annotated rebinding
        elif isinstance(st, ast.Delete):
            for t in st.targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in builds:
                    rec = builds[t.value.id]
                    sl = t.slice
                    if isinstance(sl, ast.Constant) and \
                            isinstance(sl.value, str):
                        # a (possibly conditional) delete: the key may
                        # still appear on some path, but is no longer
                        # guaranteed on every one
                        rec.guaranteed.discard(sl.value)
                    else:
                        rec.guaranteed.clear()  # del reply[var]
        elif isinstance(st, ast.Return):
            value = _return_value(st)
            if isinstance(value, ast.Name) and value.id in builds:
                rec = builds[value.id]
                if rec.first_return_line is None:
                    rec.first_return_line = st.lineno

    def walk(stmts, conditional):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            visit(st, conditional)
            always = isinstance(st, (ast.With, ast.AsyncWith))
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(st, attr, None)
                if sub:
                    walk(sub, conditional or not always)
            for h in getattr(st, "handlers", None) or ():
                walk(h.body, True)

    walk(fi.node.body, False)
    if not builds:
        return builds
    # Escape scan: a tracked name used anywhere beyond the benign set
    # (subscript base, `.get`, membership test, the return) may leak
    # the dict to code that mutates it — not provable, drop it.
    parents: Dict[int, ast.AST] = {}
    for node in body_nodes(fi.node):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    for node in body_nodes(fi.node):
        if not (isinstance(node, ast.Name) and node.id in builds):
            continue
        rec = builds[node.id]
        parent = parents.get(id(node))
        if isinstance(parent, ast.Subscript) and parent.value is node:
            continue
        if isinstance(parent, ast.Return):
            continue
        if isinstance(parent, ast.Tuple) and \
                isinstance(parents.get(id(parent)), ast.Return) and \
                len(parent.elts) == 2 and parent.elts[0] is node:
            continue                          # return reply, bufs
        if isinstance(parent, ast.Attribute) and parent.value is node \
                and parent.attr == "get":
            continue
        if isinstance(parent, ast.Compare) and node in parent.comparators \
                and len(parent.ops) == 1 and \
                isinstance(parent.ops[0], (ast.In, ast.NotIn)):
            continue
        if isinstance(parent, ast.Assign) and node in parent.targets:
            # the bind itself; note `other[k] = reply` has the name as
            # the VALUE, falls through, and correctly counts as an
            # aliasing escape
            continue
        rec.escaped = True
    # A nested def/lambda referencing the name can mutate the dict
    # after every linear-order fact above was collected (body_nodes
    # deliberately does not descend into them): that is an escape.
    for node in body_nodes(fi.node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            for inner in ast.walk(node):
                if isinstance(inner, ast.Name) and inner.id in builds:
                    builds[inner.id].escaped = True
    # Bound EXACTLY once means once across EVERY store of the name,
    # not just dict-literal ones: `reply = cached(); if x: reply =
    # {...}; return reply` must not pass off the literal branch alone.
    store_counts: Dict[str, int] = {}
    for node in body_nodes(fi.node):
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, (ast.Store, ast.Del)):
            store_counts[node.id] = store_counts.get(node.id, 0) + 1
    return {name: rec for name, rec in builds.items()
            if rec.binds == 1 and store_counts.get(name, 0) == 1
            and not rec.escaped}


def _stub_ctor_binds(fi, program) -> Dict[str, object]:
    """Names bound exactly once to a stub constructor — ``rep =
    XReply(...); ...; return rep.to_header()``."""
    if program is None:
        return {}
    binds: Dict[str, list] = {}
    for node in body_nodes(fi.node):
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, (ast.Store, ast.Del)):
            binds.setdefault(node.id, []).append(None)
    out: Dict[str, object] = {}
    for node in body_nodes(fi.node):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            continue
        name = node.targets[0].id
        if len(binds.get(name, ())) != 1:
            continue
        dotted = dotted_name(node.value.func)
        stub = program.stub_class(dotted.rsplit(".", 1)[-1])
        if stub is not None:
            out[name] = stub
    return out


def _infer_reply(fi, program=None):
    """(keys, guaranteed, open) over the handler's own ``return``
    statements. Contributors, per return path:

    * ``return {...}`` / ``return {...}, bufs`` literals;
    * ``return X(...).to_header()`` (directly or through a name bound
      once to the constructor) where X is a generated protocol stub:
      the stub's required set is guaranteed, required+optional are the
      producible keys;
    * ``return reply`` where ``reply`` is a provably local
      incrementally-built dict (``reply = {}; reply["k"] = v``);
    * a bare/None return contributes none (guaranteed drops to the
      empty set).

    Anything else — a forwarded argument, a Future from a sync
    fast-path handler — marks the reply OPEN and callers' reply-key
    reads are out of scope for this method."""
    keys: Set[str] = set()
    guaranteed: Optional[Set[str]] = None
    open_ = False
    inc = _incremental_dicts(fi)
    ctor_binds = _stub_ctor_binds(fi, program)
    for node in body_nodes(fi.node):
        if not isinstance(node, ast.Return):
            continue
        value = _return_value(node)
        if value is None or (isinstance(value, ast.Constant) and
                             value.value is None):
            guaranteed = set()
            continue
        if isinstance(value, ast.Call) and \
                isinstance(value.func, ast.Attribute) and \
                value.func.attr == "to_header" and not value.args:
            inner = value.func.value
            stub = None
            if isinstance(inner, ast.Call):
                dotted = dotted_name(inner.func)
                stub = program.stub_class(dotted.rsplit(".", 1)[-1]) \
                    if program is not None else None
            elif isinstance(inner, ast.Name):
                stub = ctor_binds.get(inner.id)
            if stub is not None:
                keys |= stub.required | stub.optional
                g = set(stub.required)
                guaranteed = g if guaranteed is None else guaranteed & g
                if stub.open:
                    open_ = True
                continue
            open_ = True
            continue
        if isinstance(value, ast.Dict) and all(
                isinstance(k, ast.Constant) and isinstance(k.value, str)
                for k in value.keys):
            ks = {k.value for k in value.keys}
            keys |= ks
            guaranteed = ks if guaranteed is None else guaranteed & ks
        elif isinstance(value, ast.Name) and value.id in inc:
            rec = inc[value.id]
            keys |= rec.keys
            guaranteed = set(rec.guaranteed) if guaranteed is None \
                else guaranteed & rec.guaranteed
            if rec.open:
                open_ = True
        else:
            open_ = True
    return keys, guaranteed or set(), open_


def infer_schemas(program) -> Dict[str, MethodSchema]:
    """Per-method schemas over every registration in the program (also
    the `--dump-schemas` backend). Memoized on the Program — the rule's
    finalize pass, the JSON reporter, and bench.py all read one table
    instead of re-walking every handler body."""
    cached = getattr(program, "_schema_cache", None)
    if cached is not None:
        return cached
    out: Dict[str, MethodSchema] = {}
    for method, regs in program.rpc.registrations.items():
        seen = set()
        handlers: List[HandlerSchema] = []
        for reg in regs:
            fi = reg.handler
            if fi is None:
                continue
            key = (fi.path, fi.qualname)
            if key in seen:
                continue
            seen.add(key)
            handlers.append(infer_handler_schema(fi, program))
        if handlers:
            out[method] = MethodSchema(method, handlers)
    program._schema_cache = out
    return out


def schemas_as_dict(program) -> dict:
    """JSON-friendly dump of the inferred contract. Every collection is
    sorted and every value is plain JSON so two runs over the same tree
    — whatever the hash seed or argument order — emit byte-identical
    output; the schemagen drift gate diffs this table against its
    checked-in golden."""
    out = {}
    for method, ms in sorted(infer_schemas(program).items()):
        out[method] = {
            "required": sorted(ms.required),
            "optional": sorted(ms.known - ms.required),
            "closed": ms.closed,
            "compat_defaults": {k: ms.compat_defaults[k]
                                for k in sorted(ms.compat_defaults)},
            "reply": sorted(ms.reply_keys),
            "reply_guaranteed": sorted(ms.reply_guaranteed),
            "reply_open": ms.reply_open,
            "handlers": sorted(
                f"{h.fi.path}:{h.fi.node.lineno}:{h.fi.qualname}"
                for h in ms.handlers),
        }
    return out


def _unwrap_reply_call(node: ast.AST) -> Optional[ast.Call]:
    """The client Call whose reply tuple an expression evaluates to,
    seen through ``await`` and the known sync/timeout bridges — or None
    when the value is not provably a reply."""
    while True:
        if isinstance(node, ast.Await):
            node = node.value
            continue
        if isinstance(node, ast.Call):
            term = dotted_name(node.func).rsplit(".", 1)[-1]
            if term in _REPLYING:
                return node
            if term in _BRIDGES and node.args:
                node = node.args[0]
                continue
        return None


def _reply_read_events(fi):
    """Sorted (line, col, prio, kind, name, payload) events for one
    function: reply-tuple bindings, rebindings of the same names, and
    constant-key subscript loads, in linear source order. A read that
    precedes every binding (loop carry) simply goes unchecked —
    conservative."""
    binds = {}                       # id(Name node) -> method string
    for node in body_nodes(fi.node):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        target = node.targets[0]
        if isinstance(target, ast.Tuple) and len(target.elts) == 2 and \
                isinstance(target.elts[0], ast.Name):
            call = _unwrap_reply_call(node.value)
            if call is not None:
                method = first_str_arg(call)
                if method is not None:
                    binds[id(target.elts[0])] = method
    if not binds:
        return []                    # no reply in scope: skip the scan
    events = []
    for node in body_nodes(fi.node):
        if isinstance(node, ast.Name) and \
                isinstance(node.ctx, (ast.Store, ast.Del)):
            if id(node) in binds:
                events.append((node.lineno, node.col_offset, 0,
                               "bind", node.id, binds[id(node)]))
            else:
                events.append((node.lineno, node.col_offset, 0,
                               "kill", node.id, None))
        elif isinstance(node, ast.Subscript) and \
                isinstance(node.value, ast.Name) and \
                isinstance(node.ctx, ast.Load) and \
                isinstance(node.slice, ast.Constant) and \
                isinstance(node.slice.value, str):
            events.append((node.lineno, node.col_offset, 1,
                           "read", node.value.id, node.slice.value))
    events.sort(key=lambda e: (e[0], e[1], e[2]))
    return events


def _literal_keys(header: ast.AST) -> Optional[Set[str]]:
    """Key set of a dict literal, or None when not statically knowable
    (non-dict, `**spread`, computed keys)."""
    if not isinstance(header, ast.Dict):
        return None
    keys: Set[str] = set()
    for k in header.keys:
        if k is None:                        # {**spread}
            return None
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            return None
        keys.add(k.value)
    return keys


@register
class RpcSchemaRule(Rule):
    name = "rpc-schema"
    description = ("client header dicts must satisfy the key schema "
                   "inferred from the registered handlers' bodies")

    def __init__(self):
        self._program = None

    def setup(self, program) -> None:
        self._program = program

    def finalize(self) -> Iterable[Violation]:
        out: List[Violation] = []
        if self._program is None:
            return out
        rpc = self._program.rpc
        schemas = infer_schemas(self._program)

        seen_handlers = set()
        for method, regs in rpc.registrations.items():
            for reg in regs:
                if reg.provably_missing:
                    out.append(Violation(
                        self.name, reg.path, reg.lineno, reg.col,
                        f'"{method}" is registered to `{reg.value_desc}` '
                        f"but no class in the scanned tree defines that "
                        f"method — dispatch raises AttributeError at "
                        f"registration time"))
                fi = reg.handler
                if fi is None or (fi.path, fi.qualname) in seen_handlers:
                    continue
                seen_handlers.add((fi.path, fi.qualname))
                pos = fi.positional_params()
                min_req = len(pos) - len(fi.node.args.defaults)
                if min_req > 3 or (len(pos) < 3 and not fi.has_var_pos):
                    out.append(Violation(
                        self.name, fi.path, fi.node.lineno,
                        fi.node.col_offset,
                        f"handler `{fi.qualname}` for \"{method}\" takes "
                        f"{len(pos)} non-self positional arg(s); dispatch "
                        f"always calls it with (conn, header, bufs)"))

        for cc in rpc.client_calls:
            ms = schemas.get(cc.method)
            if ms is None:
                continue                     # existence is rpc-contract's job
            required = ms.required
            if cc.header is None or (isinstance(cc.header, ast.Constant)
                                     and cc.header.value is None):
                if required:
                    out.append(Violation(
                        self.name, cc.path, cc.lineno, cc.col,
                        f'`{cc.kind}("{cc.method}")` sends no header but '
                        f"the handler ({ms.where()}) requires key(s) "
                        f"{_fmt(required)} — the handler raises TypeError "
                        f"subscripting None"))
                continue
            keys = _literal_keys(cc.header)
            if keys is None:
                continue                     # dynamic header: out of scope
            missing = required - keys
            if missing:
                out.append(Violation(
                    self.name, cc.path, cc.lineno, cc.col,
                    f'`{cc.kind}("{cc.method}", {{...}})` is missing '
                    f"required header key(s) {_fmt(missing)} — the "
                    f"handler ({ms.where()}) raises KeyError at runtime"))
            if ms.closed:
                unknown = keys - ms.known
                for k in sorted(unknown):
                    hint = difflib.get_close_matches(k, ms.known, n=1)
                    suggest = f' (did you mean "{hint[0]}"?)' if hint else ""
                    out.append(Violation(
                        self.name, cc.path, cc.lineno, cc.col,
                        f'`{cc.kind}("{cc.method}", {{...}})` sends key '
                        f'"{k}" that no handler ({ms.where()}) ever reads'
                        f"{suggest} — a typo'd key silently drops the "
                        f"field on the floor"))

        out.extend(self._reply_read_violations(schemas))
        return out

    def _reply_read_violations(self, schemas) -> List[Violation]:
        """``reply["k"]`` reads of keys no return path produces, through
        ``reply, bufs = await conn.call(...)`` tuple bindings.

        A name bound from several reply calls (one per branch of an
        ``if``/``try``) is checked against the UNION of those methods'
        reply keys — linear source order cannot tell which branch ran,
        so a key any of them can produce passes. Rebinding to a
        non-reply value kills checking for the name from that point on.
        """
        out: List[Violation] = []
        for fi in self._program.functions.values():
            events = _reply_read_events(fi)
            name_methods: Dict[str, Set[str]] = {}
            for _, _, _, kind, name, payload in events:
                if kind == "bind":
                    name_methods.setdefault(name, set()).add(payload)
            bound: Set[str] = set()
            for lineno, col, _prio, kind, name, payload in events:
                if kind == "bind":
                    bound.add(name)
                elif kind == "kill":
                    bound.discard(name)
                else:
                    if name not in bound:
                        continue
                    mss = [schemas.get(m) for m in name_methods[name]]
                    if any(ms is None or ms.reply_open for ms in mss):
                        continue
                    keys = set().union(*(ms.reply_keys for ms in mss))
                    if payload in keys:
                        continue
                    methods = ", ".join(
                        f'"{m}"' for m in sorted(name_methods[name]))
                    where = "; ".join(ms.where() for ms in mss)
                    hint = difflib.get_close_matches(payload, keys, n=1)
                    suggest = f' (did you mean "{hint[0]}"?)' \
                        if hint else ""
                    out.append(Violation(
                        self.name, fi.path, lineno, col,
                        f'`{name}["{payload}"]` reads a reply key no '
                        f"return path of {methods} ({where}) ever "
                        f"produces{suggest} — a guaranteed KeyError "
                        f"when the reply lands"))
        return out


def _fmt(keys: Set[str]) -> str:
    return ", ".join(f'"{k}"' for k in sorted(keys))
