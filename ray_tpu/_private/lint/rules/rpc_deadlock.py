"""rpc-deadlock: synchronous RPC wait cycles between single-threaded loops.

Every component (gcs / raylet / worker / executor) is ONE asyncio
loop. When a handler for method M on component A synchronously awaits
``conn.call(M2)`` whose handler lives on component B, A's task blocks
on B's loop. A cycle of such edges is a distributed deadlock armed by
load: once every loop in the cycle is busy waiting on the next, no
reply can ever be produced (the classic reason Ray's core keeps
cross-component acks one-way or bounded).

The rule builds the cross-process **wait-for graph** from the RPC
index: nodes are ``component:Method`` handlers, and there is an edge
``A:M -> B:M2`` when M's handler — or anything it calls through
resolved call-graph edges, up to 3 hops — awaits a ``call``/
``_gcs_call`` for M2 handled on a different component. One-way sends
(``push``/``*_nowait``) never block, so they create no edge. An edge
is **bounded** when every contributing call site carries a timeout
(``timeout=`` on the call or an enclosing ``asyncio.wait_for``); a
bounded leg eventually unwinds the cycle, which is how an existing
cycle is *proven safe* (the raylet→owner ``WorkerOOMKilled`` ack is
exactly this: 1 s timeout, grant path re-validates afterwards).

Flags every cycle whose legs are ALL unbounded — fix by bounding one
leg with a timeout, or turning one leg into a one-way push. The full
graph ships in the ci/lint.sh JSON artifact (``rpc_wait_for_graph``)
next to ``rpc_schemas``.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Tuple

from ray_tpu._private.lint.engine import Module, Rule, Violation, register

# handler class -> process the handler's loop runs in
COMPONENTS = {
    "Raylet": "raylet",
    "CoreWorker": "worker",
    "GcsServer": "gcs",
    "TaskExecutor": "executor",
}

WAITING_KINDS = {"call", "_gcs_call"}
MAX_HOPS = 3
MAX_CYCLE_LEN = 8


def _component(fi) -> str:
    if fi.class_name:
        return COMPONENTS.get(fi.class_name, fi.class_name.lower())
    base = fi.path.replace("\\", "/").rsplit("/", 1)[-1]
    return base[:-3] if base.endswith(".py") else base


def _reach(fi, depth: int, visited: Dict[Tuple[str, str], int]):
    key = (fi.path, fi.qualname)
    if visited.get(key, 0) >= depth:
        return
    visited[key] = depth
    if depth > 1:
        for node, callee in fi.calls:
            if id(node) in fi.spawned_calls:
                continue    # detached task: the handler does not wait
            _reach(callee, depth - 1, visited)


def build_wait_graph(program) -> List[dict]:
    """Every cross-component synchronous-wait edge, deterministic
    order. Edge: {from_component, from_method, to_component,
    to_method, via, path, line, bounded}. ``bounded`` is True only
    when EVERY contributing call site is bounded; path/line anchor an
    unbounded site when one exists.

    Roots are (a) every registered handler — cycle-capable nodes named
    by their method — and (b) every spawned background task (the
    callee of a ``create_task``-style edge), named ``task:<qualname>``
    and attributed to the SPAWNER's loop. Task roots have no incoming
    edges, so they can never fabricate a cycle, but their waits (the
    raylet→owner ``WorkerOOMKilled`` ack, the owner→raylet lease
    request) belong in the artifact — each is one bounded-or-not leg
    a reviewer must be able to audit."""
    calls_by_fn: Dict[Tuple[str, str], list] = {}
    for cc in program.rpc.client_calls:
        if cc.in_function is None or not cc.awaited or \
                cc.kind not in WAITING_KINDS:
            continue
        key = (cc.in_function.path, cc.in_function.qualname)
        calls_by_fn.setdefault(key, []).append(cc)

    roots = []
    for method in sorted(program.rpc.registrations):
        for reg in program.rpc.registrations[method]:
            if reg.handler is not None:
                roots.append((method, _component(reg.handler),
                              reg.handler))
    seen_tasks = set()
    for fi in sorted(program.functions.values(),
                     key=lambda f: (f.path, f.qualname)):
        for node, callee in fi.calls:
            if id(node) not in fi.spawned_calls:
                continue
            tkey = (callee.path, callee.qualname)
            if tkey in seen_tasks:
                continue
            seen_tasks.add(tkey)
            roots.append((f"task:{callee.qualname}", _component(fi),
                          callee))

    edges: Dict[Tuple[str, str, str, str], dict] = {}
    for label, comp, root_fi in roots:
        visited: Dict[Tuple[str, str], int] = {}
        _reach(root_fi, MAX_HOPS + 1, visited)
        for key in visited:
            for cc in calls_by_fn.get(key, []):
                for treg in program.rpc.registrations.get(
                        cc.method, []):
                    if treg.handler is None:
                        continue
                    tcomp = _component(treg.handler)
                    if tcomp == comp:
                        continue
                    ekey = (comp, label, tcomp, cc.method)
                    e = edges.get(ekey)
                    if e is None:
                        edges[ekey] = {
                            "from_component": comp,
                            "from_method": label,
                            "to_component": tcomp,
                            "to_method": cc.method,
                            "via": key[1],
                            "path": cc.path,
                            "line": cc.lineno,
                            "bounded": bool(cc.bounded),
                        }
                    elif e["bounded"] and not cc.bounded:
                        # one unbounded site makes the edge
                        # unbounded; anchor it there
                        e.update(bounded=False, via=key[1],
                                 path=cc.path, line=cc.lineno)
    return [edges[k] for k in sorted(edges)]


def find_cycles(edge_list: List[dict]) -> List[List[dict]]:
    """Elementary cycles over ``component:method`` nodes, each
    returned as its edge list rotated so the smallest node leads."""
    by_node: Dict[Tuple[str, str], List[dict]] = {}
    for e in edge_list:
        by_node.setdefault(
            (e["from_component"], e["from_method"]), []).append(e)
    cycles: List[List[dict]] = []
    for start in sorted(by_node):
        stack: List[Tuple[Tuple[str, str], List[dict]]] = [(start, [])]
        while stack:
            cur, trail = stack.pop()
            for e in by_node.get(cur, []):
                nxt = (e["to_component"], e["to_method"])
                if nxt == start:
                    cycles.append(trail + [e])
                elif nxt > start and len(trail) < MAX_CYCLE_LEN and \
                        all((t["from_component"], t["from_method"])
                            != nxt for t in trail):
                    stack.append((nxt, trail + [e]))
    return cycles


def wait_graph_report(program) -> dict:
    """The JSON-artifact payload: the full edge list plus every cycle
    with its safety verdict."""
    edge_list = build_wait_graph(program)
    cycles = []
    for cyc in find_cycles(edge_list):
        cycles.append({
            "members": [f'{e["from_component"]}:{e["from_method"]}'
                        for e in cyc],
            "bounded": any(e["bounded"] for e in cyc),
        })
    return {"edges": edge_list, "cycles": cycles}


@register
class RpcDeadlockRule(Rule):
    name = "rpc-deadlock"
    description = ("cycles in the cross-process RPC wait-for graph "
                   "where every leg is an unbounded synchronous await "
                   "— a distributed deadlock armed by load")

    def __init__(self):
        self._program = None

    def setup(self, program) -> None:
        self._program = program

    def collect(self, module: Module) -> Iterable[Violation]:
        return ()

    def finalize(self) -> Iterable[Violation]:
        if self._program is None:
            return ()
        out: List[Violation] = []
        edge_list = build_wait_graph(self._program)
        for cyc in find_cycles(edge_list):
            if any(e["bounded"] for e in cyc):
                continue        # a bounded leg unwinds the cycle
            chain = " -> ".join(
                [f'{e["from_component"]}:{e["from_method"]}'
                 for e in cyc] +
                [f'{cyc[0]["from_component"]}:{cyc[0]["from_method"]}'])
            anchor = cyc[0]
            out.append(Violation(
                self.name, anchor["path"], anchor["line"], 0,
                f"synchronous RPC wait cycle {chain}: every leg is an "
                f"unbounded await between single-threaded loops — "
                f"bound one leg (call(..., timeout=...) or "
                f"asyncio.wait_for) or make one leg a one-way push"))
        return out
