"""exception-hygiene: no silent error-swallowing on control-plane paths.

Ownership bugs hide behind ``except: pass``: a failed location update
or dropped borrower registration surfaces hours later as an object
"lost" with no trail. On ``_private/`` (the control plane) this rule
rejects:

  * bare ``except:`` — catches SystemExit/KeyboardInterrupt too;
  * ``except Exception:`` / ``except BaseException:`` (alone or in a
    tuple) whose body is only ``pass``/``...`` — swallow-with-no-trace.

Catching Exception and logging (or re-raising, or replying with the
error) is fine; catching NARROW exception types with ``pass`` is fine
(e.g. ``except FileNotFoundError: pass``). Genuinely-benign broad
swallows carry a pragma with the reason.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ray_tpu._private.lint.engine import (
    Module, Rule, Violation, dotted_name, register,
)

_BROAD = {"Exception", "BaseException"}


def _is_broad(type_node: ast.AST) -> bool:
    if type_node is None:
        return True
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(e) for e in type_node.elts)
    return dotted_name(type_node).rsplit(".", 1)[-1] in _BROAD


def _is_silent(body: List[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Constant) and \
                stmt.value.value is ...:
            continue
        return False
    return True


@register
class ExceptionHygieneRule(Rule):
    name = "exception-hygiene"
    description = ("bare `except:` and silent `except Exception: pass` "
                   "on _private/ control-plane paths")

    def collect(self, module: Module) -> Iterable[Violation]:
        if "_private" not in module.path.replace("\\", "/"):
            return ()
        out: List[Violation] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                out.append(Violation(
                    self.name, module.path, node.lineno, node.col_offset,
                    "bare `except:` catches SystemExit/KeyboardInterrupt "
                    "— name the exception types"))
            elif _is_broad(node.type) and _is_silent(node.body):
                out.append(Violation(
                    self.name, module.path, node.lineno, node.col_offset,
                    "`except Exception: pass` silently swallows control-"
                    "plane errors — log, reply, or narrow the type"))
        return out
