"""protocol-stub: generated methods must speak through their stubs.

schemagen promotes the inferred RPC schemas to source of truth by
emitting typed stubs into ``_private/protocol.py``. This rule is the
migration ratchet and the stub-usage checker that keeps them honest:

* a client call to a GENERATED method (one with a request stub) that
  still passes a **literal header dict** is flagged — the stub exists
  precisely so those dicts are deleted, and a literal dict silently
  bypasses the constructor's required-field enforcement. Dynamic
  headers (a forwarded variable, ``stub.to_header()``) pass.
* a **stub constructor call** with keyword arguments is checked against
  the class's declared schema: an unknown field (typo — the value would
  be dropped on the floor at runtime by ``TypeError``, or worse survive
  a ``**``-forwarding refactor) and a missing required field are both
  reported at the call site, with did-you-mean hints. Positional
  arguments are flagged too: generated ``__init__`` is keyword-only.

The generated-method set is discovered from the scanned tree itself
(classes with the schemagen stub shape — see callgraph.StubClassInfo),
so fixture trees without stubs are naturally out of scope and the rule
needs no knowledge of where protocol.py lives.
"""

from __future__ import annotations

import ast
import difflib
from typing import Dict, Iterable, List

from ray_tpu._private.lint.engine import (
    Module, Rule, Violation, dotted_name, register,
)
from ray_tpu._private.lint.rules.rpc_schema import _literal_keys


@register
class ProtocolStubRule(Rule):
    name = "protocol-stub"
    description = ("calls to schemagen-generated methods must use the "
                   "typed protocol stubs, and stub constructors must "
                   "match the declared schema")

    def __init__(self):
        self._program = None
        self._by_method: Dict[str, object] = {}
        self._by_class: Dict[str, object] = {}

    def setup(self, program) -> None:
        self._program = program
        for info in program.stub_classes():
            if info.method and info.kind == "request":
                self._by_method[info.method] = info
            if info.method:
                self._by_class[info.name] = info

    def collect(self, module: Module) -> Iterable[Violation]:
        if not self._by_class or module.tree is None:
            return ()
        out: List[Violation] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            cls_name = dotted_name(node.func).rsplit(".", 1)[-1]
            info = self._by_class.get(cls_name)
            if info is None or module.path == info.path:
                continue      # protocol.py itself never constructs stubs
            out.extend(self._check_ctor(module, node, info))
        return out

    def _check_ctor(self, module: Module, node: ast.Call,
                    info) -> Iterable[Violation]:
        out: List[Violation] = []
        if node.args:
            out.append(Violation(
                self.name, module.path, node.lineno, node.col_offset,
                f"`{info.name}(...)` takes keyword-only field "
                f"arguments — positional args raise TypeError at "
                f"runtime"))
        present = set()
        has_spread = False
        for kw in node.keywords:
            if kw.arg is None:
                has_spread = True          # **kwargs: fields unknowable
                continue
            present.add(kw.arg)
            if kw.arg not in info.known:
                hint = difflib.get_close_matches(kw.arg, info.known, n=1)
                suggest = f' (did you mean "{hint[0]}"?)' if hint else ""
                out.append(Violation(
                    self.name, module.path, node.lineno, node.col_offset,
                    f'`{info.name}(...)` sets unknown field '
                    f'"{kw.arg}"{suggest} — the generated schema for '
                    f'"{info.method}" does not declare it'))
        missing = info.required - present
        if missing and not has_spread and not node.args:
            keys = ", ".join(f'"{k}"' for k in sorted(missing))
            out.append(Violation(
                self.name, module.path, node.lineno, node.col_offset,
                f"`{info.name}(...)` omits required field(s) {keys} — "
                f"encode is strict even for keys with decode-side "
                f"compat defaults"))
        return out

    def finalize(self) -> Iterable[Violation]:
        out: List[Violation] = []
        if self._program is None or not self._by_method:
            return out
        for cc in self._program.rpc.client_calls:
            info = self._by_method.get(cc.method)
            if info is None or cc.header is None:
                continue
            if _literal_keys(cc.header) is None and \
                    not isinstance(cc.header, ast.Dict):
                continue                   # dynamic header: stub output
            out.append(Violation(
                self.name, cc.path, cc.lineno, cc.col,
                f'`{cc.kind}("{cc.method}", {{...}})` passes a literal '
                f"header dict to a generated method — construct "
                f"protocol.{info.name}(...) and send .to_header() "
                f"instead (stubs are the schema source of truth; see "
                f"_private/protocol.py)"))
        return out
