"""orphan-task: fire-and-forget tasks must be tracked.

asyncio keeps only a WEAK reference to running tasks: a task whose
handle is dropped can be garbage-collected mid-flight, silently
abandoning the work, and any exception it raises is swallowed with no
log line and no metric (Python only mutters "Task exception was never
retrieved" at GC time, often long after the cause). On a control plane
where spawned tasks carry lease grants, pubsub pushes, and OOM-kill
acks, a dropped spawn is a correctness bug twice over.

Flags ``create_task(...)`` / ``ensure_future(...)`` whose result is
discarded — i.e. the call is the whole expression statement. Handled
shapes are NOT flagged:

  * bound:          ``t = loop.create_task(c)``
  * awaited:        ``await asyncio.create_task(c)``
  * stored:         ``self._tasks.add(loop.create_task(c))`` or passed
                    as any argument
  * chained:        ``loop.create_task(c).add_done_callback(f)``
  * sanctioned:     ``rpc.spawn_logged(c, what="...")`` — the tracked
                    helper that holds a strong ref and logs + counts
                    the exception via the metrics registry.

The fix for a legit fire-and-forget is ``rpc.spawn_logged``; a spawn
whose exception is provably impossible or handled elsewhere carries a
pragma with the reason.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ray_tpu._private.lint.engine import (
    Module, Rule, Violation, dotted_name, register,
)

SPAWNERS = {"create_task", "ensure_future"}


@register
class OrphanTaskRule(Rule):
    name = "orphan-task"
    description = ("create_task/ensure_future results dropped on the "
                   "floor — the task can be GC'd mid-flight and its "
                   "exception is swallowed; route through "
                   "rpc.spawn_logged or track the handle")

    def collect(self, module: Module) -> Iterable[Violation]:
        path = module.path.replace("\\", "/")
        if "/tests/" in path or path.startswith("tests/"):
            return ()
        out: List[Violation] = []
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Expr) and
                    isinstance(node.value, ast.Call)):
                continue
            call = node.value
            name = dotted_name(call.func).rsplit(".", 1)[-1]
            if name not in SPAWNERS:
                continue
            out.append(Violation(
                self.name, module.path, call.lineno, call.col_offset,
                f"`{name}` result dropped: the task holds no strong "
                "reference and its exception is swallowed — bind and "
                "track the handle, or use rpc.spawn_logged(coro, what) "
                "for fire-and-forget"))
        return out
