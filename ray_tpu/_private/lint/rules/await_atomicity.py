"""await-atomicity: decisions about shared state must not span awaits.

A single-threaded asyncio loop interleaves ONLY at await points — the
whole reason the control plane can mutate ``self.`` state without
locks. The flip side: any check-then-act or read-modify-write on
``self.`` state that spans an ``await`` is a race. Another task runs
during the suspension, the checked value is stale by the time the act
lands, and the bug reproduces only under concurrency (double worker
starts, duplicate pulls, lost counter updates).

Two flagged shapes, inside ``async def`` methods on ``_private/``:

1. **check-then-act** — an ``if``/``while`` test reads ``self.A``, and
   the guarded suite writes ``self.A`` (assignment or subscript store)
   after an ``await``. Includes TRANSITIVE writes: a call after the
   await whose callee (same class / same module, up to 3 resolved
   hops) performs the write.
2. **stale read-modify-write** — ``v = self.A``, an ``await``, then
   ``self.A = <expr using v>``: a lost update for every task that
   wrote ``self.A`` during the suspension.

Sanctioned idioms, recognized as safe:

  * **re-sample after await** — any read of ``self.A`` between the
    last await and the write re-bases the decision on fresh state; a
    TRANSITIVE writer that itself reads the attribute (a reconnect
    helper checking the live connection before replacing it) counts
    as a callee-side re-sample;
  * **lock-guarded sections** — the whole sequence inside one
    ``async with`` over a lock/Condition/Semaphore (name containing
    lock/cond/sem/mutex): mutators serialize on the lock;
  * **single-assignment latch** — writing a constant
    (``self._broken = True``): last-writer-wins is idempotent;
  * **augmented writes** (``self.A += x``) are never the *act* of
    shape 1: they re-read at write time (still flagged as shape 2
    when based on a stale bound read — they aren't, by construction).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Set, Tuple

from ray_tpu._private.lint.engine import (
    Module, Rule, Violation, body_nodes, dotted_name, register,
    walk_functions,
)

_LOCKISH = re.compile(r"lock|cond|sem|mutex", re.IGNORECASE)


def _block_range(stmts) -> Tuple[int, int]:
    return (stmts[0].lineno,
            max(getattr(s, "end_lineno", None) or s.lineno for s in stmts))


def _self_attr(node: ast.AST) -> str:
    """'A' when node is exactly ``self.A``, else ''."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return ""


@register
class AwaitAtomicityRule(Rule):
    name = "await-atomicity"
    description = ("check-then-act / read-modify-write on shared self. "
                   "state spanning an await (incl. transitive writes "
                   "through resolved self/module calls): the checked "
                   "value is stale after the suspension")

    def __init__(self):
        self._program = None
        self._direct_cache: Dict[int, Dict[str, Set[str]]] = {}
        self._read_cache: Dict[int, Set[str]] = {}

    def setup(self, program) -> None:
        self._program = program
        self._direct_cache = {}
        self._read_cache = {}

    def collect(self, module: Module) -> Iterable[Violation]:
        if "_private" not in module.path.replace("\\", "/"):
            return ()
        out: List[Violation] = []
        for func, qualname, cls in walk_functions(module.tree):
            if not isinstance(func, ast.AsyncFunctionDef):
                continue
            args = func.args.posonlyargs + func.args.args
            if not (cls and args and args[0].arg == "self"):
                continue
            self._check_function(module, func, qualname, out)
        return out

    def _check_function(self, module, func, qualname, out):
        nodes = list(body_nodes(func))
        await_lines = sorted(n.lineno for n in nodes
                             if isinstance(n, ast.Await))
        if not await_lines:
            return

        # ids of nodes that sit inside an assignment TARGET — the
        # self.A inside `self.A[k] = v` is ctx=Load but is the store,
        # not a re-sample
        target_ids: Set[int] = set()
        writes: List[Tuple[int, str, str, ast.AST]] = []
        binds: List[Tuple[int, str, str]] = []
        for n in nodes:
            if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                for t in targets:
                    for sub in ast.walk(t):
                        target_ids.add(id(sub))
            if isinstance(n, ast.Assign):
                for t in targets:
                    for el in (t.elts if isinstance(t, (ast.Tuple,
                                                        ast.List))
                               else [t]):
                        attr = _self_attr(el)
                        if attr:
                            writes.append((n.lineno, attr, "assign",
                                           n.value))
                        elif isinstance(el, ast.Subscript):
                            attr = _self_attr(el.value)
                            if attr:
                                writes.append((n.lineno, attr, "sub",
                                               n.value))
                if len(n.targets) == 1 and \
                        isinstance(n.targets[0], ast.Name):
                    attr = _self_attr(n.value)
                    if attr:
                        binds.append((n.lineno, n.targets[0].id, attr))

        reads: Dict[str, List[int]] = {}
        for n in nodes:
            attr = _self_attr(n)
            if attr and isinstance(n.ctx, ast.Load) and \
                    id(n) not in target_ids:
                reads.setdefault(attr, []).append(n.lineno)

        lock_ranges: List[Tuple[int, int]] = []
        for n in nodes:
            if isinstance(n, ast.AsyncWith) and any(
                    _LOCKISH.search(dotted_name(it.context_expr))
                    for it in n.items):
                lock_ranges.append(
                    (n.lineno, getattr(n, "end_lineno", None) or n.lineno))

        def locked(*lines) -> bool:
            return any(all(a <= ln <= b for ln in lines)
                       for a, b in lock_ranges)

        def resampled(attr, last_await, wl) -> bool:
            return any(last_await < r <= wl
                       for r in reads.get(attr, []))

        fi = None
        if self._program is not None:
            fi = self._program.functions.get((module.path, qualname))

        flagged: Set[Tuple[int, str]] = set()

        # ---- shape 1: check-then-act -------------------------------
        for stmt in nodes:
            if not isinstance(stmt, (ast.If, ast.While)):
                continue
            test_attrs = {a for a in (
                _self_attr(n) for n in ast.walk(stmt.test)) if a}
            if not test_attrs or locked(stmt.lineno):
                continue
            b_start, b_end = _block_range(stmt.body)
            for wl, attr, kind, value in writes:
                if attr not in test_attrs or not (b_start <= wl <= b_end):
                    continue
                if kind == "assign" and isinstance(value, ast.Constant):
                    continue    # single-assignment latch
                between = [a for a in await_lines
                           if stmt.lineno < a <= wl]
                if not between or resampled(attr, max(between), wl) or \
                        (wl, attr) in flagged:
                    continue
                flagged.add((wl, attr))
                out.append(Violation(
                    self.name, module.path, wl, 0,
                    f"`self.{attr}` checked at line {stmt.lineno} in "
                    f"`{qualname}` but written here after an await "
                    f"(line {max(between)}): the check is stale by "
                    f"the time the write lands — re-sample after the "
                    f"await, hold one async lock across the section, "
                    f"or make this a constant latch"))
            if fi is None:
                continue
            for call_node, callee in fi.calls:
                cl = call_node.lineno
                if id(call_node) in fi.spawned_calls:
                    continue    # detached task, not this continuation
                if not (b_start <= cl <= b_end) or \
                        not (callee.class_name == fi.class_name or
                             callee.path == fi.path):
                    continue
                before = [a for a in await_lines
                          if stmt.lineno < a < cl]
                if not before:
                    continue
                wmap = self._writes_trans(callee, 3, {
                    (fi.path, fi.qualname): 99})
                reads_there = self._reads_trans(callee, 3, {
                    (fi.path, fi.qualname): 99})
                for attr in test_attrs:
                    kinds = wmap.get(attr, set())
                    if not (kinds & {"assign", "sub"}) or \
                            resampled(attr, max(before), cl) or \
                            attr in reads_there or \
                            (cl, attr) in flagged:
                        continue
                    flagged.add((cl, attr))
                    out.append(Violation(
                        self.name, module.path, cl, call_node.col_offset,
                        f"`self.{attr}` checked at line {stmt.lineno} "
                        f"in `{qualname}` but `{callee.qualname}` "
                        f"(called here, after the await at line "
                        f"{max(before)}) writes it: the check is "
                        f"stale — re-sample before the call or "
                        f"serialize the section"))

        # ---- shape 2: stale read-modify-write ----------------------
        for wl, attr, kind, value in writes:
            if kind != "assign":
                continue
            rhs_names = {n.id for n in ast.walk(value)
                         if isinstance(n, ast.Name)}
            for bl, var, battr in binds:
                if battr != attr or var not in rhs_names or bl >= wl:
                    continue
                between = [a for a in await_lines if bl < a <= wl]
                if not between or resampled(attr, max(between), wl) or \
                        locked(bl, wl) or (wl, attr) in flagged:
                    continue
                flagged.add((wl, attr))
                out.append(Violation(
                    self.name, module.path, wl, 0,
                    f"`{var} = self.{attr}` (line {bl}) in "
                    f"`{qualname}` is written back here across an "
                    f"await (line {max(between)}): every write to "
                    f"`self.{attr}` during the suspension is lost — "
                    f"re-read after the await or fold into one "
                    f"augmented/locked update"))

    # ------------------------------------------------- transitive writes

    def _writes_trans(self, fi, depth: int, visited: dict
                      ) -> Dict[str, Set[str]]:
        """self-attributes written by ``fi`` or same-class/same-module
        callees within ``depth`` hops: attr -> {'assign','sub','aug'}.
        Budget-keyed ``visited`` as in async-blocking."""
        key = (fi.path, fi.qualname)
        if visited.get(key, 0) >= depth:
            return {}
        visited[key] = depth
        agg = {a: set(k) for a, k in self._direct_writes(fi).items()}
        if depth > 1:
            for node, callee in fi.calls:
                if id(node) in fi.spawned_calls or \
                        not (callee.class_name == fi.class_name or
                             callee.path == fi.path):
                    continue
                for a, k in self._writes_trans(
                        callee, depth - 1, visited).items():
                    agg.setdefault(a, set()).update(k)
        return agg

    def _reads_trans(self, fi, depth: int, visited: dict) -> Set[str]:
        """self-attributes the callee closure READS — a callee that
        re-reads the attribute before acting has re-sampled it on the
        fresh side of the await (e.g. a reconnect helper that checks
        the live connection under its own lock before replacing it)."""
        key = (fi.path, fi.qualname)
        if visited.get(key, 0) >= depth:
            return set()
        visited[key] = depth
        agg = set(self._direct_reads(fi))
        if depth > 1:
            for node, callee in fi.calls:
                if id(node) in fi.spawned_calls or \
                        not (callee.class_name == fi.class_name or
                             callee.path == fi.path):
                    continue
                agg |= self._reads_trans(callee, depth - 1, visited)
        return agg

    def _direct_writes(self, fi) -> Dict[str, Set[str]]:
        cached = self._direct_cache.get(id(fi))
        if cached is not None:
            return cached
        out: Dict[str, Set[str]] = {}
        for n in body_nodes(fi.node):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    for el in (t.elts if isinstance(t, (ast.Tuple,
                                                        ast.List))
                               else [t]):
                        attr = _self_attr(el)
                        if attr:
                            out.setdefault(attr, set()).add("assign")
                        elif isinstance(el, ast.Subscript):
                            attr = _self_attr(el.value)
                            if attr:
                                out.setdefault(attr, set()).add("sub")
            elif isinstance(n, ast.AugAssign):
                attr = _self_attr(n.target)
                if attr:
                    out.setdefault(attr, set()).add("aug")
        self._direct_cache[id(fi)] = out
        return out

    def _direct_reads(self, fi) -> Set[str]:
        cached = self._read_cache.get(id(fi))
        if cached is not None:
            return cached
        out: Set[str] = set()
        for n in body_nodes(fi.node):
            attr = _self_attr(n)
            if attr and isinstance(n.ctx, ast.Load):
                out.add(attr)
        self._read_cache[id(fi)] = out
        return out
