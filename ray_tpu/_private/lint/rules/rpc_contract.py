"""rpc-contract: every client method string resolves to a handler.

RPC dispatch is string-keyed (rpc.py Connection.handlers): a renamed or
typo'd method name is not an AttributeError at import time — the caller
just hangs (requests) or logs a warning nobody reads (pushes) at
runtime. This rule cross-checks every client-side

    conn.call("Method", ...) / push / call_nowait / push_nowait
    core._gcs_call("Method", ...)     (the redial wrapper)

string-literal method against the union of handler registrations seen
anywhere in the scanned tree.

Since v2 both sides come from the shared call-graph substrate
(callgraph.Program's RPC index) — the same registration detection
(``RpcServer({...})``, ``handlers=`` kwargs, ``.handlers.update``,
dicts in ``*handlers*`` functions, ``handlers = {...}`` assignments)
also feeds rpc-schema's payload checking, so the two rules can never
disagree about what counts as a registration. Calls whose method is
not a string literal (generic forwarders like ``_gcs_call``'s own
body) are out of scope by construction. Scan whole packages:
registrations from one module satisfy calls from another.
"""

from __future__ import annotations

from typing import Iterable, List

from ray_tpu._private.lint.engine import Rule, Violation, register

# Re-exported for callers that treated this module as the source of
# truth for the client-side method-call spelling (tests, tooling).
from ray_tpu._private.lint.callgraph import CLIENT_METHODS  # noqa: F401


@register
class RpcContractRule(Rule):
    name = "rpc-contract"
    description = ("client call()/push() method strings must resolve to "
                   "a registered RPC handler somewhere in the package")

    def __init__(self):
        self._program = None

    def setup(self, program) -> None:
        self._program = program

    def finalize(self) -> Iterable[Violation]:
        out: List[Violation] = []
        if self._program is None:
            return out
        rpc = self._program.rpc
        registered = rpc.registered_methods
        if not registered:
            # Linting a lone client file: no server side in scope means
            # no contract to check, not a hundred false positives.
            return out
        for cc in rpc.client_calls:
            if cc.method not in registered:
                out.append(Violation(
                    self.name, cc.path, cc.lineno, cc.col,
                    f"`{cc.kind}(\"{cc.method}\", ...)` has no registered "
                    f"handler anywhere in the scanned tree — a renamed "
                    f"or typo'd RPC method hangs the caller at runtime"))
        return out
