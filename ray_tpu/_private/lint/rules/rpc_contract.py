"""rpc-contract: every client method string resolves to a handler.

RPC dispatch is string-keyed (rpc.py Connection.handlers): a renamed or
typo'd method name is not an AttributeError at import time — the caller
just hangs (requests) or logs a warning nobody reads (pushes) at
runtime. This rule cross-checks every client-side

    conn.call("Method", ...) / push / call_nowait / push_nowait
    core._gcs_call("Method", ...)     (the redial wrapper)

string-literal method against the union of handler registrations seen
anywhere in the scanned tree:

  * dict literal passed to ``RpcServer({...})``;
  * dict literal bound to a ``handlers=`` keyword (rpc.connect);
  * ``<x>.handlers.update({...})`` (task_executor worker services);
  * dict literal returned from / assigned inside a function whose name
    contains "handlers" (gcs/raylet/core_worker ``_handlers()``);
  * dict literal assigned to a variable named ``handlers``.

Calls whose method is not a string literal (generic forwarders like
``_gcs_call``'s own body) are out of scope by construction. Scan whole
packages: registrations from one module satisfy calls from another.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from ray_tpu._private.lint.engine import (
    Module, Rule, Violation, dotted_name, first_str_arg, register,
)

CLIENT_METHODS = {"call", "push", "call_nowait", "push_nowait", "_gcs_call"}


@register
class RpcContractRule(Rule):
    name = "rpc-contract"
    description = ("client call()/push() method strings must resolve to "
                   "a registered RPC handler somewhere in the package")

    def __init__(self):
        self.registered: Set[str] = set()
        # (method, path, line, col, kind)
        self.client_refs: List[Tuple[str, str, int, int, str]] = []

    def collect(self, module: Module) -> Iterable[Violation]:
        parents: Dict[int, ast.AST] = {}
        for node in ast.walk(module.tree):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Dict):
                if self._is_registration(node, parents):
                    for key in node.keys:
                        if isinstance(key, ast.Constant) and \
                                isinstance(key.value, str):
                            self.registered.add(key.value)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in CLIENT_METHODS:
                method = first_str_arg(node)
                if method is not None:
                    self.client_refs.append(
                        (method, module.path, node.lineno,
                         node.col_offset, node.func.attr))
        return ()

    def _is_registration(self, node: ast.Dict, parents) -> bool:
        parent = parents.get(id(node))
        # RpcServer({...}) positional / any f(handlers={...}) keyword
        if isinstance(parent, ast.Call):
            func_name = dotted_name(parent.func)
            if func_name.rsplit(".", 1)[-1] == "RpcServer" and \
                    parent.args and parent.args[0] is node:
                return True
            for kw in parent.keywords:
                if kw.arg == "handlers" and kw.value is node:
                    return True
            # <x>.handlers.update({...})
            if isinstance(parent.func, ast.Attribute) and \
                    parent.func.attr == "update" and \
                    dotted_name(parent.func.value).endswith("handlers"):
                return True
        if isinstance(parent, ast.keyword) and parent.arg == "handlers":
            return True
        # handlers = {...} (any scope)
        if isinstance(parent, ast.Assign) and any(
                isinstance(t, ast.Name) and "handlers" in t.id
                for t in parent.targets):
            return True
        # return {...} / x = {...} inside def *handlers*():
        anc = parent
        while anc is not None:
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return "handlers" in anc.name
            if isinstance(anc, ast.ClassDef):
                return False
            anc = parents.get(id(anc))
        return False

    def finalize(self) -> Iterable[Violation]:
        out: List[Violation] = []
        if not self.registered:
            # Linting a lone client file: no server side in scope means
            # no contract to check, not a hundred false positives.
            return out
        for method, path, line, col, kind in self.client_refs:
            if method not in self.registered:
                out.append(Violation(
                    self.name, path, line, col,
                    f"`{kind}(\"{method}\", ...)` has no registered "
                    f"handler anywhere in the scanned tree — a renamed "
                    f"or typo'd RPC method hangs the caller at runtime"))
        return out
