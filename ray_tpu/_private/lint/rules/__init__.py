"""Built-in raylint rules. Importing this package registers them all
with the engine registry (each module calls ``engine.register``)."""

from ray_tpu._private.lint.rules import (  # noqa: F401
    async_blocking,
    await_atomicity,
    cancel_safety,
    exception_flow,
    exception_hygiene,
    lock_discipline,
    orphan_task,
    protocol_stub,
    rpc_contract,
    rpc_deadlock,
    rpc_schema,
    shm_lifecycle,
)
