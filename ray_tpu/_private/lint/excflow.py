"""Exception-flow substrate: whole-program raise-set inference.

The fault-tolerance story of this runtime rides TYPED errors —
``OutOfMemoryError`` with owner-acked retry budgets, ``retry_later``
lease backpressure, ``ActorDiedError``/``ObjectLostError`` with a
structured ``cause_kind``, ``ProtocolError`` on wire drift — but the
language gives exception flow no static surface: which ``except`` sites
a raise can actually reach is invisible until a chaos seed happens to
drive the path. This module gives raylint that surface, on the same
``callgraph.Program`` substrate (and with the same conservative
no-edge-on-ambiguity discipline) the rpc-schema inference runs on.

Per function it infers a :class:`RaiseInfo`:

* ``escapes`` — exception type NAMES the function can raise to its
  caller: direct ``raise X(...)`` sites, re-raises out of ``except``
  clauses, ``X.from_header(...)`` decodes through a generated protocol
  stub (``ProtocolError`` on drift), and propagation through RESOLVED
  call edges — each contribution filtered through the ``try`` frames
  enclosing its site (a type whose first matching handler cannot
  re-raise is subtracted). The set is a LOWER bound by construction:
  an unresolved call contributes nothing, so every name in it is a
  provable flow.
* ``complete`` — True when ``escapes`` is ALSO an upper bound for the
  project typed-error family (every call in the body resolved with
  complete callees or provably benign, no dynamic ``raise <expr>``,
  no bare ``await`` of a non-call, no dynamically-typed handler):
  only then can "cannot raise T" be claimed. Benign means a site that
  provably never re-enters tree code: an unshadowed builtin call, a
  ``logger.<level>(...)`` call, or the CONSTRUCTION of a known
  exception class.
* ``stored`` — typed-error constructions routed through a store sink
  (``_store_error_for_task(spec, XError(...))``): not a raise HERE,
  but the error the task's caller gets at ``get`` — part of the
  method's observable error surface.

Exception identity is the terminal NAME (``exc.CollectiveError`` →
``CollectiveError``), judged against a hierarchy merged from every
``class X(Y)`` in the scanned tree plus the real builtin exception
MRO. A name with no known ancestry is modeled as a direct
``Exception`` subclass — the documented modeling assumption: it only
widens what a broad handler catches, never what a narrow one does.

From the handler side of the RPC index this yields per-method **error
contracts** (:func:`error_contracts`): the handler family's escaping
raise-set — exactly what the client's ``await conn.call(...)``
re-raises when the dispatcher error-replies — plus its sink-stored
errors and the ``ERROR_REPLY_KEYS`` subset of its reply schema
(``retry_later`` lease backpressure, ``stale_epoch`` fences, in-band
``error`` strings). ``schemagen`` freezes the table into a drift-gated
golden; the ``exception-flow`` rule family judges handlers and call
sites against it.
"""

from __future__ import annotations

import ast
import builtins
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ray_tpu._private.lint.engine import dotted_name

# Reply keys that signal an error/backpressure path rather than payload
# (the vocabulary actually spoken on the wire: in-band error strings,
# lease backpressure, epoch fences). A method's reply schema
# intersected with this set is its error-reply surface.
ERROR_REPLY_KEYS = frozenset({"error", "retry_later", "stale_epoch"})

# Sinks that convert a constructed typed error into a stored task
# result (re-raised at the caller's ``get``): the error never RAISES
# here, but it is part of the path's observable error surface.
ERROR_SINKS = frozenset({"_store_error_for_task"})

# Bare-name builtin calls that provably never re-enter tree code.
# They still raise builtins (ValueError from int(), KeyError…) —
# completeness does not claim to bound those, only project-typed flow.
_BENIGN_BUILTINS = frozenset({
    "abs", "bool", "bytearray", "bytes", "callable", "chr", "dict",
    "divmod", "enumerate", "filter", "float", "format", "frozenset",
    "getattr", "hasattr", "hash", "hex", "id", "int", "isinstance",
    "issubclass", "iter", "len", "list", "map", "max", "min", "next",
    "oct", "ord", "print", "range", "repr", "reversed", "round", "set",
    "setattr", "sorted", "str", "sum", "tuple", "type", "vars", "zip",
})

# ``logger.info(...)``-style method names treated as benign: logging
# never raises project-typed errors back into the flow being judged.
_LOG_METHODS = frozenset({
    "debug", "info", "warning", "error", "exception", "critical", "log",
})

_PROJECT_ROOT_EXC = "RayTpuError"

_TRY_TYPES = (ast.Try,) + ((ast.TryStar,) if hasattr(ast, "TryStar")
                           else ())


@dataclasses.dataclass
class RaiseInfo:
    """Per-function inference result (see module docstring)."""
    escapes: Set[str] = dataclasses.field(default_factory=set)
    complete: bool = True
    stored: Set[str] = dataclasses.field(default_factory=set)


class HandlerMeta:
    """One ``except`` clause of a try frame, as the fold sees it."""

    __slots__ = ("node", "types", "dynamic", "broad", "can_reraise",
                 "bound_name")

    def __init__(self, node: ast.ExceptHandler, star: bool = False):
        self.node = node
        self.types: List[str] = []
        # type expr not statically a (tuple of) name(s) — or an
        # ``except*`` clause, whose group-splitting semantics this
        # model does not attempt
        self.dynamic = star
        self.broad = node.type is None
        self.bound_name = node.name
        if node.type is not None:
            elts = node.type.elts if isinstance(node.type, ast.Tuple) \
                else [node.type]
            for e in elts:
                name = dotted_name(e).rsplit(".", 1)[-1]
                if name and name != "?":
                    self.types.append(name)
                else:
                    self.dynamic = True
        # A handler that can re-raise keeps its caught types escaping:
        # bare ``raise`` or ``raise e`` of the bound name, at any depth
        # (a conditional re-raise still CAN escape).
        self.can_reraise = False
        for inner in ast.walk(node):
            if isinstance(inner, ast.Raise):
                if inner.exc is None:
                    self.can_reraise = True
                elif self.bound_name and \
                        isinstance(inner.exc, ast.Name) and \
                        inner.exc.id == self.bound_name:
                    self.can_reraise = True

    def catches_broadly(self) -> bool:
        """Bare ``except``, ``except Exception`` or ``BaseException``."""
        return self.broad or bool(
            {"Exception", "BaseException"} & set(self.types))


# One try frame: (id(try node), [HandlerMeta, ...] clause-ordered).
_Frame = Tuple[int, List[HandlerMeta]]


class _Event:
    """One raise-capable site with the try frames protecting it
    (innermost first). ``kind`` is one of ``raise`` / ``stub_decode``
    (``names`` carries the types), ``call`` (``callee`` carries the
    function key), or ``unresolved`` (contributes nothing to the lower
    bound, voids the upper)."""

    __slots__ = ("kind", "names", "callee", "frames", "node")

    def __init__(self, kind: str, node: ast.AST, names=(), callee=None,
                 frames: Tuple[_Frame, ...] = ()):
        self.kind = kind
        self.node = node
        self.names = frozenset(names)
        self.callee = callee
        self.frames = tuple(frames)


class Hierarchy:
    """Merged exception-class hierarchy: scanned-tree ``class X(Y)``
    edges plus the real builtin exception MRO. Unknown names read as
    direct Exception subclasses; two same-named tree classes with
    different bases resolve to "not provable" (ancestry falls back to
    the unknown-name modeling)."""

    def __init__(self, program):
        self.parents: Dict[str, Tuple[str, ...]] = {}
        self._ambiguous: Set[str] = set()
        for module in program.modules.values():
            if module.tree is None:
                continue
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                bases = tuple(
                    n for n in
                    (dotted_name(b).rsplit(".", 1)[-1]
                     for b in node.bases)
                    if n and n != "?")
                if node.name in self._ambiguous:
                    continue
                prior = self.parents.get(node.name)
                if prior is not None and prior != bases:
                    self._ambiguous.add(node.name)
                    del self.parents[node.name]
                else:
                    self.parents[node.name] = bases
        self._ancestors_cache: Dict[str, frozenset] = {}

    def ancestors(self, name: str) -> frozenset:
        """Every ancestor name of ``name``, inclusive."""
        cached = self._ancestors_cache.get(name)
        if cached is not None:
            return cached
        out: Set[str] = set()
        stack = [name]
        seen: Set[str] = set()
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            out.add(cur)
            parents = self.parents.get(cur)
            if parents:
                stack.extend(parents)
                continue
            b = getattr(builtins, cur, None)
            if isinstance(b, type) and issubclass(b, BaseException):
                out.update(c.__name__ for c in b.__mro__
                           if issubclass(c, BaseException))
            elif cur == name and cur not in self.parents:
                # modeling assumption: an unknown exception name is a
                # direct Exception subclass
                out.update(("Exception", "BaseException"))
        result = frozenset(out)
        self._ancestors_cache[name] = result
        return result

    def is_exception(self, name: str) -> bool:
        return "BaseException" in self.ancestors(name)

    def catches(self, handler_type: str, raised: str) -> bool:
        """True when ``except handler_type`` catches ``raised``."""
        return handler_type in self.ancestors(raised)

    def project_typed(self, name: str) -> bool:
        """True when ``name`` is in the project typed-error family."""
        return _PROJECT_ROOT_EXC in self.ancestors(name)


def _raised_name(exc_node: ast.AST) -> Optional[str]:
    """Terminal class name of a ``raise`` operand, or None when the
    raised value is dynamic (``raise err``, ``raise make_error()``).
    The lowercase gate reads ``raise err`` as a re-raise of a bound
    value, not a construction — class names here are CapWords."""
    node = exc_node
    if isinstance(node, ast.Call):
        node = node.func
    name = dotted_name(node).rsplit(".", 1)[-1]
    if not name or name == "?" or not name[0].isupper():
        return None
    return name


def _stub_decode_call(program, node: ast.Call) -> bool:
    """True for ``X.from_header(...)`` where X is a generated protocol
    stub — the decode raises ProtocolError on a frame violating the
    declared schema."""
    if not isinstance(node.func, ast.Attribute) or \
            node.func.attr != "from_header":
        return False
    cls_name = dotted_name(node.func.value).rsplit(".", 1)[-1]
    return bool(cls_name) and cls_name != "?" and \
        program.stub_class(cls_name) is not None


class _Collector:
    """Extracts the raise-capable events of ONE function body, with
    the try frames protecting each site (innermost first). Nested
    defs/lambdas/classes are other execution contexts and are not
    descended into; a site inside a ``try`` is protected only when it
    sits in the try's BODY (handlers, orelse and finalbody run outside
    the frame)."""

    def __init__(self, program, fi, hierarchy: Hierarchy):
        self.program = program
        self.fi = fi
        self.hierarchy = hierarchy
        self.edge_by_node = {id(node): callee for node, callee in fi.calls}
        self.events: List[_Event] = []
        self.stored: Set[str] = set()
        self.shadowed = set(program.module_level.get(fi.path, {})) | \
            set(program.import_names.get(fi.path, {}))

    def run(self) -> Tuple[List[_Event], Set[str]]:
        self._stmts(self.fi.node.body, (), frozenset())
        return self.events, self.stored

    # ---------------------------------------------------------- statements

    def _stmts(self, stmts, frames: Tuple[_Frame, ...],
               bound: frozenset):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, _TRY_TYPES):
                star = hasattr(ast, "TryStar") and \
                    isinstance(st, ast.TryStar)
                metas = [HandlerMeta(h, star=star) for h in st.handlers]
                self._stmts(st.body, ((id(st), metas),) + frames, bound)
                for h in st.handlers:
                    inner_bound = bound | {h.name} if h.name else bound
                    self._stmts(h.body, frames, inner_bound)
                self._stmts(st.orelse, frames, bound)
                self._stmts(st.finalbody, frames, bound)
                continue
            if isinstance(st, ast.Raise):
                self._raise(st, frames, bound)
                continue
            if isinstance(st, ast.Assert):
                # AssertionError is never project-typed and asserts
                # vanish under -O: not an event either way
                continue
            for _, value in ast.iter_fields(st):
                if isinstance(value, list):
                    if value and isinstance(value[0], ast.stmt):
                        self._stmts(value, frames, bound)
                    else:
                        for v in value:
                            if hasattr(ast, "match_case") and \
                                    isinstance(v, ast.match_case):
                                if v.guard is not None:
                                    self._expr(v.guard, frames)
                                self._stmts(v.body, frames, bound)
                            elif isinstance(v, ast.AST):
                                self._expr(v, frames)
                elif isinstance(value, ast.AST):
                    self._expr(value, frames)

    def _raise(self, st: ast.Raise, frames, bound: frozenset):
        if st.exc is None:
            # bare re-raise: modeled by the enclosing handler's
            # can_reraise flag, nothing to record here
            return
        name = _raised_name(st.exc)
        if name is not None:
            self.events.append(_Event("raise", st, names=[name],
                                      frames=frames))
        elif not (isinstance(st.exc, ast.Name) and st.exc.id in bound):
            # dynamic raise of something other than a handler-bound
            # name: type unknowable, upper bound void
            self.events.append(_Event("unresolved", st, frames=frames))
        self._expr(st.exc, frames)
        if st.cause is not None:
            self._expr(st.cause, frames)

    # --------------------------------------------------------- expressions

    def _expr(self, node: ast.AST, frames):
        stack = [node]
        while stack:
            cur = stack.pop()
            if isinstance(cur, (ast.Lambda, ast.FunctionDef,
                                ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(cur, ast.Await) and \
                    not isinstance(cur.value, ast.Call):
                # awaiting a stored future/coroutine: raises whatever
                # the producer failed with — unknowable statically
                self.events.append(_Event("unresolved", cur,
                                          frames=frames))
            elif isinstance(cur, ast.Call):
                self._call(cur, frames)
            stack.extend(ast.iter_child_nodes(cur))

    def _call(self, node: ast.Call, frames):
        term = dotted_name(node.func).rsplit(".", 1)[-1]
        if term in ERROR_SINKS:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Call):
                        cname = dotted_name(sub.func).rsplit(".", 1)[-1]
                        if cname and cname != "?" and \
                                self.hierarchy.project_typed(cname):
                            self.stored.add(cname)
        if _stub_decode_call(self.program, node):
            self.events.append(_Event("stub_decode", node,
                                      names=["ProtocolError"],
                                      frames=frames))
            return
        callee = self.edge_by_node.get(id(node))
        if callee is not None:
            if id(node) not in self.fi.spawned_calls:
                # a spawned (create_task/…) call is a DETACHED task:
                # its raises never propagate to this caller
                self.events.append(_Event(
                    "call", node, callee=(callee.path, callee.qualname),
                    frames=frames))
            return
        if isinstance(node.func, ast.Name) and \
                node.func.id in _BENIGN_BUILTINS and \
                node.func.id not in self.shadowed:
            return
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _LOG_METHODS and \
                dotted_name(node.func.value).rsplit(".", 1)[-1] in (
                    "logger", "log", "logging", "_logger"):
            return
        if term and term != "?" and term[0].isupper() and \
                self.hierarchy.is_exception(term):
            # constructing a known exception (raise operands, stored
            # errors, reply payloads) never re-enters tree flow
            return
        self.events.append(_Event("unresolved", node, frames=frames))


def _filter_through_frames(names: Set[str], frames,
                           hierarchy: Hierarchy) -> Tuple[Set[str], bool]:
    """(escaping subset, precise) after the try frames protecting a
    site, innermost first. Per frame the FIRST matching clause
    decides: caught without re-raise → subtracted; caught with a
    possible re-raise → kept (CAN escape). A dynamically-typed clause
    may or may not catch anything — the name drops from the lower
    bound and ``precise`` flips False (no upper-bound claim through
    it)."""
    out = set(names)
    precise = True
    for _, metas in frames:
        if not out:
            break
        survivors = set()
        for r in out:
            verdict = "escape"
            for m in metas:
                if m.dynamic:
                    verdict = "caught"
                    precise = False
                    break
                if m.broad or any(hierarchy.catches(t, r)
                                  for t in m.types):
                    verdict = "reraise" if m.can_reraise else "caught"
                    break
            if verdict != "caught":
                survivors.add(r)
        out = survivors
    return out, precise


def excflow_hierarchy(program) -> Hierarchy:
    cached = getattr(program, "_excflow_hierarchy", None)
    if cached is None:
        cached = Hierarchy(program)
        program._excflow_hierarchy = cached
    return cached


def infer_raise_sets(program) -> Dict[Tuple[str, str], RaiseInfo]:
    """Fixed-point raise-set inference over every function in the
    program. Memoized on the Program (like the rpc-schema table): the
    rule pass, the error-contract table and the JSON reporter all read
    one computation. ``escapes`` grows monotonically and ``complete``
    only ever flips True→False, so the fold terminates."""
    cached = getattr(program, "_excflow_cache", None)
    if cached is not None:
        return cached
    hierarchy = excflow_hierarchy(program)
    events: Dict[Tuple[str, str], List[_Event]] = {}
    infos: Dict[Tuple[str, str], RaiseInfo] = {}
    for key, fi in program.functions.items():
        evs, stored = _Collector(program, fi, hierarchy).run()
        events[key] = evs
        infos[key] = RaiseInfo(stored=stored)
    changed = True
    while changed:
        changed = False
        for key, evs in events.items():
            info = infos[key]
            new_escapes = set(info.escapes)
            complete = True
            for ev in evs:
                if ev.kind == "unresolved":
                    complete = False
                    continue
                if ev.kind == "call":
                    callee = infos.get(ev.callee)
                    if callee is None:
                        complete = False
                        continue
                    contributed = callee.escapes
                    if not callee.complete:
                        complete = False
                else:
                    contributed = ev.names
                escaped, precise = _filter_through_frames(
                    set(contributed), ev.frames, hierarchy)
                if not precise:
                    complete = False
                new_escapes |= escaped
            if new_escapes != info.escapes or \
                    (info.complete and not complete):
                info.escapes = new_escapes
                info.complete = info.complete and complete
                changed = True
    program._excflow_cache = infos
    program._excflow_events = events
    return infos


def handler_reach(program, fi) -> Iterator[
        Tuple[HandlerMeta, Set[str], bool]]:
    """Per ``except`` clause of ``fi``: ``(meta, reach, complete)``.

    ``reach`` is the lower-bound set of exception names arriving at
    that clause — everything the try BODY provably raises (sites at any
    nesting depth, each filtered through the frames between the site
    and this try) minus what EARLIER clauses of the same try catch.
    ``complete`` is True when the try body's raise sources are fully
    resolved — only then is "T cannot reach this clause" provable.
    Clauses after a dynamically-typed clause are not yielded at all:
    neither bound survives an unknowable earlier catch."""
    infos = infer_raise_sets(program)
    hierarchy = excflow_hierarchy(program)
    events = getattr(program, "_excflow_events", {}).get(
        (fi.path, fi.qualname), [])
    reach: Dict[int, Set[str]] = {}
    complete: Dict[int, bool] = {}
    metas_by_try: Dict[int, List[HandlerMeta]] = {}
    order: List[int] = []
    for ev in events:
        if ev.kind == "call":
            callee = infos.get(ev.callee)
            base = set(callee.escapes) if callee else set()
            base_ok = callee is not None and callee.complete
        elif ev.kind == "unresolved":
            base, base_ok = set(), False
        else:
            base, base_ok = set(ev.names), True
        for i, (tid, metas) in enumerate(ev.frames):
            if tid not in reach:
                reach[tid] = set()
                complete[tid] = True
                metas_by_try[tid] = metas
                order.append(tid)
            escaped, precise = _filter_through_frames(
                base, ev.frames[:i], hierarchy)
            reach[tid] |= escaped
            if not (precise and base_ok):
                complete[tid] = False
    for tid in order:
        remaining = set(reach[tid])
        ok = complete[tid]
        for meta in metas_by_try[tid]:
            yield meta, set(remaining), ok
            if meta.dynamic:
                break
            if meta.broad:
                remaining = set()
            else:
                remaining = {r for r in remaining
                             if not any(hierarchy.catches(t, r)
                                        for t in meta.types)}


def error_contracts(program) -> Dict[str, dict]:
    """Per-RPC-method error contract over the registered handler
    family (see module docstring). Deterministic: every collection
    sorted, handler entries ``path:qualname`` with no line numbers —
    the schemagen golden diffs this table."""
    cached = getattr(program, "_error_contract_cache", None)
    if cached is not None:
        return cached
    from ray_tpu._private.lint.rules.rpc_schema import infer_schemas
    infos = infer_raise_sets(program)
    schemas = infer_schemas(program)
    out: Dict[str, dict] = {}
    for method, regs in sorted(program.rpc.registrations.items()):
        raises: Set[str] = set()
        stored: Set[str] = set()
        complete = True
        handlers: Set[str] = set()
        seen = set()
        for reg in regs:
            fi = reg.handler
            if fi is None:
                complete = False
                continue
            key = (fi.path, fi.qualname)
            if key in seen:
                continue
            seen.add(key)
            handlers.add(f"{fi.path}:{fi.qualname}")
            info = infos.get(key)
            if info is None:
                complete = False
                continue
            raises |= info.escapes
            stored |= info.stored
            complete = complete and info.complete
        if not handlers:
            continue
        ms = schemas.get(method)
        error_keys = sorted(ERROR_REPLY_KEYS & ms.reply_keys) \
            if ms is not None and ms.reply_keys is not None else []
        out[method] = {
            "raises": sorted(raises),
            "raises_complete": complete,
            "stored": sorted(stored),
            "error_reply_keys": error_keys,
            "handlers": sorted(handlers),
        }
    program._error_contract_cache = out
    return out
