"""raylint: repo-specific static analysis for the ray_tpu control plane.

The whole control plane (gcs.py, raylet.py, rpc.py, serve/) is
single-threaded asyncio with string-dispatched RPC handlers and
lock-guarded shared state — exactly the layer where hidden blocking and
contention dominate task latency ("Runtime vs Scheduler: Analyzing
Dask's Overheads", arxiv 2010.11105) and where the ownership/RPC
contract must hold (Ray, arxiv 1712.05889). raylint machine-checks the
invariants that previously lived as tribal knowledge, judging on a
whole-program substrate (symbol table + call graph + RPC index,
callgraph.Program) built once over every scanned module:

  async-blocking     no blocking calls on the event loop — directly,
                     or transitively through resolved sync call chains
  lock-discipline    no await/sleep under a threading lock; acyclic
                     cross-module lock acquisition graph
  rpc-contract       every call()/push() method string resolves to a
                     registered handler
  rpc-schema         literal payloads carry the keys the handler reads
                     (schemas inferred from handler bodies); reply
                     reads name keys some return path produces
  exception-hygiene  no bare/silent exception swallowing on _private/
  shm-lifecycle      every AllocSegment lease is sealed or aborted

Usage:
    python -m ray_tpu._private.lint ray_tpu/            # text report
    python -m ray_tpu._private.lint --format json ray_tpu/
    python -m ray_tpu._private.lint --stale-pragmas ray_tpu/
    python -m ray_tpu._private.lint --dump-schemas ray_tpu/
    python -m ray_tpu._private.lint --list-rules

Suppress a finding with a pragma on the flagged line or the line above:
    # raylint: disable=<rule>[,<rule>...] — <why>
or a whole file with:
    # raylint: disable-file=<rule>[,<rule>...]

Exit codes: 0 clean, 1 violations found, 2 usage/internal error.
See RULES.md (next to this file) for the rule catalogue.
"""

from ray_tpu._private.lint.engine import (  # noqa: F401
    Module,
    Rule,
    Violation,
    all_rules,
    analyze_modules,
    find_stale_pragmas,
    lint_paths,
    lint_sources,
    load_modules,
    register,
)
from ray_tpu._private.lint.callgraph import (  # noqa: F401
    Program,
    build_program,
)
