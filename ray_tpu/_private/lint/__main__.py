"""CLI entry point: ``python -m ray_tpu._private.lint <paths>``."""

import sys

from ray_tpu._private.lint.engine import main

sys.exit(main())
