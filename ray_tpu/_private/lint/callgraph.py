"""Whole-program substrate shared by raylint rules.

One ``Program`` is built per lint run from every parsed module and
handed to each rule via ``Rule.setup(program)``. It carries three
layers, each conservative by construction (an edge or resolution only
exists when the AST proves it — dynamic dispatch stays out of scope):

  * **symbol table** — every function/method as a ``FunctionInfo``
    (qualname, enclosing class, async flag, positional signature);
  * **call graph** — edges from each function to callees the resolver
    can pin down statically: same-module top-level calls, explicitly
    imported names, ``mod.func`` through an imported module, and
    ``self.method``/``cls.method`` within the enclosing class.
    Function references passed as arguments (``run_in_executor(None,
    f)``, ``Thread(target=f)``) are deliberately NOT edges: they hop
    threads, which is exactly the boundary async-reachability must
    not cross;
  * **RPC index** — every handler registration (``RpcServer({...})``,
    ``handlers=`` kwargs, ``.handlers.update({...})``, dicts in
    ``*handlers*`` functions) with the handler expression resolved to
    its ``FunctionInfo``, plus every client-side
    ``call/push/call_nowait/push_nowait/_gcs_call`` site with its
    header expression. rpc-contract checks name existence against it;
    rpc-schema infers per-method header schemas from it;
  * **stub index** — every generated protocol stub class (a ClassDef
    declaring ``_REQUIRED``/``_OPTIONAL`` string-set class attrs, the
    shape ``lint/schemagen.py`` emits into ``_private/protocol.py``)
    with its declared schema. rpc-schema resolves
    ``X.from_header(header)`` / ``return X(...).to_header()`` through
    it so a migrated handler keeps a CLOSED inferred schema, and
    protocol-stub checks stub constructor kwargs against it.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu._private.lint.engine import (
    Module, dotted_name, first_str_arg, walk_functions, body_nodes,
)

CLIENT_METHODS = {"call", "push", "call_nowait", "push_nowait", "_gcs_call"}


class FunctionInfo:
    """One function or method definition, with the signature facts
    rules need and (after resolution) its outgoing call edges."""

    __slots__ = ("path", "qualname", "node", "class_name", "is_async",
                 "params", "has_var_pos", "has_var_kw", "calls",
                 "spawned_calls")

    def __init__(self, path: str, qualname: str, node: ast.AST,
                 class_name: str):
        self.path = path
        self.qualname = qualname
        self.node = node
        self.class_name = class_name
        self.is_async = isinstance(node, ast.AsyncFunctionDef)
        args = node.args
        self.params = [a.arg for a in args.posonlyargs + args.args]
        self.has_var_pos = args.vararg is not None
        self.has_var_kw = args.kwarg is not None
        # (call node, callee FunctionInfo) — filled by _resolve_edges
        self.calls: List[Tuple[ast.Call, "FunctionInfo"]] = []
        # ids of call nodes handed to create_task/ensure_future/
        # spawn_logged: the edge exists (the code runs) but it is a
        # DETACHED task, not part of this function's synchronous
        # continuation — wait-for analyses must not follow it
        self.spawned_calls: Set[int] = set()

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]

    @property
    def is_method(self) -> bool:
        return bool(self.class_name) and bool(self.params) and \
            self.params[0] in ("self", "cls")

    def positional_params(self) -> List[str]:
        """Positional parameter names with self/cls stripped."""
        return self.params[1:] if self.is_method else list(self.params)

    def __repr__(self):
        return f"<fn {self.path}:{self.qualname}>"


@dataclasses.dataclass
class Registration:
    """One ``"Method": <handler expr>`` entry in a registration dict."""
    method: str
    path: str
    lineno: int
    col: int
    value_desc: str                       # dotted text of the handler expr
    handler: Optional[FunctionInfo]       # resolved def, when provable
    # True when the expr was `self.x` / `obj.x`, the owning class is
    # known and base-less, and NO class anywhere defines x — i.e. the
    # registration provably dangles (rpc-schema reports it).
    provably_missing: bool = False


@dataclasses.dataclass
class StubClassInfo:
    """One generated protocol stub class (the ``schemagen.py`` shape:
    ``_REQUIRED``/``_OPTIONAL`` frozenset-of-str class attrs plus
    ``METHOD``/``KIND``/``_OPEN``/``_COMPAT_DEFAULTS``). The declared
    sets ARE the wire schema — rpc-schema reads them instead of the
    (absent) literal header accesses in a stub-migrated handler."""
    name: str
    path: str
    lineno: int
    method: str                           # METHOD attr; "" = base class
    kind: str                             # "request" | "reply" | ""
    required: frozenset
    optional: frozenset
    open: bool = False
    compat_defaults: Dict[str, object] = dataclasses.field(
        default_factory=dict)

    @property
    def known(self) -> frozenset:
        return self.required | self.optional


@dataclasses.dataclass
class ClientCall:
    """One client-side RPC reference: conn.call("Method", header, ...)."""
    method: str
    kind: str                             # call/push/call_nowait/...
    path: str
    lineno: int
    col: int
    header: Optional[ast.AST]             # None when no header was passed
    # Enclosing def, when the call sits inside one (rpc-deadlock walks
    # the wait-for graph from handler FunctionInfos to these sites).
    in_function: Optional[FunctionInfo] = None
    # True when the caller synchronously waits for the reply: the call
    # is under an ``await`` in the same expression statement (directly
    # or through an ``asyncio.wait_for`` wrapper).
    awaited: bool = False
    # True when the wait is provably bounded: ``timeout=`` passed to the
    # call itself or an enclosing ``wait_for`` with a timeout.
    bounded: bool = False


class RpcIndex:
    def __init__(self):
        self.registrations: Dict[str, List[Registration]] = {}
        self.client_calls: List[ClientCall] = []

    @property
    def registered_methods(self) -> Set[str]:
        return set(self.registrations)


class Program:
    def __init__(self):
        self.modules: Dict[str, Module] = {}
        self.functions: Dict[Tuple[str, str], FunctionInfo] = {}
        # path -> {name: fi} for module-level (non-nested) functions
        self.module_level: Dict[str, Dict[str, FunctionInfo]] = {}
        # class name -> {method name: [fi, ...]} across all modules
        self.methods: Dict[str, Dict[str, List[FunctionInfo]]] = {}
        # method name -> [fi, ...] over every class (for obj.x resolution)
        self.any_method: Dict[str, List[FunctionInfo]] = {}
        # module basename ("gcs") -> [path, ...]
        self.by_basename: Dict[str, List[str]] = {}
        # path -> {local alias: imported module basename or dotted path}
        self.import_modules: Dict[str, Dict[str, str]] = {}
        # path -> {local name: (module basename, original name)}
        self.import_names: Dict[str, Dict[str, Tuple[str, str]]] = {}
        # class name -> has non-object bases (methods may be inherited)
        self.class_has_bases: Dict[str, bool] = {}
        # stub class name -> StubClassInfo (None-valued when two
        # same-named stub classes declare DIFFERENT schemas: ambiguity
        # resolves to "not provable", like every other layer here)
        self._stub_classes: Dict[str, Optional[StubClassInfo]] = {}
        # id(def node) -> FunctionInfo, for ancestor walks that land on
        # a FunctionDef and need its info back
        self.fi_by_node: Dict[int, FunctionInfo] = {}
        self.rpc = RpcIndex()

    # -------------------------------------------------------------- lookup

    def module_function(self, path: str, name: str) -> Optional[FunctionInfo]:
        return self.module_level.get(path, {}).get(name)

    def class_method(self, class_name: str, method: str,
                     prefer_path: str = "") -> Optional[FunctionInfo]:
        cands = self.methods.get(class_name, {}).get(method, [])
        if not cands:
            return None
        for fi in cands:
            if fi.path == prefer_path:
                return fi
        return cands[0] if len(cands) == 1 else None

    def imported_function(self, path: str, name: str) -> Optional[FunctionInfo]:
        """Resolve a bare name through `from mod import name`."""
        imp = self.import_names.get(path, {}).get(name)
        if imp is None:
            return None
        mod_base, orig = imp
        return self._unique_basename_def(mod_base, orig)

    def module_attr_function(self, path: str, mod_alias: str,
                             name: str) -> Optional[FunctionInfo]:
        """Resolve `alias.name()` through `import mod [as alias]`."""
        base = self.import_modules.get(path, {}).get(mod_alias)
        if base is None:
            return None
        return self._unique_basename_def(base, name)

    def stub_class(self, name: str) -> Optional[StubClassInfo]:
        """The unique stub class called ``name``, or None (unknown or
        ambiguously multi-defined)."""
        return self._stub_classes.get(name)

    def stub_classes(self):
        """Every unambiguous stub class, name-sorted."""
        return [info for _, info in sorted(self._stub_classes.items())
                if info is not None]

    def _unique_basename_def(self, mod_base: str,
                             name: str) -> Optional[FunctionInfo]:
        """The one module-level def of ``name`` across every file named
        ``mod_base``.py — two same-named modules both defining it (e.g.
        ``a/util.py`` and ``b/util.py``) are ambiguous without package
        paths, and an edge needs proof: ambiguity resolves to None."""
        found = None
        for target in self.by_basename.get(mod_base, []):
            fi = self.module_function(target, name)
            if fi is None:
                continue
            if found is not None:
                return None
            found = fi
        return found


# ---------------------------------------------------------------- builders

def _const_str_set(node: ast.AST) -> Optional[frozenset]:
    """``frozenset({"a", ...})`` / ``{"a", ...}`` / ``frozenset()`` as a
    frozenset of strings, or None when not statically that shape."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("frozenset", "set"):
        if not node.args and not node.keywords:
            return frozenset()
        if len(node.args) != 1 or node.keywords:
            return None
        node = node.args[0]
    if not isinstance(node, (ast.Set, ast.List, ast.Tuple)):
        return None
    out = set()
    for e in node.elts:
        if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
            return None
        out.add(e.value)
    return frozenset(out)


def _stub_class_of(node: ast.ClassDef, path: str) -> Optional[StubClassInfo]:
    """Parse ``node`` as a generated protocol stub class, or None. The
    qualifying shape is exactly what schemagen emits: ``_REQUIRED`` and
    ``_OPTIONAL`` as constant string sets (everything else optional)."""
    attrs: Dict[str, ast.AST] = {}
    for st in node.body:
        if isinstance(st, ast.Assign) and len(st.targets) == 1 and \
                isinstance(st.targets[0], ast.Name):
            attrs[st.targets[0].id] = st.value
    if "_REQUIRED" not in attrs or "_OPTIONAL" not in attrs:
        return None
    required = _const_str_set(attrs["_REQUIRED"])
    optional = _const_str_set(attrs["_OPTIONAL"])
    if required is None or optional is None:
        return None

    def _const(name, default):
        v = attrs.get(name)
        if isinstance(v, ast.Constant):
            return v.value
        return default

    compat: Dict[str, object] = {}
    cd = attrs.get("_COMPAT_DEFAULTS")
    if isinstance(cd, ast.Dict):
        try:
            compat = ast.literal_eval(cd)
        except ValueError:
            compat = {}
    return StubClassInfo(
        name=node.name, path=path, lineno=node.lineno,
        method=str(_const("METHOD", "") or ""),
        kind=str(_const("KIND", "") or ""),
        required=required, optional=optional,
        open=bool(_const("_OPEN", False)),
        compat_defaults=compat)


def _collect_symbols(program: Program, module: Module):
    path = module.path
    base = path.rsplit("/", 1)[-1]
    if base.endswith(".py"):
        base = base[:-3]
    program.by_basename.setdefault(base, []).append(path)
    program.module_level.setdefault(path, {})
    for func, qualname, cls in walk_functions(module.tree):
        fi = FunctionInfo(path, qualname, func, cls)
        program.functions[(path, qualname)] = fi
        program.fi_by_node[id(func)] = fi
        if "." not in qualname:
            program.module_level[path][qualname] = fi
        if cls and qualname.endswith("." + func.name) and \
                qualname[:-len(func.name) - 1].rsplit(".", 1)[-1] == cls:
            program.methods.setdefault(cls, {}).setdefault(
                func.name, []).append(fi)
            program.any_method.setdefault(func.name, []).append(fi)
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ClassDef):
            has_bases = any(
                not (isinstance(b, ast.Name) and b.id == "object")
                for b in node.bases)
            # ORed across same-named classes: any inheriting variant
            # makes "method not found" unprovable.
            program.class_has_bases[node.name] = \
                program.class_has_bases.get(node.name, False) or has_bases
            stub = _stub_class_of(node, path)
            if stub is not None:
                prior = program._stub_classes.get(node.name)
                if node.name in program._stub_classes and (
                        prior is None or
                        (prior.required, prior.optional, prior.open,
                         prior.method, prior.kind,
                         prior.compat_defaults) !=
                        (stub.required, stub.optional, stub.open,
                         stub.method, stub.kind,
                         stub.compat_defaults)):
                    # two same-named stub classes with ANY schema
                    # difference — compat defaults included, since
                    # retiring an overlay changes only those — are not
                    # provable; last-write-wins would make the golden
                    # depend on scan order
                    program._stub_classes[node.name] = None
                else:
                    program._stub_classes[node.name] = stub
        elif isinstance(node, ast.Import):
            for alias in node.names:
                # `import a.b as c` binds c to module a.b; a bare
                # `import a.b` binds only the top-level package a.
                if alias.asname:
                    local, target = alias.asname, alias.name.rsplit(".", 1)[-1]
                else:
                    local = target = alias.name.split(".")[0]
                program.import_modules.setdefault(path, {})[local] = target
        elif isinstance(node, ast.ImportFrom) and node.module:
            mod_base = node.module.rsplit(".", 1)[-1]
            for alias in node.names:
                if alias.name == "*":
                    continue
                local = alias.asname or alias.name
                program.import_names.setdefault(path, {})[local] = \
                    (mod_base, alias.name)
                # `from pkg import mod` also enables `mod.func(...)`
                program.import_modules.setdefault(path, {}) \
                    .setdefault(local, alias.name)


def _enclosing_class(node: ast.AST, parents: Dict[int, ast.AST]) -> str:
    anc = parents.get(id(node))
    while anc is not None:
        if isinstance(anc, ast.ClassDef):
            return anc.name
        anc = parents.get(id(anc))
    return ""


def _resolve_callable(program: Program, path: str, expr: ast.AST,
                      enclosing_class: str,
                      any_method_fallback: bool = False
                      ) -> Optional[FunctionInfo]:
    """Resolve a callable reference expression to its def, or None.

    ``any_method_fallback`` lets an unqualified ``obj.x`` match a
    method name that is unique across the whole program. That is right
    for handler-dict values (``"PushTasks": executor.handle_push_tasks``
    deliberately points at one def) but far too eager for call edges
    (``anything.join()`` must not edge into an unrelated class), so
    edge resolution leaves it off.
    """
    if isinstance(expr, ast.Name):
        return (program.module_function(path, expr.id)
                or program.imported_function(path, expr.id))
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        base, attr = expr.value.id, expr.attr
        if base in ("self", "cls") and enclosing_class:
            fi = program.class_method(enclosing_class, attr,
                                      prefer_path=path)
            if fi is not None:
                return fi
            # Not on the class itself (inherited / mixed in): unique
            # across the program still identifies it; ambiguity stays
            # unresolved.
            cands = program.any_method.get(attr, [])
            return cands[0] if len(cands) == 1 else None
        fi = program.module_attr_function(path, base, attr)
        if fi is not None:
            return fi
        if any_method_fallback:
            cands = program.any_method.get(attr, [])
            return cands[0] if len(cands) == 1 else None
    return None


def _resolve_edges(program: Program, module: Module,
                   parents: Dict[int, ast.AST]):
    path = module.path
    for func, qualname, cls in walk_functions(module.tree):
        fi = program.functions[(path, qualname)]
        for node in body_nodes(func):
            if not isinstance(node, ast.Call):
                continue
            callee = _resolve_callable(program, path, node.func, cls)
            if callee is not None and callee is not fi:
                fi.calls.append((node, callee))
                parent = parents.get(id(node))
                if isinstance(parent, ast.Call) and node in parent.args \
                        and dotted_name(parent.func).rsplit(".", 1)[-1] \
                        in ("create_task", "ensure_future",
                            "spawn_logged"):
                    fi.spawned_calls.add(id(node))


def _is_registration(node: ast.Dict, parents: Dict[int, ast.AST]) -> bool:
    """True when a dict literal is an RPC handler registration (the v1
    rpc-contract heuristics, now shared program-wide)."""
    parent = parents.get(id(node))
    if isinstance(parent, ast.Call):
        func_name = dotted_name(parent.func)
        if func_name.rsplit(".", 1)[-1] == "RpcServer" and \
                parent.args and parent.args[0] is node:
            return True
        for kw in parent.keywords:
            if kw.arg == "handlers" and kw.value is node:
                return True
        if isinstance(parent.func, ast.Attribute) and \
                parent.func.attr == "update" and \
                dotted_name(parent.func.value).endswith("handlers"):
            return True
    if isinstance(parent, ast.keyword) and parent.arg == "handlers":
        return True
    if isinstance(parent, ast.Assign) and any(
            isinstance(t, ast.Name) and "handlers" in t.id
            for t in parent.targets):
        return True
    anc = parent
    while anc is not None:
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return "handlers" in anc.name
        if isinstance(anc, ast.ClassDef):
            return False
        anc = parents.get(id(anc))
    return False


def _call_context(program: Program, node: ast.Call,
                  parents: Dict[int, ast.AST]
                  ) -> Tuple[Optional[FunctionInfo], bool, bool]:
    """(enclosing def, awaited, bounded) for a client-call site.

    ``awaited`` only looks within the call's own expression statement:
    ``await conn.call(...)`` and ``await wait_for(conn.call(...), t)``
    both count; a task spawned from the call does not. ``bounded``
    needs a ``timeout=`` on the call itself or a wrapping ``wait_for``
    with a timeout argument."""
    in_fn: Optional[FunctionInfo] = None
    awaited = False
    bounded = any(kw.arg == "timeout" and not (
        isinstance(kw.value, ast.Constant) and kw.value.value is None)
        for kw in node.keywords)
    crossed_stmt = False
    anc = parents.get(id(node))
    while anc is not None:
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            in_fn = program.fi_by_node.get(id(anc))
            break
        if not crossed_stmt:
            if isinstance(anc, ast.Await):
                awaited = True
            elif isinstance(anc, ast.Call) and \
                    dotted_name(anc.func).rsplit(".", 1)[-1] == "wait_for":
                if len(anc.args) > 1 or any(
                        kw.arg == "timeout" for kw in anc.keywords):
                    bounded = True
            elif isinstance(anc, ast.stmt):
                crossed_stmt = True
        anc = parents.get(id(anc))
    return in_fn, awaited, bounded


def _index_rpc(program: Program, module: Module,
               parents: Dict[int, ast.AST]):
    path = module.path
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Dict) and _is_registration(node, parents):
            cls = _enclosing_class(node, parents)
            for key, value in zip(node.keys, node.values):
                if not (isinstance(key, ast.Constant) and
                        isinstance(key.value, str)):
                    continue
                handler = _resolve_callable(program, path, value, cls,
                                            any_method_fallback=True)
                missing = False
                if handler is None and isinstance(value, ast.Attribute) \
                        and isinstance(value.value, ast.Name):
                    owner = cls if value.value.id in ("self", "cls") \
                        else ""
                    # `self.x` with no x on any class and no bases to
                    # inherit from: the registration provably dangles.
                    if owner and not program.class_has_bases.get(owner) \
                            and value.attr not in program.any_method:
                        missing = True
                program.rpc.registrations.setdefault(key.value, []).append(
                    Registration(key.value, path, key.lineno,
                                 key.col_offset, dotted_name(value),
                                 handler, missing))
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in CLIENT_METHODS:
            method = first_str_arg(node)
            if method is None:
                continue
            header: Optional[ast.AST] = None
            if len(node.args) > 1:
                header = node.args[1]
            else:
                for kw in node.keywords:
                    if kw.arg == "header":
                        header = kw.value
            in_fn, awaited, bounded = _call_context(program, node, parents)
            program.rpc.client_calls.append(ClientCall(
                method, node.func.attr, path, node.lineno,
                node.col_offset, header, in_function=in_fn,
                awaited=awaited, bounded=bounded))


def build_program(modules: List[Module]) -> Program:
    program = Program()
    parsed = [m for m in modules if m.tree is not None]
    for m in parsed:
        program.modules[m.path] = m
        _collect_symbols(program, m)
    # Parent maps are per-module and needed by both late passes; edges
    # and RPC indexing each see the full symbol table.
    for m in parsed:
        parents: Dict[int, ast.AST] = {}
        for node in ast.walk(m.tree):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
        _resolve_edges(program, m, parents)
        _index_rpc(program, m, parents)
    return program
