"""schemagen: generated typed RPC stubs + the schema drift gate.

raylint's rpc-schema rule *infers* a wire schema for every RPC method
(``--dump-schemas``). This module promotes that inference from lint
artifact to source of truth:

* ``python -m ray_tpu._private.lint.schemagen ray_tpu/`` runs the
  inference over the tree, applies the ``OVERLAYS`` evolution table,
  and (re)generates two checked-in artifacts:

    - ``ray_tpu/_private/protocol.py`` — one slots-based typed
      request/reply stub per method in ``GENERATE`` (near-zero-overhead
      ``to_header``/``from_header``, required/optional/open-key
      semantics, ``PROTOCOL_VERSION``, per-method compat rules);
    - ``ray_tpu/_private/lint/rpc_schemas_golden.json`` — the full
      normalized schema table for EVERY method (line numbers stripped,
      everything sorted, byte-stable across runs).

* ``--check`` (the ci/lint.sh drift gate) re-runs the inference and
  fails with a diff when either artifact is stale: editing a handler's
  schema without regenerating cannot land.

The loop closes through the inference itself: a handler migrated to
``X.from_header(header)`` / ``return XReply(...).to_header()`` is
inferred FROM the stub's declared ``_REQUIRED``/``_OPTIONAL`` sets
(callgraph.StubClassInfo), so regeneration over a fully-migrated tree
is a fixed point. Schema evolution happens by editing a handler (a new
literal key read unions into the stub's schema on regen) or by adding
an ``OVERLAYS`` entry, then regenerating.

Compat rules (enforced by the generated ``from_header``):

* unknown keys are tolerated by default (dropped for closed schemas,
  preserved in ``_extras`` for open ones) — old receivers survive new
  senders;
* a required-key ADDITION must ship with a deprecation-window default
  in ``OVERLAYS`` (emitted as ``_COMPAT_DEFAULTS``): the decoder fills
  the default when a pre-window peer omits the key — new receivers
  survive old senders. After one release window the entry is retired
  and the key becomes hard-required.

``--from-snapshot`` builds the stub module from a saved golden instead
of live inference — the bootstrap path, and how the two-version interop
tests materialize an OLD protocol from a fixture snapshot.
"""

from __future__ import annotations

import argparse
import difflib
import io
import json
import keyword
import os
import re
import sys
from typing import Dict, List, Optional, Sequence

PROTOCOL_VERSION = 2
MIN_PROTOCOL_VERSION = 1

# Methods that get generated stubs (the rest stay literal-dict and are
# only drift-gated through the golden). Growing this tuple + regen +
# migrating the call sites the protocol-stub rule then flags is the
# whole mechanical migration recipe.
GENERATE = (
    "AddClusterEvents",
    "AddObjectEvents",
    "AddTaskEvents",
    "BookGangMembers",
    "FetchObjectMeta",
    "GatherShards",
    "GetClusterEvents",
    "GetNodeStats",
    "GetNodeStatsSummary",
    "GetObjectLocations",
    "GetObjectSummary",
    "GetRpcTelemetry",
    "GrantLeaseCredits",
    "Heartbeat",
    "KVDel",
    "KVGet",
    "KVKeys",
    "KVPut",
    "RegisterNode",
    "ReleaseGangLease",
    "ReleaseGangMembers",
    "ReportLeaseDemand",
    "ReportRpcTelemetry",
    "RequestGangLease",
    "RequestWorkerLease",
    "ReturnWorker",
    "RevokeLeaseCredits",
    "RingAbort",
    "RingFinish",
    "RingInit",
    "RingStep",
    "SealObject",
    "WorkerOOMKilled",
)

# Schema evolution overlays, applied on top of the inference. "require"
# adds a key to the required set WITH a deprecation-window decode
# default (the compat rule for required-key additions). Retire entries
# after one release window to make the key hard-required.
OVERLAYS: Dict[str, dict] = {
    "RegisterNode": {
        # v2: nodes advertise their protocol version at registration;
        # a v1 raylet omits both sides and decodes as version 1.
        "request": {"require": {"protocol_version": 1}},
        "reply": {"require": {"protocol_version": 1,
                              "negotiated_protocol_version": 1}},
    },
}

_LINT_DIR = os.path.dirname(os.path.abspath(__file__))
GOLDEN_PATH = os.path.join(_LINT_DIR, "rpc_schemas_golden.json")
CONTRACTS_PATH = os.path.join(_LINT_DIR, "error_contracts_golden.json")
PROTOCOL_PATH = os.path.normpath(
    os.path.join(_LINT_DIR, os.pardir, "protocol.py"))

_IDENT_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")
# Names the stub machinery owns; a wire key colliding with one cannot
# become a slot.
_RESERVED = {"METHOD", "KIND", "get", "to_header", "from_header",
             "_REQUIRED", "_OPTIONAL", "_COMPAT_DEFAULTS", "_OPEN",
             "_extras"}


def _norm_path(path: str) -> str:
    """Repo-stable handler path: strip any absolute prefix up to the
    package root so goldens diff cleanly across checkouts. Greedy match
    anchors on the LAST ``ray_tpu/`` component — a checkout under an
    ancestor directory that happens to be named ray_tpu must not leak
    into the golden."""
    return re.sub(r"^.*(ray_tpu/)", r"\1", path.replace(os.sep, "/"))


def _norm_handler(entry: str) -> str:
    """``path:lineno:qualname`` -> ``path:qualname`` — line numbers
    must never gate CI (editing unrelated code above a handler moves
    them)."""
    parts = entry.split(":")
    if len(parts) >= 3 and parts[1].isdigit():
        parts.pop(1)
    return _norm_path(":".join(parts))


def _side(required: Sequence[str], optional: Sequence[str], open_: bool,
          compat: Optional[dict] = None) -> dict:
    compat = compat or {}
    return {
        "required": sorted(required),
        "optional": sorted(set(optional) - set(required)),
        "open": bool(open_),
        "compat_defaults": {k: compat[k] for k in sorted(compat)},
    }


def normalize_dump(dump: dict) -> dict:
    """``schemas_as_dict`` output -> the normalized golden spec:
    ``{method: {handlers, request: {...}, reply: {...}}}``.

    Deliberately DROPS the dump's inference-side ``compat_defaults``
    (which reflect the checked-in stubs' ``_COMPAT_DEFAULTS``): compat
    defaults originate ONLY from ``OVERLAYS``, applied after this. If
    the stubs fed their own compat back through the inference,
    retiring an overlay entry would regenerate the identical stub and
    a deprecation window could never actually close."""
    spec = {}
    for method, d in sorted(dump.items()):
        spec[method] = {
            "handlers": sorted(_norm_handler(h) for h in d["handlers"]),
            "request": _side(d["required"], d["optional"],
                             not d["closed"]),
            "reply": _side(d["reply_guaranteed"],
                           set(d["reply"]) - set(d["reply_guaranteed"]),
                           d["reply_open"]),
        }
    return spec


def apply_overlays(spec: dict,
                   overlays: Optional[Dict[str, dict]] = None) -> dict:
    overlays = OVERLAYS if overlays is None else overlays
    for method, sides in overlays.items():
        ms = spec.get(method)
        if ms is None:
            continue
        for side_name, ops in sides.items():
            side = ms[side_name]
            for key, default in ops.get("require", {}).items():
                if key not in side["required"]:
                    side["required"] = sorted(side["required"] + [key])
                side["optional"] = sorted(
                    set(side["optional"]) - {key})
                side["compat_defaults"][key] = default
            side["compat_defaults"] = {
                k: side["compat_defaults"][k]
                for k in sorted(side["compat_defaults"])}
    return spec


def build_spec(program) -> dict:
    """Inference -> normalized spec with overlays applied (the thing
    the golden stores and the drift gate recomputes)."""
    from ray_tpu._private.lint.rules.rpc_schema import schemas_as_dict
    return apply_overlays(normalize_dump(schemas_as_dict(program)))


def spec_from_paths(paths: Sequence[str]) -> dict:
    from ray_tpu._private.lint.callgraph import build_program
    from ray_tpu._private.lint.engine import load_modules
    return build_spec(build_program(load_modules(paths)))


def build_contracts(program) -> dict:
    """The excflow error-contract table, path-normalized for golden
    stability (same discipline as the schema golden: sorted, no line
    numbers, checkout-relative handler paths)."""
    from ray_tpu._private.lint.excflow import error_contracts
    out = {}
    for method, c in sorted(error_contracts(program).items()):
        out[method] = {
            "raises": list(c["raises"]),
            "raises_complete": bool(c["raises_complete"]),
            "stored": list(c["stored"]),
            "error_reply_keys": list(c["error_reply_keys"]),
            "handlers": sorted(_norm_path(h) for h in c["handlers"]),
        }
    return out


def emit_contracts(contracts: dict,
                   version: int = PROTOCOL_VERSION) -> str:
    return json.dumps(
        {"protocol_version": version, "contracts": contracts},
        indent=2, sort_keys=True) + "\n"


def spec_from_snapshot(snapshot: dict) -> dict:
    """A saved golden (``{"protocol_version", "methods"}``) or a raw
    ``--dump-schemas`` table -> spec. No overlays: a snapshot is
    already post-evolution for its version."""
    methods = snapshot.get("methods", snapshot)
    first = next(iter(methods.values()), None)
    if first is not None and "request" not in first:
        return normalize_dump(methods)
    return {m: methods[m] for m in sorted(methods)}


def emit_golden(spec: dict, version: int = PROTOCOL_VERSION) -> str:
    return json.dumps({"protocol_version": version, "methods": spec},
                      indent=2, sort_keys=True) + "\n"


# ------------------------------------------------------------- emission

def _check_keys(method: str, side: dict) -> Optional[str]:
    for k in side["required"] + side["optional"]:
        if not _IDENT_RE.match(k) or keyword.iskeyword(k) \
                or k in _RESERVED:
            return (f"{method}: wire key {k!r} cannot become a slot "
                    f"(not an identifier, or reserved)")
    return None


def _wrap(prefix: str, items: Sequence[str], suffix: str) -> str:
    """``prefix + ", ".join(items) + suffix`` wrapped at 79 cols with
    continuation lines aligned under the opening paren."""
    one = prefix + ", ".join(items) + suffix
    if len(one) <= 79 or not items:
        return one
    pad = " " * len(prefix)
    lines = [prefix + items[0]]
    for item in items[1:]:
        candidate = lines[-1] + ", " + item
        if len(candidate) <= 77:
            lines[-1] = candidate
        else:
            lines[-1] += ","
            lines.append(pad + item)
    lines[-1] += suffix
    return "\n".join(lines)


def _fmt_set(values: Sequence[str], indent: str) -> str:
    if not values:
        return "frozenset()"
    inner = ", ".join(f'"{v}"' for v in sorted(values))
    one = f"frozenset({{{inner}}})"
    if len(one) + len(indent) <= 72:
        return one
    lines = ",\n".join(f'{indent}    "{v}"' for v in sorted(values))
    return "frozenset({\n" + lines + f",\n{indent}}})"


def _emit_class(out: io.StringIO, method: str, kind: str, side: dict,
                handlers: Sequence[str]) -> str:
    cls = method + ("Request" if kind == "request" else "Reply")
    req = sorted(side["required"])
    opt = sorted(side["optional"])
    open_ = side["open"]
    compat = side["compat_defaults"]
    fields = req + opt
    w = out.write
    w(f"\n\nclass {cls}(_StubBase):\n")
    w(f'    """{kind.capitalize()} stub for the ``{method}`` RPC.\n')
    if handlers:
        w("\n")
        for h in handlers:
            w(f"    Handler: ``{h}``.\n")
    w('    """\n\n')
    w(f'    METHOD = "{method}"\n')
    w(f'    KIND = "{kind}"\n')
    w(f"    _REQUIRED = {_fmt_set(req, '    ')}\n")
    w(f"    _OPTIONAL = {_fmt_set(opt, '    ')}\n")
    # repr, not json.dumps: a bool/None default must land as
    # True/False/None in the generated source, never true/false/null
    w("    _COMPAT_DEFAULTS = "
      f"{repr({k: compat[k] for k in sorted(compat)})}\n")
    w(f"    _OPEN = {open_}\n")
    slots = list(fields) + (["_extras"] if open_ else [])
    w(_wrap("    __slots__ = (", [f'"{s}"' for s in slots],
            ",)" if len(slots) == 1 else ")") + "\n")
    # __init__: required keys are strict on ENCODE even when a compat
    # default exists — only the decoder tolerates their absence.
    params = ["self"]
    if fields or open_:
        params.append("*")
    params += req + [f"{k}=UNSET" for k in opt]
    if open_:
        params.append("extras=None")
    w("\n" + _wrap("    def __init__(", params, "):") + "\n")
    if not fields and not open_:
        w("        pass\n")
    for k in fields:
        w(f"        self.{k} = {k}\n")
    if open_:
        w("        self._extras = dict(extras) if extras else {}\n")
    # to_header
    w("\n    def to_header(self):\n")
    if open_:
        w("        h = dict(self._extras)\n")
        for k in req:
            w(f'        h["{k}"] = self.{k}\n')
    elif req:
        w("        h = {\n")
        for k in req:
            w(f'            "{k}": self.{k},\n')
        w("        }\n")
    else:
        w("        h = {}\n")
    for k in opt:
        w(f"        if self.{k} is not UNSET:\n")
        w(f'            h["{k}"] = self.{k}\n')
    w("        return h\n")
    # from_header
    w("\n    @classmethod\n")
    w("    def from_header(cls, header):\n")
    if open_:
        w("        return _decode_slow(cls, header)\n")
        return cls
    w("        self = cls.__new__(cls)\n")
    if req:
        w("        try:\n")
        for k in req:
            w(f'            self.{k} = header["{k}"]\n')
        w("        except (KeyError, TypeError):\n")
        w("            return _decode_slow(cls, header)\n")
    else:
        w("        if not isinstance(header, dict):\n")
        w("            return _decode_slow(cls, header)\n")
    for k in opt:
        w(f'        self.{k} = header.get("{k}", UNSET)\n')
    w("        return self\n")
    return cls


_MODULE_HEAD = '''\
"""Typed control-plane protocol stubs. GENERATED — DO NOT EDIT.

Generated by ``ray_tpu/_private/lint/schemagen.py`` from the rpc-schema
inference (see that module for the full wire/compat rules). To change a
method's schema, edit its handler (or a schemagen OVERLAYS entry) and
regenerate; ci/lint.sh fails on any drift between the handlers, this
module, and the schema golden:

    python -m ray_tpu._private.lint.schemagen ray_tpu/

Semantics shared by every stub:

* ``to_header()`` emits required fields always and optional fields only
  when set; ``X.from_header(h).to_header() == h`` for any valid ``h``.
* ``from_header()`` tolerates unknown keys (compat rule: old receivers
  must survive new senders), fills ``_COMPAT_DEFAULTS`` for required
  keys a pre-deprecation-window peer omits, and raises a typed
  ``ProtocolError`` for anything else missing.
* Absent optional fields read as the ``UNSET`` sentinel; ``stub.get(
  "field", default)`` mirrors ``dict.get``.
"""

PROTOCOL_VERSION = {version}
MIN_PROTOCOL_VERSION = {min_version}


class _Unset:
    """Singleton marking an optional field absent from the frame."""

    __slots__ = ()

    def __repr__(self):
        return "<UNSET>"

    def __bool__(self):
        return False


UNSET = _Unset()


class ProtocolError(TypeError):
    """A frame violating a generated method schema (missing required
    key with no compat default, or a non-dict header)."""

    def __init__(self, method, kind, detail):
        super().__init__(f"{{method}} {{kind}}: {{detail}}")
        self.method = method
        self.kind = kind
        self.detail = detail


def negotiate(peer_version):
    """The protocol version two peers speak: min(ours, theirs), floored
    at MIN_PROTOCOL_VERSION (an unparseable/absent advertisement reads
    as the floor — the pre-versioning wire)."""
    try:
        pv = int(peer_version)
    except (TypeError, ValueError):
        pv = MIN_PROTOCOL_VERSION
    return max(MIN_PROTOCOL_VERSION, min(PROTOCOL_VERSION, pv))


def _decode_slow(cls, header):
    """Shared miss-path decode: compat defaults, typed errors, open-
    schema extras. The generated fast paths are plain subscripts and
    only fall through here on a miss."""
    if not isinstance(header, dict):
        raise ProtocolError(
            cls.METHOD, cls.KIND,
            f"header is {{type(header).__name__}}, not a dict")
    self = cls.__new__(cls)
    missing = []
    for k in sorted(cls._REQUIRED):
        if k in header:
            setattr(self, k, header[k])
        elif k in cls._COMPAT_DEFAULTS:
            # deprecation-window tolerance: a peer predating this
            # required key decodes as the documented default
            setattr(self, k, cls._COMPAT_DEFAULTS[k])
        else:
            missing.append(k)
    if missing:
        raise ProtocolError(cls.METHOD, cls.KIND,
                            "missing required key(s) " + ", ".join(missing))
    for k in sorted(cls._OPTIONAL):
        setattr(self, k, header.get(k, UNSET))
    if cls._OPEN:
        known = cls._REQUIRED | cls._OPTIONAL
        self._extras = {{k: v for k, v in header.items()
                        if k not in known}}
    return self


class _StubBase:
    """Base for the generated stubs (slots-only; near-zero overhead)."""

    __slots__ = ()

    METHOD = ""
    KIND = ""
    _REQUIRED = frozenset()
    _OPTIONAL = frozenset()
    _COMPAT_DEFAULTS = {{}}
    _OPEN = False

    def get(self, name, default=None):
        """``dict.get`` for optional fields: default when UNSET."""
        value = getattr(self, name, UNSET)
        return default if value is UNSET else value

    def to_header(self):
        raise NotImplementedError

    def __repr__(self):
        return f"{{type(self).__name__}}({{self.to_header()!r}})"

    def __eq__(self, other):
        return type(other) is type(self) and \\
            other.to_header() == self.to_header()

    __hash__ = None
'''


def emit_protocol(spec: dict, version: int = PROTOCOL_VERSION,
                  generate: Sequence[str] = GENERATE) -> str:
    """The full generated module source for ``generate`` methods found
    in ``spec`` (a missing method is an error: the handler family a
    stub anchors must exist)."""
    missing = [m for m in generate if m not in spec]
    if missing:
        raise ValueError(
            f"cannot generate stubs for unregistered method(s): "
            f"{', '.join(missing)} — no handler found by inference")
    out = io.StringIO()
    out.write(_MODULE_HEAD.format(version=version,
                                  min_version=MIN_PROTOCOL_VERSION))
    entries = []
    for method in sorted(generate):
        ms = spec[method]
        err = _check_keys(method, ms["request"]) or \
            (None if ms["reply"]["open"]
             else _check_keys(method, ms["reply"]))
        if err:
            raise ValueError(err)
        req_cls = _emit_class(out, method, "request", ms["request"],
                              ms["handlers"])
        reply = ms["reply"]
        if not reply["open"] and (reply["required"] or reply["optional"]):
            reply_cls = _emit_class(out, method, "reply", reply,
                                    ms["handlers"])
        else:
            # open reply (e.g. future-based handlers) or a bare-{} ack:
            # nothing to type on the reply side
            reply_cls = "None"
        entries.append((method, req_cls, reply_cls))
    out.write("\n\n# method -> (request stub, reply stub or None)\n")
    out.write("GENERATED_METHODS = {\n")
    for method, req_cls, reply_cls in entries:
        out.write(f'    "{method}": ({req_cls}, {reply_cls}),\n')
    out.write("}\n")
    return out.getvalue()


def compile_protocol(source: str, name: str = "_ray_tpu_protocol_gen"):
    """Exec a generated module source into a fresh module object — how
    the interop tests materialize an OLD protocol from a snapshot."""
    import types

    mod = types.ModuleType(name)
    exec(compile(source, f"<{name}>", "exec"), mod.__dict__)
    return mod


# ----------------------------------------------------------- drift gate

def _diff(expected: str, actual: str, what: str) -> List[str]:
    lines = list(difflib.unified_diff(
        actual.splitlines(), expected.splitlines(),
        fromfile=f"{what} (checked in)", tofile=f"{what} (regenerated)",
        lineterm="", n=2))
    return lines[:120]


def check_program(program, golden_path: str = GOLDEN_PATH,
                  protocol_path: str = PROTOCOL_PATH,
                  generate: Optional[Sequence[str]] = None,
                  contracts_path: str = CONTRACTS_PATH) -> List[str]:
    """Drift findings for an already-built Program; [] = in sync."""
    findings: List[str] = []
    try:
        spec = build_spec(program)
    except ValueError as e:
        return [f"schema inference failed: {e}"]
    try:
        with open(golden_path, "r", encoding="utf-8") as f:
            golden_text = f.read()
    except OSError:
        golden_text = ""
    # Emit at the CURRENT version: bumping PROTOCOL_VERSION without
    # regenerating is itself drift (both artifacts stamp the version).
    expected_golden = emit_golden(spec, PROTOCOL_VERSION)
    if golden_text != expected_golden:
        findings.append(
            f"schema golden is stale: {golden_path} no longer matches "
            f"the schemas inferred from the handlers")
        findings.extend(_diff(expected_golden, golden_text,
                              os.path.basename(golden_path)))
    # Third artifact: the error-contract table. Adding a raise to (or
    # removing one from) a handler's escaping raise-set without
    # regenerating is drift exactly like a schema edit.
    expected_contracts = emit_contracts(build_contracts(program),
                                        PROTOCOL_VERSION)
    try:
        with open(contracts_path, "r", encoding="utf-8") as f:
            contracts_text = f.read()
    except OSError:
        contracts_text = ""
    if contracts_text != expected_contracts:
        findings.append(
            f"error-contract golden is stale: {contracts_path} no "
            f"longer matches the raise-sets inferred from the handlers")
        findings.extend(_diff(expected_contracts, contracts_text,
                              os.path.basename(contracts_path)))
    try:
        expected_proto = emit_protocol(
            spec, PROTOCOL_VERSION,
            GENERATE if generate is None else generate)
    except ValueError as e:
        findings.append(f"stub generation failed: {e}")
        return findings
    try:
        with open(protocol_path, "r", encoding="utf-8") as f:
            proto_text = f.read()
    except OSError:
        proto_text = ""
    if proto_text != expected_proto:
        findings.append(
            f"generated stubs are stale: {protocol_path} does not "
            f"match what the current handler schemas generate")
        findings.extend(_diff(expected_proto, proto_text,
                              os.path.basename(protocol_path)))
    if findings:
        findings.append(
            "regenerate with: python -m ray_tpu._private.lint.schemagen "
            "ray_tpu/")
    return findings


def check_paths(paths: Sequence[str], golden_path: str = GOLDEN_PATH,
                protocol_path: str = PROTOCOL_PATH) -> List[str]:
    from ray_tpu._private.lint.callgraph import build_program
    from ray_tpu._private.lint.engine import load_modules
    return check_program(build_program(load_modules(paths)),
                         golden_path, protocol_path)


# ------------------------------------------------------------------ CLI

def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu._private.lint.schemagen",
        description="generate (or drift-check) the typed control-plane "
                    "protocol stubs from the rpc-schema inference")
    parser.add_argument("paths", nargs="*", default=[],
                        help="files or directories to infer from "
                             "(default: ray_tpu/)")
    parser.add_argument("--check", action="store_true",
                        help="verify protocol.py and the schema golden "
                             "match the current inference; exit 1 on "
                             "drift (the ci/lint.sh gate)")
    parser.add_argument("--from-snapshot", metavar="FILE",
                        help="generate from a saved schema snapshot "
                             "instead of live inference")
    parser.add_argument("--stdout", action="store_true",
                        help="print the generated module instead of "
                             "writing the checked-in files")
    parser.add_argument("--version", type=int, default=PROTOCOL_VERSION,
                        help="protocol version to stamp (snapshot "
                             "builds; default: current)")
    args = parser.parse_args(argv)

    paths = args.paths or ["ray_tpu"]
    if args.from_snapshot:
        with open(args.from_snapshot, "r", encoding="utf-8") as f:
            snap = json.load(f)
        version = snap.get("protocol_version", args.version) \
            if isinstance(snap, dict) else args.version
        spec = spec_from_snapshot(snap)
        source = emit_protocol(
            spec, version, [m for m in GENERATE if m in spec])
        if args.stdout:
            sys.stdout.write(source)
            return 0
        print("error: --from-snapshot requires --stdout (snapshot "
              "builds never overwrite the checked-in protocol)",
              file=sys.stderr)
        return 2
    for p in paths:
        if not os.path.exists(p):
            print(f"error: no such path: {p}", file=sys.stderr)
            return 2

    if args.check:
        findings = check_paths(paths)
        for line in findings:
            print(line, file=sys.stderr)
        if findings:
            print("schemagen: DRIFT — handlers, protocol.py and the "
                  "golden disagree (see diff above)", file=sys.stderr)
            return 1
        print("schemagen: protocol.py and schema golden in sync "
              f"(protocol version {PROTOCOL_VERSION})")
        return 0

    from ray_tpu._private.lint.callgraph import build_program
    from ray_tpu._private.lint.engine import load_modules
    program = build_program(load_modules(paths))
    spec = build_spec(program)
    source = emit_protocol(spec)
    golden = emit_golden(spec)
    contracts = emit_contracts(build_contracts(program))
    if args.stdout:
        sys.stdout.write(source)
        return 0
    with open(PROTOCOL_PATH, "w", encoding="utf-8") as f:
        f.write(source)
    with open(GOLDEN_PATH, "w", encoding="utf-8") as f:
        f.write(golden)
    with open(CONTRACTS_PATH, "w", encoding="utf-8") as f:
        f.write(contracts)
    n_contracts = contracts.count('"handlers"')
    print(f"schemagen: wrote {PROTOCOL_PATH} "
          f"({len([m for m in GENERATE if m in spec])} methods), "
          f"{GOLDEN_PATH} ({len(spec)} schemas) and "
          f"{CONTRACTS_PATH} ({n_contracts} error contracts, "
          f"protocol version {PROTOCOL_VERSION})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
