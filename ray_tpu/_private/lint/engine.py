"""raylint engine: rule registry, file walking, pragmas, reporting.

Rules are two-phase so whole-package contracts (rpc-contract's
client-string vs handler-registration cross-check, lock-discipline's
cross-module acquisition graph) see every module before judging:

    rule.collect(module) -> per-module violations (and side tables)
    rule.finalize()      -> cross-module violations

Pragmas are line-anchored comments, honoured for a violation on the
same line or the line directly above it:

    # raylint: disable=<rule>[,<rule>...]
    # raylint: disable-file=<rule>[,<rule>...]   (anywhere in the file)

``disable=all`` suppresses every rule at that anchor. The engine (not
individual rules) applies suppression, so finalize()-phase violations
honour pragmas exactly like collect()-phase ones. The engine also
tracks which pragmas actually suppressed something: ``--stale-pragmas``
reports the anchors that suppress nothing (rule renamed, violation
long since fixed) so pragma justifications can't rot.

Since v2 the engine also hands every rule a whole-program substrate
before collect() runs: ``callgraph.build_program`` turns the parsed
modules into one ``Program`` (symbol table, call graph, RPC index)
passed to each rule via ``rule.setup(program)``. rpc-schema and
async-blocking's transitive mode are built on it; the JSON reporter
serializes its inferred per-method schemas as ``rpc_schemas``.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Set, Type

_PRAGMA_RE = re.compile(
    r"#\s*raylint:\s*(disable(?:-file)?)\s*=\s*([A-Za-z0-9_,\- ]+)")

# Directories never worth parsing (caches, build artifacts).
_SKIP_DIRS = {"__pycache__", "_native_cache", ".git", "build", "dist"}


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    path: str       # path as reported (relative to the scan root)
    line: int       # 1-indexed
    col: int        # 0-indexed (ast convention)
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Pragma:
    """One ``# raylint: disable[-file]=`` comment anchor. ``used`` is
    flipped by the engine when the anchor suppresses a violation — the
    raw material of the stale-pragma report."""
    lineno: int
    kind: str            # "line" | "file"
    rules: Set[str]
    used: bool = False


class Module:
    """One parsed source file plus the lookup tables rules share."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.syntax_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            self.syntax_error = e
        self.pragmas: List[Pragma] = []
        # line -> pragmas anchored at that line; file-kind pragmas apply
        # everywhere. "all" disables every rule at the anchor.
        self._line_pragmas: Dict[int, List[Pragma]] = {}
        self._file_pragmas: List[Pragma] = []
        for lineno, text in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(text)
            if not m:
                continue
            # First whitespace-delimited token per comma piece: trailing
            # justification text ("disable=r — why") never leaks into
            # the rule name.
            rules = {piece.split()[0] for piece in m.group(2).split(",")
                     if piece.strip()}
            kind = "file" if m.group(1) == "disable-file" else "line"
            pragma = Pragma(lineno, kind, rules)
            self.pragmas.append(pragma)
            if kind == "file":
                self._file_pragmas.append(pragma)
            else:
                self._line_pragmas.setdefault(lineno, []).append(pragma)

    def suppressed(self, v: Violation) -> bool:
        """True if a pragma suppresses ``v``; marks every matching
        anchor as used (line and file anchors both, if both match)."""
        hit = False
        for pragma in self._file_pragmas:
            if {"all", v.rule} & pragma.rules:
                pragma.used = True
                hit = True
        for anchor in (v.line, v.line - 1):
            for pragma in self._line_pragmas.get(anchor, ()):
                if {"all", v.rule} & pragma.rules:
                    pragma.used = True
                    hit = True
        return hit


class Rule:
    """Base class. Subclasses set ``name`` and override collect()
    (per-module) and optionally finalize() (cross-module). Rules that
    need whole-program context (the call graph, the RPC index) override
    setup(), which runs once before any collect() with the shared
    ``callgraph.Program`` built from every parsed module."""

    name = ""
    description = ""

    def setup(self, program) -> None:
        pass

    def collect(self, module: Module) -> Iterable[Violation]:
        return ()

    def finalize(self) -> Iterable[Violation]:
        return ()


_REGISTRY: Dict[str, Type[Rule]] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    assert cls.name and cls.name not in _REGISTRY, cls
    _REGISTRY[cls.name] = cls
    return cls


def all_rules() -> Dict[str, Type[Rule]]:
    # Import side effect registers the built-in rules exactly once.
    from ray_tpu._private.lint import rules as _rules  # noqa: F401
    return dict(_REGISTRY)


# ------------------------------------------------------------- AST helpers

def dotted_name(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' for anything dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif parts:
        parts.append("?")  # call()/subscript[] etc. at the chain root
    return ".".join(reversed(parts))


def first_str_arg(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def walk_functions(tree: ast.AST):
    """Yield (funcdef, qualname, class_name) for every function/method,
    including nested ones."""
    stack: List[tuple] = [(tree, [], "")]
    while stack:
        node, quals, cls = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = quals + [child.name]
                yield child, ".".join(q), cls
                stack.append((child, q, cls))
            elif isinstance(child, ast.ClassDef):
                stack.append((child, quals + [child.name], child.name))
            else:
                stack.append((child, quals, cls))


def body_nodes(func: ast.AST):
    """Walk a function body WITHOUT descending into nested function or
    class definitions (their bodies run in a different context)."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


# ----------------------------------------------------------------- driver

def iter_py_files(paths: Sequence[str]) -> List[str]:
    """Every .py file under ``paths``, deduplicated by realpath:
    overlapping arguments (``ray_tpu/ ray_tpu/_private``) must not
    double-report every violation in the overlap."""
    out: List[str] = []
    seen: Set[str] = set()

    def add(f: str):
        real = os.path.realpath(f)
        if real not in seen:
            seen.add(real)
            out.append(f)

    for p in paths:
        if os.path.isfile(p):
            add(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
            for f in sorted(files):
                if f.endswith(".py"):
                    add(os.path.join(root, f))
    return out


def analyze_modules(modules: List[Module],
                    rule_names: Optional[Sequence[str]] = None
                    ) -> tuple:
    """Run the rules over ``modules``; returns (violations, program).
    The callgraph.Program is built once and handed to every rule via
    setup() before any collect() runs."""
    registry = all_rules()
    names = list(rule_names) if rule_names else sorted(registry)
    unknown = [n for n in names if n not in registry]
    if unknown:
        raise ValueError(f"unknown rule(s): {', '.join(unknown)}; "
                         f"known: {', '.join(sorted(registry))}")
    rules = [registry[n]() for n in names]
    by_path = {m.path: m for m in modules}
    from ray_tpu._private.lint.callgraph import build_program
    program = build_program(modules)
    for rule in rules:
        rule.setup(program)
    violations: List[Violation] = []
    for m in modules:
        if m.syntax_error is not None:
            violations.append(Violation(
                "syntax-error", m.path, m.syntax_error.lineno or 0, 0,
                f"file does not parse: {m.syntax_error.msg}"))
            continue
        for rule in rules:
            violations.extend(rule.collect(m))
    for rule in rules:
        violations.extend(rule.finalize())
    violations = [v for v in violations
                  if v.path not in by_path or not by_path[v.path].suppressed(v)]
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return violations, program


def lint_modules(modules: List[Module],
                 rule_names: Optional[Sequence[str]] = None
                 ) -> List[Violation]:
    return analyze_modules(modules, rule_names)[0]


def find_stale_pragmas(modules: Sequence[Module],
                       rule_names: Optional[Sequence[str]] = None
                       ) -> List[Violation]:
    """Pragma anchors that suppressed nothing in the run that just
    completed (call AFTER analyze_modules — suppression marks usage).

    A dead pragma is tribal knowledge rotting in place: the rule was
    renamed, or the violation it justified was fixed. Reported as
    ``stale-pragma`` findings that the CLI treats as warnings (they
    never affect the exit code). Pragmas naming rules outside the run
    subset are skipped — only a run that actually exercised the rule
    can judge its pragmas."""
    registry = set(all_rules())
    ran = set(rule_names) if rule_names else registry
    full_run = ran >= registry
    out: List[Violation] = []
    for m in modules:
        if m.syntax_error is not None:
            continue
        for pragma in m.pragmas:
            if pragma.used:
                continue
            names = pragma.rules
            if "all" in names:
                if not full_run:
                    continue
                reason = "suppresses nothing"
            else:
                unknown = names - registry
                if unknown:
                    if not full_run:
                        continue
                    reason = ("names unknown rule(s) "
                              f"{', '.join(sorted(unknown))} — renamed?")
                elif not names <= ran:
                    continue     # rule not exercised: cannot judge
                else:
                    reason = "suppresses nothing"
            out.append(Violation(
                "stale-pragma", m.path, pragma.lineno, 0,
                f"`# raylint: disable{'-file' if pragma.kind == 'file' else ''}"
                f"={','.join(sorted(names))}` {reason} — the violation it "
                "justified is gone; delete the pragma so the next reader "
                "doesn't inherit a dead justification"))
    out.sort(key=lambda v: (v.path, v.line))
    return out


def fault_coverage(modules: Sequence[Module],
                   tests_path: str = "tests") -> dict:
    """Cross-reference the faultpoints wired into the scanned tree
    (``fire("name")`` / ``async_fire("name")`` call sites) against the
    chaos/test corpus under ``tests_path``: a point armed NOWHERE is
    dead fault-injection surface — the failure path it guards has no
    schedule driving it. Warn-only by contract: the report never
    changes the exit code (a new faultpoint should not break CI, it
    should show up here until a schedule adopts it)."""
    wired = set()
    for m in modules:
        if m.tree is None:
            continue
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Call) and \
                    dotted_name(node.func).rsplit(".", 1)[-1] in (
                        "fire", "async_fire"):
                name = first_str_arg(node)
                if name:
                    wired.add(name)
    corpus = []
    if os.path.isdir(tests_path):
        for f in iter_py_files([tests_path]):
            with open(f, "r", encoding="utf-8", errors="replace") as fh:
                corpus.append(fh.read())
    blob = "\n".join(corpus)
    armed = {name for name in wired if name in blob}
    return {
        "tests_path": tests_path,
        "wired": sorted(wired),
        "armed": sorted(armed),
        "unarmed": sorted(wired - armed),
    }


def load_modules(paths: Sequence[str]) -> List[Module]:
    modules = []
    for f in iter_py_files(paths):
        with open(f, "r", encoding="utf-8", errors="replace") as fh:
            modules.append(Module(f, fh.read()))
    return modules


def lint_paths(paths: Sequence[str],
               rule_names: Optional[Sequence[str]] = None
               ) -> tuple:
    """Returns (violations, files_scanned)."""
    modules = load_modules(paths)
    return lint_modules(modules, rule_names), len(modules)


def lint_sources(sources: Dict[str, str],
                 rule_names: Optional[Sequence[str]] = None
                 ) -> List[Violation]:
    """Lint in-memory {path: source} — the test-fixture entry point."""
    return lint_modules([Module(p, s) for p, s in sources.items()],
                        rule_names)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu._private.lint",
        description="raylint: static analysis for the ray_tpu control "
                    "plane (see RULES.md for the rule catalogue)")
    parser.add_argument("paths", nargs="*", default=[],
                        help="files or directories to lint")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--rules", default="",
                        help="comma-separated subset of rules to run")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--stale-pragmas", action="store_true",
                        help="also report `# raylint: disable=` anchors "
                             "that suppress nothing (warn-only: never "
                             "affects the exit code)")
    parser.add_argument("--stale-pragmas-error", action="store_true",
                        help="like --stale-pragmas, but stale anchors "
                             "FAIL the run (exit 1) — the CI posture: "
                             "a pragma that suppresses nothing is a "
                             "fixed bug whose waiver must be deleted")
    parser.add_argument("--dump-schemas", action="store_true",
                        help="print the inferred RPC header schema for "
                             "every registered method as JSON and exit "
                             "(the rpc-schema rule's view of the wire "
                             "contract)")
    parser.add_argument("--drift-check", action="store_true",
                        help="also run the schemagen drift gate "
                             "(generated protocol.py + schema golden + "
                             "error-contract golden vs the current "
                             "inference) on the SAME parsed program — "
                             "the single-pass ci/lint.sh gate; drift "
                             "fails the run like a violation")
    parser.add_argument("--fault-coverage", nargs="?", const="tests",
                        default=None, metavar="TESTS_DIR",
                        help="cross-reference wired faultpoints against "
                             "the test corpus (default: tests/) and "
                             "report points armed nowhere — warn-only, "
                             "never affects the exit code")
    args = parser.parse_args(argv)

    if args.list_rules:
        for name, cls in sorted(all_rules().items()):
            print(f"{name}: {cls.description}")
        return 0
    if not args.paths:
        parser.print_usage(sys.stderr)
        print("error: no paths given", file=sys.stderr)
        return 2
    for p in args.paths:
        if not os.path.exists(p):
            print(f"error: no such path: {p}", file=sys.stderr)
            return 2
    if args.dump_schemas:
        from ray_tpu._private.lint.callgraph import build_program
        from ray_tpu._private.lint.rules.rpc_schema import schemas_as_dict
        # sort_keys so repeated runs (any hash seed) emit byte-identical
        # output — the golden the schemagen drift gate diffs against is
        # derived from this table
        print(json.dumps(schemas_as_dict(
            build_program(load_modules(args.paths))), indent=2,
            sort_keys=True))
        return 0

    rule_names = [r.strip() for r in args.rules.split(",") if r.strip()] \
        or None
    modules = load_modules(args.paths)
    try:
        violations, program = analyze_modules(modules, rule_names)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    stale = find_stale_pragmas(modules, rule_names) \
        if (args.stale_pragmas or args.stale_pragmas_error) else []
    drift: List[str] = []
    if args.drift_check:
        from ray_tpu._private.lint.schemagen import check_program
        drift = check_program(program)
    coverage = fault_coverage(modules, args.fault_coverage) \
        if args.fault_coverage else None

    if args.format == "json":
        from ray_tpu._private.lint.rules.rpc_deadlock import \
            wait_graph_report
        from ray_tpu._private.lint.rules.rpc_schema import schemas_as_dict
        from ray_tpu._private.lint.schemagen import (
            PROTOCOL_VERSION, build_contracts)
        active = rule_names or sorted(all_rules())
        counts = {name: 0 for name in active}
        for v in violations:
            counts[v.rule] = counts.get(v.rule, 0) + 1
        print(json.dumps({
            "violations": [v.as_dict() for v in violations],
            # Per-rule finding totals, zeros included: a rule that ran
            # and found nothing is distinguishable from one not run.
            "violation_counts": counts,
            "stale_pragmas": [v.as_dict() for v in stale],
            "files_scanned": len(modules),
            "rules": active,
            # The wire version the generated stubs speak (see
            # _private/protocol.py + lint/schemagen.py).
            "protocol_version": PROTOCOL_VERSION,
            # Drift-gate findings (--drift-check): empty = handlers,
            # protocol.py and the schema golden agree.
            "schema_drift": drift,
            # Inferred wire schema per RPC method (ci/lint.sh artifact):
            # what each handler requires/accepts and what its replies
            # can carry — the protocol-debugging companion table.
            "rpc_schemas": schemas_as_dict(program),
            # Cross-process synchronous-wait edges + cycle verdicts
            # (the rpc-deadlock rule's full graph): the reviewer's
            # audit surface for every blocking RPC dependency.
            "rpc_wait_for_graph": wait_graph_report(program),
            # Per-RPC-method error contract (excflow raise-set
            # inference): what awaiting this method can raise, what
            # its handlers sink-store, and its error-signal reply
            # keys. Frozen as error_contracts_golden.json and
            # drift-gated alongside the schemas.
            "error_contracts": build_contracts(program),
            # --fault-coverage: wired faultpoints vs the test corpus
            # (warn-only; null when the flag was not passed).
            "fault_coverage": coverage,
        }, indent=2, sort_keys=True))
    else:
        for v in violations:
            print(v.render())
        for v in stale:
            sev = "error" if args.stale_pragmas_error else "warning"
            print(f"{sev}: {v.render()}")
        for line in drift:
            print(line, file=sys.stderr)
        if coverage is not None:
            for name in coverage["unarmed"]:
                print(f"warning: fault-coverage: point `{name}` is "
                      f"wired but armed nowhere under "
                      f"{coverage['tests_path']}/ — no schedule drives "
                      f"its failure path")
        status = "clean" if not violations else \
            f"{len(violations)} violation(s)"
        if stale:
            qual = "" if args.stale_pragmas_error else " [warn-only]"
            status += f", {len(stale)} stale pragma(s){qual}"
        if args.drift_check:
            status += ", schema drift" if drift else ", schemas in sync"
        if coverage is not None:
            status += (f", fault coverage {len(coverage['armed'])}/"
                       f"{len(coverage['wired'])} armed")
        print(f"raylint: {len(modules)} file(s), {status}")
    if args.stale_pragmas_error and stale:
        return 1
    return 1 if violations or drift else 0
