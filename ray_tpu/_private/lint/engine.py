"""raylint engine: rule registry, file walking, pragmas, reporting.

Rules are two-phase so whole-package contracts (rpc-contract's
client-string vs handler-registration cross-check, lock-discipline's
cross-module acquisition graph) see every module before judging:

    rule.collect(module) -> per-module violations (and side tables)
    rule.finalize()      -> cross-module violations

Pragmas are line-anchored comments, honoured for a violation on the
same line or the line directly above it:

    # raylint: disable=<rule>[,<rule>...]
    # raylint: disable-file=<rule>[,<rule>...]   (anywhere in the file)

``disable=all`` suppresses every rule at that anchor. The engine (not
individual rules) applies suppression, so finalize()-phase violations
honour pragmas exactly like collect()-phase ones.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Set, Type

_PRAGMA_RE = re.compile(
    r"#\s*raylint:\s*(disable(?:-file)?)\s*=\s*([A-Za-z0-9_,\- ]+)")

# Directories never worth parsing (caches, build artifacts).
_SKIP_DIRS = {"__pycache__", "_native_cache", ".git", "build", "dist"}


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    path: str       # path as reported (relative to the scan root)
    line: int       # 1-indexed
    col: int        # 0-indexed (ast convention)
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class Module:
    """One parsed source file plus the lookup tables rules share."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.syntax_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            self.syntax_error = e
        # line -> rules disabled at that line; "all" disables every rule
        self.line_disables: Dict[int, Set[str]] = {}
        self.file_disables: Set[str] = set()
        for lineno, text in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(text)
            if not m:
                continue
            # First whitespace-delimited token per comma piece: trailing
            # justification text ("disable=r — why") never leaks into
            # the rule name.
            rules = {piece.split()[0] for piece in m.group(2).split(",")
                     if piece.strip()}
            if m.group(1) == "disable-file":
                self.file_disables |= rules
            else:
                self.line_disables.setdefault(lineno, set()).update(rules)

    def suppressed(self, v: Violation) -> bool:
        if {"all", v.rule} & self.file_disables:
            return True
        for anchor in (v.line, v.line - 1):
            rules = self.line_disables.get(anchor)
            if rules and {"all", v.rule} & rules:
                return True
        return False


class Rule:
    """Base class. Subclasses set ``name`` and override collect()
    (per-module) and optionally finalize() (cross-module)."""

    name = ""
    description = ""

    def collect(self, module: Module) -> Iterable[Violation]:
        return ()

    def finalize(self) -> Iterable[Violation]:
        return ()


_REGISTRY: Dict[str, Type[Rule]] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    assert cls.name and cls.name not in _REGISTRY, cls
    _REGISTRY[cls.name] = cls
    return cls


def all_rules() -> Dict[str, Type[Rule]]:
    # Import side effect registers the built-in rules exactly once.
    from ray_tpu._private.lint import rules as _rules  # noqa: F401
    return dict(_REGISTRY)


# ------------------------------------------------------------- AST helpers

def dotted_name(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' for anything dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif parts:
        parts.append("?")  # call()/subscript[] etc. at the chain root
    return ".".join(reversed(parts))


def first_str_arg(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def walk_functions(tree: ast.AST):
    """Yield (funcdef, qualname, class_name) for every function/method,
    including nested ones."""
    stack: List[tuple] = [(tree, [], "")]
    while stack:
        node, quals, cls = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = quals + [child.name]
                yield child, ".".join(q), cls
                stack.append((child, q, cls))
            elif isinstance(child, ast.ClassDef):
                stack.append((child, quals + [child.name], child.name))
            else:
                stack.append((child, quals, cls))


def body_nodes(func: ast.AST):
    """Walk a function body WITHOUT descending into nested function or
    class definitions (their bodies run in a different context)."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


# ----------------------------------------------------------------- driver

def iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
            out.extend(os.path.join(root, f)
                       for f in sorted(files) if f.endswith(".py"))
    return out


def lint_modules(modules: List[Module],
                 rule_names: Optional[Sequence[str]] = None
                 ) -> List[Violation]:
    registry = all_rules()
    names = list(rule_names) if rule_names else sorted(registry)
    unknown = [n for n in names if n not in registry]
    if unknown:
        raise ValueError(f"unknown rule(s): {', '.join(unknown)}; "
                         f"known: {', '.join(sorted(registry))}")
    rules = [registry[n]() for n in names]
    by_path = {m.path: m for m in modules}
    violations: List[Violation] = []
    for m in modules:
        if m.syntax_error is not None:
            violations.append(Violation(
                "syntax-error", m.path, m.syntax_error.lineno or 0, 0,
                f"file does not parse: {m.syntax_error.msg}"))
            continue
        for rule in rules:
            violations.extend(rule.collect(m))
    for rule in rules:
        violations.extend(rule.finalize())
    violations = [v for v in violations
                  if v.path not in by_path or not by_path[v.path].suppressed(v)]
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return violations


def lint_paths(paths: Sequence[str],
               rule_names: Optional[Sequence[str]] = None
               ) -> tuple:
    """Returns (violations, files_scanned)."""
    files = iter_py_files(paths)
    modules = []
    for f in files:
        with open(f, "r", encoding="utf-8", errors="replace") as fh:
            modules.append(Module(f, fh.read()))
    return lint_modules(modules, rule_names), len(files)


def lint_sources(sources: Dict[str, str],
                 rule_names: Optional[Sequence[str]] = None
                 ) -> List[Violation]:
    """Lint in-memory {path: source} — the test-fixture entry point."""
    return lint_modules([Module(p, s) for p, s in sources.items()],
                        rule_names)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu._private.lint",
        description="raylint: static analysis for the ray_tpu control "
                    "plane (see RULES.md for the rule catalogue)")
    parser.add_argument("paths", nargs="*", default=[],
                        help="files or directories to lint")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--rules", default="",
                        help="comma-separated subset of rules to run")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for name, cls in sorted(all_rules().items()):
            print(f"{name}: {cls.description}")
        return 0
    if not args.paths:
        parser.print_usage(sys.stderr)
        print("error: no paths given", file=sys.stderr)
        return 2
    for p in args.paths:
        if not os.path.exists(p):
            print(f"error: no such path: {p}", file=sys.stderr)
            return 2
    rule_names = [r.strip() for r in args.rules.split(",") if r.strip()] \
        or None
    try:
        violations, nfiles = lint_paths(args.paths, rule_names)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.format == "json":
        print(json.dumps({
            "violations": [v.as_dict() for v in violations],
            "files_scanned": nfiles,
            "rules": rule_names or sorted(all_rules()),
        }, indent=2))
    else:
        for v in violations:
            print(v.render())
        status = "clean" if not violations else \
            f"{len(violations)} violation(s)"
        print(f"raylint: {nfiles} file(s), {status}")
    return 1 if violations else 0
