"""Asyncio RPC transport: framed msgpack control plane with raw byte frames.

Role-equivalent of the reference's templated gRPC layer (reference:
src/ray/rpc/grpc_server.h, client_call.h): every control-plane service
(GCS, raylet, core worker) is an ``RpcServer`` with named async handlers,
and clients hold one multiplexed connection per peer. Large payloads travel
as separate length-prefixed raw frames after the msgpack envelope so object
data is never re-encoded by msgpack.

Wire format per message:
    [u32 body_len][msgpack body][u64 buf_len + raw bytes] * nbufs
    body = [kind, seq, method, header, nbufs]
kinds: 0=request 1=reply 2=error 3=push (one-way).

Headers for the high-traffic methods are typed through the generated
stubs in ``_private/protocol.py`` (schema-checked at lint time by the
rpc-schema/protocol-stub rules, drift-gated by lint/schemagen.py). The
protocol version negotiated at registration lands on
``Connection.peer_protocol_version``; the envelope itself never changes
shape, so mixed-version peers always frame-interoperate.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import pickle
import socket
import struct
import threading
import time
from typing import Any, Awaitable, Callable, Dict, List, Optional, Sequence, Tuple

import cloudpickle
import msgpack

from ray_tpu._private import faultpoints

logger = logging.getLogger(__name__)


class _HandlerStats:
    """Per-process, per-handler RPC latency accounting (reference: the
    instrumented-asio per-handler event stats, C4 —
    src/ray/common/asio/instrumented_io_context.h stats_ tracking).
    SINGLE-WRITER CONTRACT (audited for raylint; the benign-race
    fixture in tests/test_lint.py encodes this decision): ``note()`` is
    called only from the process's IO-loop thread — every handler,
    sync-fast-path or task-wrapped, runs there — so the [count, total,
    max] cells have exactly one writer and need no lock. ``snapshot()``
    may run on a foreign thread (metrics scrape): it takes
    ``list(self._stats.items())`` in one C-level call (atomic under the
    GIL) and tolerates values read mid-update — monotonic counters can
    be one tick stale, never torn, because each cell mutation is a
    single STORE_SUBSCR. Guarding this with a lock would put an
    acquire/release on every RPC for no observable difference."""

    def __init__(self):
        self._stats: Dict[str, list] = {}

    def note(self, method: str, dt: float) -> None:
        e = self._stats.get(method)
        if e is None:
            e = self._stats[method] = [0, 0.0, 0.0]
        e[0] += 1
        e[1] += dt
        if dt > e[2]:
            e[2] = dt

    def snapshot(self) -> Dict[str, dict]:
        out = {}
        for method, (count, total, mx) in list(self._stats.items()):
            out[method] = {
                "count": count,
                "mean_ms": round(total / count * 1e3, 3) if count else 0.0,
                "total_s": round(total, 3),
                "max_ms": round(mx * 1e3, 3),
            }
        return out


handler_stats = _HandlerStats()

KIND_REQUEST = 0
KIND_REPLY = 1
KIND_ERROR = 2
KIND_PUSH = 3

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

Handler = Callable[["Connection", Any, List[bytes]], Awaitable[Any]]

# Write-buffer size above which senders apply backpressure by awaiting
# drain. Below it, writes are fire-and-forget into the transport buffer —
# one syscall per event-loop flush instead of one drain await per message.
DRAIN_HIGH_WATER = 4 * 1024 * 1024


def _pack_msg(kind: int, seq: int, method: str, header: Any,
              bufs: Sequence[bytes]) -> List[bytes]:
    """Frames are any buffer objects (bytes, or LIVE memoryviews on
    the zero-copy data plane — pickle-5 frames, shm chunk slices);
    they ride to the socket as-is, never flattened. Length framing
    uses nbytes: len(memoryview) counts elements, not bytes."""
    body = msgpack.packb([kind, seq, method, header, len(bufs)],
                         use_bin_type=True)
    parts = [_U32.pack(len(body)), body]
    for b in bufs:
        parts.append(_U64.pack(
            b.nbytes if isinstance(b, memoryview) else len(b)))
        parts.append(b)
    return parts


def _try_parse_msg(buf: bytearray, pos: int, env_cache: list):
    """Parse ONE complete message from ``buf`` starting at ``pos``.

    Returns ``(msg, next_pos)`` on success or ``(None, needed)`` where
    ``needed`` is the minimum total buffer length before a retry can
    possibly succeed (so partially-received large frames aren't
    re-parsed on every arriving TCP chunk). Parsing is synchronous —
    the recv loop awaits the socket once per chunk, not per field
    (profiled: readexactly per length prefix cost ~6us/message).

    ``env_cache`` is a one-slot list caching the decoded msgpack
    envelope of the HEAD message across retries: a multi-buf message
    trickling in over several chunks would otherwise re-decode its
    body at every buf-length threshold. The caller clears it when a
    message completes (only the head message is ever parsed)."""
    n = len(buf)
    if n - pos < 4:
        return None, pos + 4
    (body_len,) = _U32.unpack_from(buf, pos)
    p = pos + 4
    if n - p < body_len:
        return None, p + body_len
    if env_cache[0] is not None:
        kind, seq, method, header, nbufs = env_cache[0]
    else:
        kind, seq, method, header, nbufs = env = msgpack.unpackb(
            memoryview(buf)[p:p + body_len], raw=False)
        env_cache[0] = env
    p += body_len
    if nbufs == 0:
        return (kind, seq, method, header, []), p
    bufs = []
    for _ in range(nbufs):
        if n - p < 8:
            return None, p + 8
        (blen,) = _U64.unpack_from(buf, p)
        p += 8
        if n - p < blen:
            return None, p + blen
        bufs.append(bytes(memoryview(buf)[p:p + blen]))
        p += blen
    return (kind, seq, method, header, bufs), p


class Connection:
    """One duplex connection. Used symmetrically: either side can issue
    requests and pushes once established (workers serve PushTask on the same
    connection they used to register, like the reference's bidirectional
    core-worker channels)."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 handlers: Dict[str, Handler], peer_name: str = "?"):
        self.reader = reader
        self.writer = writer
        self.handlers = handlers
        self.peer_name = peer_name
        self._seq = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._closed = False
        # Write coalescing: messages buffer here and flush once per loop
        # iteration — one syscall for a whole burst of small messages
        # instead of one sendmsg each (~120us apiece on this box).
        self._loop = asyncio.get_running_loop()
        self._out: List[bytes] = []
        self._flush_scheduled = False
        # Serializes writer.drain(): pre-3.12 FlowControlMixin supports
        # only ONE drain waiter per transport (single _drain_waiter slot).
        self._drain_lock = asyncio.Lock()
        self.on_disconnect: List[Callable[["Connection"], None]] = []
        # Wire-protocol version negotiated with this peer (see
        # _private/protocol.py). Stamped by the registration handshakes
        # (GCS RegisterNode sets it server-side, the raylet sets it on
        # its gcs_conn from the reply); None = peer never advertised,
        # treat as MIN_PROTOCOL_VERSION. The transport itself is
        # deliberately version-blind — versioning rides header keys,
        # never the envelope, so old and new framing interoperate.
        self.peer_protocol_version: Optional[int] = None
        # Arbitrary per-connection state stamped by services (worker id etc).
        self.tags: Dict[str, Any] = {}
        self._recv_task: Optional[asyncio.Task] = None

    def start(self):
        self._recv_task = asyncio.get_running_loop().create_task(self._recv_loop())

    def _write_nowait(self, parts: List[bytes]):
        """Coalescing buffered write (loop thread only): parts land in the
        out-buffer and flush once per loop iteration."""
        if self._closed:
            raise ConnectionError(f"connection to {self.peer_name} is closed")
        self._out.extend(parts)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            self._loop.call_soon(self._flush)

    def _flush(self):
        self._flush_scheduled = False
        if self._closed or not self._out:
            self._out.clear()
            return
        out, self._out = self._out, []
        try:
            if len(out) > 8 and sum(map(len, out)) < 262144:
                # A burst of small messages: one join + one send beats a
                # long iovec through sendmsg (memcpy is cheaper than the
                # kernel's per-iovec accounting at these sizes). Bursts
                # carrying big raw frames scatter-write instead — no
                # extra copy on the data plane.
                self.writer.write(b"".join(out))
            else:
                self.writer.writelines(out)
        except Exception:
            self._mark_closed()

    def _needs_drain(self) -> bool:
        transport = self.writer.transport
        return (transport is not None and
                transport.get_write_buffer_size() > DRAIN_HIGH_WATER)

    async def _drain(self):
        async with self._drain_lock:
            await self.writer.drain()

    async def _send(self, parts: List[bytes]):
        self._write_nowait(parts)
        if self._needs_drain():
            await self._drain()

    def call_nowait(self, method: str, header: Any = None,
                    bufs: Sequence[bytes] = ()) -> asyncio.Future:
        """Issue a request without a coroutine round trip (loop thread
        only). Returns the reply future; the pending entry is dropped by a
        done callback so abandoned futures don't leak."""
        seq = next(self._seq)
        fut = asyncio.get_running_loop().create_future()
        self._pending[seq] = fut
        fut.add_done_callback(lambda f: self._pending.pop(seq, None))
        if faultpoints.armed:
            # fault plane: a dropped request is never written (the
            # caller's timeout governs), a duplicated one is written
            # twice (handler idempotence probe), a severed connection
            # fails every pending future right here. NOTE: this is a
            # sync seam on the loop thread, so an armed ``delay``
            # blocks the WHOLE loop — deliberately: it models loop
            # occupancy/GIL stalls (the failure mode the heartbeat
            # timeout was widened for), not per-message latency.
            act = faultpoints.fire("rpc.call.send", method=method,
                                   peer=self.peer_name)
            if act == "drop":
                return fut
            if act == "sever":
                self._mark_closed()
                return fut
            if act == "duplicate":
                self._write_nowait(
                    _pack_msg(KIND_REQUEST, seq, method, header, bufs))
        self._write_nowait(_pack_msg(KIND_REQUEST, seq, method, header, bufs))
        return fut

    async def call(self, method: str, header: Any = None,
                   bufs: Sequence[bytes] = (), timeout: float | None = None):
        fut = self.call_nowait(method, header, bufs)
        if self._needs_drain():
            await self._drain()
        if timeout is not None:
            return await asyncio.wait_for(fut, timeout)
        return await fut

    async def push(self, method: str, header: Any = None,
                   bufs: Sequence[bytes] = ()):
        """One-way message; no reply expected."""
        await self._send(_pack_msg(KIND_PUSH, 0, method, header, bufs))

    def push_nowait(self, method: str, header: Any = None,
                    bufs: Sequence[bytes] = ()):
        """One-way message from the loop thread, coalesced like replies
        (used for streamed per-task actor results and the streaming-
        lease pushes: GrantLeaseCredits, ReportLeaseDemand). Routes
        through the same ``rpc.call.send`` fault seam as requests so
        chaos schedules can drop/sever/duplicate the one-way lanes too
        — a lost credit grant is a first-class failure mode."""
        if faultpoints.armed:
            act = faultpoints.fire("rpc.call.send", method=method,
                                   peer=self.peer_name)
            if act == "drop":
                return
            if act == "sever":
                self._mark_closed()
                return
            if act == "duplicate":
                self._write_nowait(
                    _pack_msg(KIND_PUSH, 0, method, header, bufs))
        self._write_nowait(_pack_msg(KIND_PUSH, 0, method, header, bufs))

    async def _recv_loop(self):
        read = self.reader.read
        buf = bytearray()
        pos = 0
        needed = 4
        env_cache = [None]
        try:
            while True:
                chunk = await read(262144)
                if not chunk:
                    break  # EOF
                if pos:
                    del buf[:pos]
                    needed -= pos
                    pos = 0
                buf += chunk
                if len(buf) < needed:
                    continue
                while True:
                    msg, p = _try_parse_msg(buf, pos, env_cache)
                    if msg is None:
                        needed = p
                        break
                    pos = p
                    env_cache[0] = None
                    self._dispatch(*msg)
                if pos == len(buf):
                    buf.clear()
                    pos = 0
                    needed = 4
        except (ConnectionError, OSError):
            pass
        except Exception:
            logger.exception("rpc recv loop error (peer %s)", self.peer_name)
        finally:
            self._mark_closed()

    def _dispatch(self, kind, seq, method, header, bufs):
        if kind == KIND_REPLY:
            fut = self._pending.get(seq)
            if fut is not None and not fut.done():
                fut.set_result((header, bufs))
        elif kind == KIND_REQUEST:
            handler = self.handlers.get(method)
            if handler is not None and \
                    getattr(handler, "rpc_sync", False):
                # Sync fast path: no per-request asyncio.Task. The
                # handler returns a reply tuple or a Future.
                self._handle_sync(handler, seq, method, header, bufs)
                return
            self._loop.create_task(
                self._handle(seq, method, header, bufs))
        elif kind == KIND_PUSH:
            handler = self.handlers.get(method)
            if handler is None:
                logger.warning("no handler for push %s", method)
            else:
                self._loop.create_task(
                    self._run_push(handler, header, bufs))
        elif kind == KIND_ERROR:
            fut = self._pending.get(seq)
            if fut is not None and not fut.done():
                fut.set_exception(pickle.loads(bufs[0]))

    async def _run_push(self, handler, header, bufs):
        try:
            await handler(self, header, bufs)
        except Exception:
            logger.exception("push handler error")

    def _reply_nowait(self, seq: int, method: str, result):
        if isinstance(result, tuple) and len(result) == 2 and \
                isinstance(result[1], (list, tuple)):
            rheader, rbufs = result
        else:
            rheader, rbufs = result, ()
        if faultpoints.armed and self._fault_reply(method):
            return
        try:
            self._write_nowait(_pack_msg(KIND_REPLY, seq, method, rheader, rbufs))
        except (ConnectionError, OSError):
            self._mark_closed()

    def _fault_reply(self, method: str) -> bool:
        """Server-side reply fault seam (both the sync fast path and
        the task-wrapped path route through here): True = the reply
        must NOT be sent. ``drop`` loses only the reply — the handler
        already ran, so the caller's retry probes idempotence; ``sever``
        tears the connection down mid-reply (the reference failure for
        "did my mutation land?" client logic). Sync seam on the loop
        thread: an armed ``delay`` here stalls the whole loop by
        design (loop-occupancy fault), like ``rpc.call.send``."""
        act = faultpoints.fire("rpc.reply.send", method=method,
                               peer=self.peer_name)
        if act == "drop":
            return True
        if act == "sever":
            self._mark_closed()
            return True
        return False

    def _reply_error_nowait(self, seq: int, method: str, e: BaseException):
        try:
            payload = cloudpickle.dumps(e)
        except Exception:
            payload = cloudpickle.dumps(RuntimeError(repr(e)))
        try:
            self._write_nowait(_pack_msg(KIND_ERROR, seq, method, None, [payload]))
        except (ConnectionError, OSError):
            self._mark_closed()

    def _handle_sync(self, handler, seq: int, method: str, header, bufs):
        """Dispatch a handler marked ``rpc_sync``: called inline on the
        recv loop; may return a Future for deferred replies."""
        t0 = time.monotonic()
        try:
            result = handler(self, header, bufs)
        except Exception as e:  # noqa: BLE001 — propagate to caller
            handler_stats.note(method, time.monotonic() - t0)
            self._reply_error_nowait(seq, method, e)
            return
        if isinstance(result, asyncio.Future):
            def _on_done(fut: asyncio.Future):
                handler_stats.note(method, time.monotonic() - t0)
                if fut.cancelled():
                    self._reply_error_nowait(
                        seq, method, RuntimeError(f"{method} cancelled"))
                elif fut.exception() is not None:
                    self._reply_error_nowait(seq, method, fut.exception())
                else:
                    self._reply_nowait(seq, method, fut.result())
            result.add_done_callback(_on_done)
        else:
            handler_stats.note(method, time.monotonic() - t0)
            self._reply_nowait(seq, method, result)

    async def _handle(self, seq: int, method: str, header, bufs):
        handler = self.handlers.get(method)
        t0 = time.monotonic()
        try:
            if handler is None:
                raise RuntimeError(f"no handler for method {method!r}")
            try:
                result = await handler(self, header, bufs)
            finally:
                # raising handlers count too — the misbehaving methods
                # are exactly the ones latency stats must show
                handler_stats.note(method, time.monotonic() - t0)
            if isinstance(result, tuple) and len(result) == 2 and \
                    isinstance(result[1], (list, tuple)):
                rheader, rbufs = result
            else:
                rheader, rbufs = result, ()
            if faultpoints.armed and self._fault_reply(method):
                return
            await self._send(_pack_msg(KIND_REPLY, seq, method, rheader, rbufs))
        except (ConnectionError, OSError):
            self._mark_closed()
        except Exception as e:  # noqa: BLE001 — propagate to caller
            try:
                # raylint: disable=async-blocking — bounded error reply (one exception object)
                payload = cloudpickle.dumps(e)
            except Exception:
                # raylint: disable=async-blocking — same bounded error path
                payload = cloudpickle.dumps(RuntimeError(repr(e)))
            try:
                await self._send(_pack_msg(KIND_ERROR, seq, method, None, [payload]))
            except (ConnectionError, OSError):
                self._mark_closed()

    def _mark_closed(self):
        if self._closed:
            return
        self._closed = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionError(
                    f"connection to {self.peer_name} lost"))
        self._pending.clear()
        # snapshot: callbacks may unregister themselves (or siblings)
        # from the live list mid-iteration, which would skip entries
        for cb in list(self.on_disconnect):
            try:
                cb(self)
            except Exception:
                logger.exception("on_disconnect callback failed")
        try:
            self.writer.close()
        except (OSError, RuntimeError):
            pass  # transport already torn down / loop already closed

    @property
    def closed(self) -> bool:
        return self._closed

    async def close(self):
        self._mark_closed()
        # Reap the recv loop on an EXTERNAL close: the transport
        # teardown delivers it EOF eventually, but a loop shutting down
        # right after close() (chaos teardown, tests) would otherwise
        # destroy a still-pending task and log noise. The loop's own
        # finally path never reaches here (it IS the current task).
        task = self._recv_task
        if task is not None and not task.done() and \
                task is not asyncio.current_task():
            task.cancel()
            await asyncio.gather(task, return_exceptions=True)


class RpcServer:
    """Listens on tcp://host:port or unix://path; spawns a Connection per
    client, dispatching to ``handlers``."""

    def __init__(self, handlers: Dict[str, Handler], name: str = "server"):
        self.handlers = handlers
        self.name = name
        self.address: str = ""
        self._server: Optional[asyncio.AbstractServer] = None
        self.connections: List[Connection] = []
        self.on_connect: List[Callable[[Connection], None]] = []

    async def _on_client(self, reader, writer):
        conn = Connection(reader, writer, self.handlers,
                          peer_name=f"client-of-{self.name}")
        self.connections.append(conn)
        conn.on_disconnect.append(lambda c: self.connections.remove(c)
                                  if c in self.connections else None)
        for cb in self.on_connect:
            cb(conn)
        conn.start()

    async def listen(self, address: str = "") -> str:
        if address.startswith("unix://"):
            path = address[len("unix://"):]
            if os.path.exists(path):
                os.unlink(path)
            self._server = await asyncio.start_unix_server(self._on_client, path=path)
            self.address = address
        else:
            host, port = "127.0.0.1", 0
            if address.startswith("tcp://"):
                hp = address[len("tcp://"):]
                host, _, p = hp.rpartition(":")
                port = int(p)
            self._server = await asyncio.start_server(
                self._on_client, host=host, port=port,
                family=socket.AF_INET)
            port = self._server.sockets[0].getsockname()[1]
            self.address = f"tcp://{host}:{port}"
        return self.address

    async def close(self):
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for conn in list(self.connections):
            await conn.close()


async def connect(address: str, handlers: Dict[str, Handler] | None = None,
                  timeout: float = 10.0, retry_interval: float = 0.05,
                  peer_name: str = "") -> Connection:
    """Dial an RpcServer, retrying until ``timeout`` (the server process may
    still be booting)."""
    deadline = asyncio.get_running_loop().time() + timeout
    last_err: Exception | None = None
    while True:
        try:
            if address.startswith("unix://"):
                reader, writer = await asyncio.open_unix_connection(
                    address[len("unix://"):])
            else:
                hp = address[len("tcp://"):] if address.startswith("tcp://") else address
                host, _, p = hp.rpartition(":")
                reader, writer = await asyncio.open_connection(host, int(p))
            break
        except (ConnectionError, OSError, FileNotFoundError) as e:
            last_err = e
            if asyncio.get_running_loop().time() > deadline:
                raise ConnectionError(
                    f"could not connect to {address}: {last_err}") from last_err
            await asyncio.sleep(retry_interval)
    conn = Connection(reader, writer, handlers or {},
                      peer_name=peer_name or address)
    conn.start()
    return conn


class EventLoopThread:
    """A dedicated asyncio loop on a daemon thread.

    The public API (``get``/``put``/``remote``) is synchronous like the
    reference's; all IO runs on this loop (the analog of the core worker's
    internal io_service, reference: src/ray/core_worker/core_worker.h
    io_service_ member).
    """

    def __init__(self, name: str = "ray-tpu-io"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro, timeout: float | None = None):
        """Run a coroutine on the loop from a foreign thread, blocking."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def call_soon(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self):
        async def _drain():
            tasks = [t for t in asyncio.all_tasks(self.loop)
                     if t is not asyncio.current_task()]
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)

        if self.loop.is_running():
            try:
                asyncio.run_coroutine_threadsafe(
                    _drain(), self.loop).result(timeout=3)
            except Exception:
                logger.debug("loop drain at stop failed", exc_info=True)
            self.loop.call_soon_threadsafe(self.loop.stop)
            self._thread.join(timeout=5)
        if not self.loop.is_closed():
            self.loop.close()
