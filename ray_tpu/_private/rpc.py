"""Asyncio RPC transport: framed msgpack control plane with raw byte frames.

Role-equivalent of the reference's templated gRPC layer (reference:
src/ray/rpc/grpc_server.h, client_call.h): every control-plane service
(GCS, raylet, core worker) is an ``RpcServer`` with named async handlers,
and clients hold one multiplexed connection per peer. Large payloads travel
as separate length-prefixed raw frames after the msgpack envelope so object
data is never re-encoded by msgpack.

Wire format per message:
    [u32 body_len][msgpack body][u64 buf_len + raw bytes] * nbufs
    body = [kind, seq, method, header, nbufs]
kinds: 0=request 1=reply 2=error 3=push (one-way).

Headers for the high-traffic methods are typed through the generated
stubs in ``_private/protocol.py`` (schema-checked at lint time by the
rpc-schema/protocol-stub rules, drift-gated by lint/schemagen.py). The
protocol version negotiated at registration lands on
``Connection.peer_protocol_version``; the envelope itself never changes
shape, so mixed-version peers always frame-interoperate.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import pickle
import socket
import struct
import threading
import time
from collections import deque
from typing import Any, Awaitable, Callable, Dict, List, Optional, Sequence, Tuple

import cloudpickle
import msgpack

from ray_tpu._private import faultpoints

logger = logging.getLogger(__name__)


# Per-method latency histogram boundaries (seconds) for the Prometheus
# export — control-plane RPCs live in the 100us..1s band; the tails are
# exactly what the flight recorder exists to catch.
RPC_LATENCY_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
                       5.0)

# Strong references for fire-and-forget tasks: asyncio itself keeps
# only a WEAK reference to running tasks, so a spawn whose handle is
# dropped can be garbage-collected mid-flight with its exception
# swallowed. Tasks park here until done (set mutation is GIL-atomic —
# multiple loops in one process, e.g. a head node, share it safely).
_SPAWNED: set = set()
_spawn_errors = None


def _spawn_error_counter():
    global _spawn_errors
    if _spawn_errors is None:
        from ray_tpu._private.metrics import Counter
        _spawn_errors = Counter(
            "ray_tpu_background_task_errors_total",
            "Exceptions raised by fire-and-forget background tasks "
            "(rpc.spawn_logged), labeled by task name.")
    return _spawn_errors


def spawn_logged(coro, what: str, loop=None) -> "asyncio.Task":
    """Tracked fire-and-forget: create a task, hold a strong reference
    until it finishes, and turn an unhandled exception into a log line
    plus a ``ray_tpu_background_task_errors_total`` count instead of a
    silent GC-time mutter. Returns the task (callers may still await
    or cancel it). ``what`` labels the spawn in logs and metrics."""
    if loop is None:
        loop = asyncio.get_event_loop()
    task = loop.create_task(coro)
    _SPAWNED.add(task)

    def _done(t, _what=what):
        _SPAWNED.discard(t)
        if t.cancelled():
            return
        exc = t.exception()
        if exc is not None:
            try:
                _spawn_error_counter().inc(labels={"what": _what})
            except Exception as me:  # metrics must not mask the log line
                logger.debug("background-task error counter "
                             "unavailable: %r", me)
            logger.error("background task %r died: %r", _what, exc,
                         exc_info=exc)

    task.add_done_callback(_done)
    return task


def _pct_block(samples: Sequence[float]) -> dict:
    """Percentile summary (ms) of a latency reservoir; ``{"count": 0}``
    when empty (metrics.percentile raises on empty input)."""
    from ray_tpu._private.metrics import percentile

    # list(deque) is one C-level copy under the GIL — safe against a
    # concurrent single-writer append (sorted() over a live deque is
    # not: deques raise on mutation-during-iteration).
    lat = sorted(list(samples))
    if not lat:
        return {"count": 0}
    return {
        "count": len(lat),
        "p50_ms": round(percentile(lat, 0.50) * 1e3, 3),
        "p90_ms": round(percentile(lat, 0.90) * 1e3, 3),
        "p99_ms": round(percentile(lat, 0.99) * 1e3, 3),
        "max_ms": round(lat[-1] * 1e3, 3),
    }


class _WindowedMax:
    """Rotating two-bucket max: ``read()`` reports the worst of the
    last one-to-two windows and ages out entirely after two quiet
    windows (a method or loop that goes silent must not pin its last
    spike forever). Shared by the per-method cells and the loop probes
    so the roll/expiry logic cannot diverge by copy."""

    __slots__ = ("win_max", "prev_max", "win_start")

    def __init__(self):
        self.win_max = 0.0
        self.prev_max = 0.0
        self.win_start = time.monotonic()

    def note(self, value: float, window: float) -> None:
        now = time.monotonic()
        if now - self.win_start >= window:
            # roll: the finished window becomes "previous"; a gap of
            # 2+ windows means both buckets are stale — start fresh
            self.prev_max = self.win_max \
                if now - self.win_start < 2 * window else 0.0
            self.win_max = 0.0
            self.win_start = now
        if value > self.win_max:
            self.win_max = value

    def read(self, window: float) -> float:
        age = time.monotonic() - self.win_start
        if age >= 2 * window:
            return 0.0
        if age >= window:
            return self.win_max
        return max(self.win_max, self.prev_max)


class _MethodStats:
    """One wire method's cells, one side (server or client).

    SINGLE-WRITER CONTRACT (audited for raylint; the benign-race
    fixture in tests/test_lint.py encodes this decision): every mutator
    runs only on the process's IO-loop thread — handlers, sync-fast-
    path replies, client done-callbacks and push sends all run there —
    so the counter cells have exactly one writer and need no lock.
    Snapshots may run on a foreign thread (metrics scrape): counters
    can be one tick stale, never torn (each mutation is a single
    STORE_ATTR / GIL-atomic deque append, and reservoirs are copied
    with one C-level ``list()`` call before sorting). Guarding this
    with a lock would put an acquire/release on every RPC for no
    observable difference.

    ``max`` is a WINDOWED max (two rotating buckets of
    ``telemetry.window_s``): dashboards see the worst of the last one
    to two windows, not an all-time high-water mark a restart ago.
    Reservoirs are bounded deques that drop OLDEST when full —
    recency-biased percentiles — with the drop count derivable (and
    reported) as ``count - len(reservoir)``."""

    __slots__ = ("count", "errors", "timeouts", "inflight", "total",
                 "queue_total", "bytes_in", "bytes_out", "push_count",
                 "push_bytes", "wmax",
                 "lat_res", "queue_res", "lat_buckets", "queue_buckets")

    def __init__(self, reservoir: int):
        self.count = 0
        self.errors = 0
        self.timeouts = 0
        self.inflight = 0
        self.total = 0.0
        self.queue_total = 0.0
        self.bytes_in = 0
        self.bytes_out = 0
        self.push_count = 0
        self.push_bytes = 0
        self.wmax = _WindowedMax()
        self.lat_res: "deque[float]" = deque(maxlen=reservoir)
        self.queue_res: "deque[float]" = deque(maxlen=reservoir)
        self.lat_buckets = [0] * (len(RPC_LATENCY_BUCKETS) + 1)
        self.queue_buckets = [0] * (len(RPC_LATENCY_BUCKETS) + 1)

    def _note_max(self, dt: float, window: float) -> None:
        self.wmax.note(dt, window)

    def windowed_max(self, window: float) -> float:
        return self.wmax.read(window)

    @staticmethod
    def _bucket(buckets: list, value: float) -> None:
        for i, b in enumerate(RPC_LATENCY_BUCKETS):
            if value <= b:
                buckets[i] += 1
                return
        buckets[-1] += 1


class _LoopProbe:
    """ONE event loop's lag probe (the ``instrumented_io_context``
    analog): ``tick()`` rides the existing periodic loops (raylet
    heartbeat, core-worker metrics report, GCS liveness monitor — no
    new thread, no own timer) and measures how long a READY callback
    waits for the loop: ``call_soon`` at t0, stamp the delta when the
    callback actually runs. That delta IS the queueing delay every
    other callback on this loop is currently paying. Samples feed a
    bounded reservoir + windowed max; a lag above
    ``loop_slow_callback_threshold_ms`` logs a WARNING and counts into
    the owner's process-wide ``slow_callbacks`` (slow *handlers* are
    attributed by name in ``note_server`` and count there too — the
    loop was occupied either way).

    Probes are NAMED, one per component loop
    (``RpcTelemetry.loop_probe("raylet"|"core"|"gcs")``): an
    in-process head runs the raylet and the driver CoreWorker on
    DIFFERENT loop threads, and a stall on one must never be shipped
    as lag of the other — each component ticks and snapshots its own
    probe, keeping the single-writer contract per cell."""

    __slots__ = ("owner", "name", "ticks", "lag_res", "wmax",
                 "_pending")

    def __init__(self, owner: "RpcTelemetry", name: str = "main"):
        self.owner = owner
        self.name = name
        self.ticks = 0
        self.lag_res: "deque[float]" = deque(maxlen=1024)
        self.wmax = _WindowedMax()
        self._pending = False

    def tick(self) -> None:
        """Schedule one lag measurement (loop thread only; one in
        flight at a time — overlapping cadences share the sample)."""
        if not self.owner.enabled or self._pending:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        self._pending = True
        loop.call_soon(self._cb, time.monotonic())

    def _cb(self, t0: float) -> None:
        self._pending = False
        lag = time.monotonic() - t0
        self.ticks += 1
        self.lag_res.append(lag)
        self.wmax.note(lag, self.owner.window_s)
        if lag * 1e3 >= self.owner.slow_ms:
            self.owner.slow_callbacks += 1
            logger.warning("event loop lag (%s): a ready callback "
                           "waited %.1f ms (threshold %.0f ms)",
                           self.name, lag * 1e3, self.owner.slow_ms)

    def snapshot(self) -> dict:
        return {
            "ticks": self.ticks,
            "slow_callbacks": self.owner.slow_callbacks,
            "lag": _pct_block(self.lag_res),
            "lag_max_ms": round(
                self.wmax.read(self.owner.window_s) * 1e3, 3),
        }


class RpcTelemetry:
    """Per-process control-plane flight recorder (reference: the
    per-handler event stats instrumented_io_context.h builds into every
    event loop, plus the client call managers' latency tracking).

    Server side (per method): exec-time reservoir percentiles, QUEUE
    delay (frame arrival -> handler start — loop scheduling, separated
    from exec so "the loop was busy" and "the handler was slow" are
    distinguishable), bytes in/out, in-flight and error counts, and a
    windowed max. Client side (per method): call latency, error and
    timeout/cancel counts, bytes written, push count/bytes. Plus the
    loop-lag probe, connection redial count, and a bounded drained ring
    of SLOW CALL records (anything over ``slow_ms``) that feeds
    ``timeline()``'s cat="rpc" slices.

    All bounded, drop-counted, single-writer on the IO-loop thread
    (see _MethodStats). Shipped cross-process piggybacked on the
    existing cadences: raylets on the heartbeat, workers/drivers on the
    metrics-report loop (``ReportRpcTelemetry``)."""

    SLOW_CALLS_MAX = 256

    def __init__(self):
        self.enabled = True
        self.reservoir = 512
        self.slow_ms = 200.0
        self.window_s = 60.0
        self.server: Dict[str, _MethodStats] = {}
        self.client: Dict[str, _MethodStats] = {}
        self.redials = 0
        # process-wide: slow handlers (note_server) + slow lag samples
        # from ANY probe — "something occupied an event loop too long"
        self.slow_callbacks = 0
        # named per-loop probes (see _LoopProbe): each component ticks
        # and ships its OWN loop's probe — an in-process head's driver
        # loop stall must never read as raylet loop lag
        self.probes: Dict[str, _LoopProbe] = {}
        self.loop = self.loop_probe("main")
        self._slow: "deque[dict]" = deque()
        # MONOTONIC drop total; drain reports deltas against
        # _slow_dropped_flushed (the series' honest-truncation rule: a
        # zero-reset would race a concurrent _note_slow increment from
        # another loop thread into a lost or re-reported drop)
        self.slow_dropped = 0
        self._slow_dropped_flushed = 0
        self._wire_cache: Dict[str, dict] = {}
        self._wire_ts: Dict[str, float] = {}

    def loop_probe(self, name: str = "main") -> _LoopProbe:
        p = self.probes.get(name)
        if p is None:
            p = self.probes[name] = _LoopProbe(self, name)
        return p

    def configure(self, config) -> None:
        """Apply the process config (called by Raylet/CoreWorker/GCS
        init; module-level state, so the last caller wins — components
        sharing a process share one config anyway)."""
        self.enabled = bool(
            getattr(config, "rpc_telemetry_enabled", True))
        self.reservoir = max(
            16, int(getattr(config, "rpc_telemetry_reservoir", 512)))
        self.slow_ms = float(
            getattr(config, "loop_slow_callback_threshold_ms", 200.0))
        self.window_s = max(
            1.0, float(getattr(config, "rpc_stats_window_s", 60.0)))

    def _entry(self, table: Dict[str, _MethodStats],
               method: str) -> _MethodStats:
        e = table.get(method)
        if e is None:
            e = table[method] = _MethodStats(self.reservoir)
        return e

    # ------------------------------------------------------- server side

    def note_request(self, method: str, nbytes: int) -> None:
        """Frame arrival of a request (recv loop): bytes in + in-flight."""
        e = self._entry(self.server, method)
        e.bytes_in += nbytes
        e.inflight += 1

    def note_done(self, method: str) -> None:
        """In-flight decrement WITHOUT a completion record: balances a
        note_request whose handler finished after ``enabled`` was
        flipped off (the toggle must never strand phantom in-flight
        counts)."""
        e = self.server.get(method)
        if e is not None:
            e.inflight = max(0, e.inflight - 1)

    def note_server(self, method: str, queue_dt: float, exec_dt: float,
                    bytes_out: int, error: bool, peer: str = "") -> None:
        """Handler completion (any path: task-wrapped, sync fast path,
        deferred future, raised)."""
        e = self._entry(self.server, method)
        e.count += 1
        e.inflight = max(0, e.inflight - 1)
        e.total += exec_dt
        e.queue_total += queue_dt
        e.bytes_out += bytes_out
        if error:
            e.errors += 1
        e.lat_res.append(exec_dt)
        e.queue_res.append(queue_dt)
        e._bucket(e.lat_buckets, exec_dt)
        e._bucket(e.queue_buckets, queue_dt)
        e._note_max(exec_dt, self.window_s)
        if exec_dt * 1e3 >= self.slow_ms:
            self.slow_callbacks += 1
            self._note_slow("server", method, exec_dt, queue_dt, peer)
            logger.warning(
                "slow RPC handler %s: %.1f ms exec (%.1f ms queued, "
                "threshold %.0f ms, peer %s)", method, exec_dt * 1e3,
                queue_dt * 1e3, self.slow_ms, peer)

    # ------------------------------------------------------- client side

    def note_client_send(self, method: str, nbytes: int) -> None:
        self._entry(self.client, method).bytes_out += nbytes

    def note_client(self, method: str, dt: float, fut) -> None:
        """Reply-future completion: latency + error/timeout verdict."""
        e = self._entry(self.client, method)
        e.count += 1
        e.total += dt
        if fut.cancelled():
            # wait_for timeouts cancel the reply future — counted as
            # timeouts (explicit caller cancellation lands here too)
            e.timeouts += 1
        elif fut.exception() is not None:
            e.errors += 1
        e.lat_res.append(dt)
        e._bucket(e.lat_buckets, dt)
        e._note_max(dt, self.window_s)
        if dt * 1e3 >= self.slow_ms:
            self._note_slow("client", method, dt, 0.0, "")

    def note_push(self, method: str, nbytes: int) -> None:
        e = self._entry(self.client, method)
        e.push_count += 1
        e.push_bytes += nbytes
        e.bytes_out += nbytes

    def note_redial(self) -> None:
        self.redials += 1

    # -------------------------------------------------------- slow calls

    def _note_slow(self, side: str, method: str, dur: float,
                   queue_dt: float, peer: str) -> None:
        if len(self._slow) >= self.SLOW_CALLS_MAX:
            self.slow_dropped += 1
            return
        # wall-clock ts so the record merges onto the same timeline
        # clock as tasks/objects/pulls; stamped back to the call start
        self._slow.append({
            "side": side, "method": method,
            "ts": time.time() - dur,
            "dur_ms": round(dur * 1e3, 3),
            "queue_ms": round(queue_dt * 1e3, 3),
            "peer": peer,
        })

    def drain_slow_calls(self) -> Tuple[List[dict], int]:
        """-> (records, dropped): pop everything buffered (GIL-atomic
        popleft — an append racing the drain lands in the next one) and
        the drop count since the last drain."""
        out = []
        buf = self._slow
        for _ in range(len(buf)):
            try:
                out.append(buf.popleft())
            except IndexError:
                break
        total = self.slow_dropped
        dropped = total - self._slow_dropped_flushed
        self._slow_dropped_flushed = total
        return out, dropped

    # --------------------------------------------------------- snapshots

    def _side_snapshot(self, table: Dict[str, _MethodStats],
                       percentiles: bool) -> Dict[str, dict]:
        out = {}
        window = self.window_s
        for method, e in list(table.items()):
            count = e.count
            d = {
                "count": count,
                "mean_ms": round(e.total / count * 1e3, 3)
                if count else 0.0,
                "total_s": round(e.total, 3),
                "max_ms": round(e.windowed_max(window) * 1e3, 3),
                "errors": e.errors,
                "timeouts": e.timeouts,
                "inflight": e.inflight,
                "bytes_in": e.bytes_in,
                "bytes_out": e.bytes_out,
            }
            if e.push_count:
                d["push_count"] = e.push_count
                d["push_bytes"] = e.push_bytes
            if count:
                d["queue_mean_ms"] = round(
                    e.queue_total / count * 1e3, 3)
            if percentiles:
                d["exec"] = _pct_block(e.lat_res)
                d["queue"] = _pct_block(e.queue_res)
                # reservoirs drop OLDEST when full: the honest count
                d["dropped_samples"] = max(0, count - len(e.lat_res))
            out[method] = d
        return out

    def snapshot(self, percentiles: bool = True,
                 probe: str = "main") -> dict:
        """Full snapshot. ``probe`` names the loop whose lag block to
        carry as ``loop`` — each shipping component passes its own
        ("raylet"/"core"/"gcs"), so a reporter's loop block is always
        the loop that reporter actually runs on."""
        return {
            "server": self._side_snapshot(self.server, percentiles),
            "client": self._side_snapshot(self.client, percentiles),
            "loop": self.loop_probe(probe).snapshot(),
            "redials": self.redials,
        }

    def wire(self, min_interval: float = 1.0,
             probe: str = "main") -> dict:
        """Snapshot for the shipping cadences, recomputed at most every
        ``min_interval`` seconds (per probe): sorting every reservoir
        4-20x/s on the heartbeat would buy nothing a dashboard can
        see."""
        now = time.monotonic()
        if now - self._wire_ts.get(probe, -1e9) >= min_interval:
            self._wire_cache[probe] = self.snapshot(percentiles=True,
                                                    probe=probe)
            self._wire_ts[probe] = now
        return self._wire_cache[probe]

    def handler_brief(self) -> Dict[str, dict]:
        """Compact per-handler block for heartbeat ``stats`` — the
        pre-flight-recorder ``rpc_handlers`` shape (count/mean/total/
        max), kept for the node-stats surface."""
        out = {}
        window = self.window_s
        for method, e in list(self.server.items()):
            out[method] = {
                "count": e.count,
                "mean_ms": round(e.total / e.count * 1e3, 3)
                if e.count else 0.0,
                "total_s": round(e.total, 3),
                "max_ms": round(e.windowed_max(window) * 1e3, 3),
            }
        return out

    def prom_snapshot(self) -> dict:
        """Per-method latency histograms in the metrics-registry wire
        format (metrics.py snapshot dicts) — merged into whatever this
        process already ships (heartbeat ``metrics`` key /
        ``ReportMetrics``), so the GCS renders real cumulative
        Prometheus histograms without a new transport."""
        bounds = list(RPC_LATENCY_BUCKETS)

        def hist(desc, table, buckets_of, sum_of):
            values = []
            for method, e in list(table.items()):
                buckets = buckets_of(e)
                count = sum(buckets)
                if not count:
                    continue
                values.append([[["method", method]],
                               [list(buckets), round(sum_of(e), 6),
                                count]])
            return {"kind": "histogram", "description": desc,
                    "boundaries": bounds, "values": values}

        return {
            "ray_tpu_rpc_server_seconds": hist(
                "Server-side RPC handler exec time by method",
                self.server, lambda e: e.lat_buckets,
                lambda e: e.total),
            "ray_tpu_rpc_server_queue_seconds": hist(
                "Server-side RPC queueing delay (frame arrival to "
                "handler start) by method",
                self.server, lambda e: e.queue_buckets,
                lambda e: e.queue_total),
            "ray_tpu_rpc_client_seconds": hist(
                "Client-side RPC call latency by method",
                self.client, lambda e: e.lat_buckets,
                lambda e: e.total),
        }


telemetry = RpcTelemetry()


class _HandlerStatsView:
    """Back-compat facade over ``telemetry.server`` (the old module
    global ``handler_stats``): same ``note``/``snapshot`` surface, same
    snapshot keys — ``max_ms`` is now the WINDOWED max (satellite fix:
    an all-time max made dashboards show a cold-start spike forever)."""

    def note(self, method: str, dt: float) -> None:
        telemetry.note_server(method, 0.0, dt, 0, False)

    def snapshot(self) -> Dict[str, dict]:
        return telemetry.handler_brief()


handler_stats = _HandlerStatsView()


class RpcTelemetryTable:
    """GCS-side aggregation of per-reporter telemetry snapshots (the
    queryable plane behind ``state.list_rpc()`` / ``summary_rpc()`` /
    ``/api/rpc``). Reporters that stop shipping age out on the same TTL
    as metric snapshots; slow-call records accumulate in a capped ring
    with an honest drop counter (they feed ``timeline()``'s cat="rpc"
    slices)."""

    SLOW_CALLS_MAX = 2048
    TTL_S = 60.0

    def __init__(self):
        # reporter -> (ts, snapshot)
        self._reporters: Dict[str, Tuple[float, dict]] = {}
        self.slow_calls: "deque[dict]" = deque()
        self.slow_dropped = 0

    def ingest(self, reporter: str, payload: dict) -> None:
        snap = payload.get("snapshot")
        if snap:
            self._reporters[reporter] = (time.time(), snap)
        for rec in payload.get("slow_calls") or ():
            if len(self.slow_calls) >= self.SLOW_CALLS_MAX:
                self.slow_calls.popleft()
                self.slow_dropped += 1
            self.slow_calls.append({**rec, "reporter": reporter})
        self.slow_dropped += int(payload.get("slow_calls_dropped") or 0)

    def prune(self) -> None:
        cutoff = time.time() - self.TTL_S
        for key in [k for k, (ts, _) in self._reporters.items()
                    if ts < cutoff]:
            del self._reporters[key]

    def reporters(self) -> Dict[str, dict]:
        self.prune()
        return {k: snap for k, (_, snap) in self._reporters.items()}

    def rows(self, method: Optional[str] = None,
             reporter: Optional[str] = None,
             side: Optional[str] = None) -> List[dict]:
        """Flat per-(reporter, side, method) rows, filterable: method
        substring, reporter prefix, side exact ("server"/"client")."""
        out = []
        for rep, snap in sorted(self.reporters().items()):
            if reporter and not rep.startswith(reporter):
                continue
            for sd in ("server", "client"):
                if side and sd != side:
                    continue
                for m, d in sorted((snap.get(sd) or {}).items()):
                    if method and method not in m:
                        continue
                    out.append({"reporter": rep, "side": sd,
                                "method": m, **d})
        return out

    def loops(self) -> Dict[str, dict]:
        return {rep: snap.get("loop") or {}
                for rep, snap in sorted(self.reporters().items())}

    def summary(self) -> Dict[str, dict]:
        """Cluster-wide per-method aggregate. Counts/bytes/errors/
        in-flight are summed over the SERVER rows only (every call the
        cluster saw is observed by exactly one server; summing both
        sides would double-count anything a client reporter also
        watched) — a method nothing serves (one-way pushes recorded
        client-side only) falls back to its client rows. ``timeouts``
        sums the CLIENT rows (only callers see timeouts); latency
        percentiles take the WORST row of either side (a conservative
        "slowest reporter" view, not a pooled population — the raw
        reservoirs never leave their process)."""
        per_side: Dict[str, Dict[str, dict]] = {}
        worst: Dict[str, dict] = {}
        reporters: Dict[str, set] = {}
        for row in self.rows():
            key = row["method"]
            side = row["side"]
            d = per_side.setdefault(key, {}).setdefault(side, {
                "count": 0, "errors": 0, "timeouts": 0, "inflight": 0,
                "bytes_in": 0, "bytes_out": 0})
            for k in ("count", "errors", "timeouts", "inflight",
                      "bytes_in", "bytes_out"):
                d[k] += row.get(k, 0)
            w = worst.setdefault(key, {"max_ms": 0.0,
                                       "exec_p99_ms": 0.0,
                                       "queue_p99_ms": 0.0})
            w["max_ms"] = max(w["max_ms"], row.get("max_ms", 0.0))
            w["exec_p99_ms"] = max(
                w["exec_p99_ms"],
                (row.get("exec") or {}).get("p99_ms", 0.0))
            w["queue_p99_ms"] = max(
                w["queue_p99_ms"],
                (row.get("queue") or {}).get("p99_ms", 0.0))
            reporters.setdefault(key, set()).add(row["reporter"])
        agg: Dict[str, dict] = {}
        for key, sides in per_side.items():
            src = sides.get("server") or sides["client"]
            agg[key] = {
                "count": src["count"], "errors": src["errors"],
                "timeouts": sides.get("client", {}).get("timeouts", 0),
                "inflight": src["inflight"],
                "bytes_in": src["bytes_in"],
                "bytes_out": src["bytes_out"],
                **worst[key],
                "reporters": len(reporters[key]),
                "sides": sorted(sides),
            }
        return agg

KIND_REQUEST = 0
KIND_REPLY = 1
KIND_ERROR = 2
KIND_PUSH = 3

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

Handler = Callable[["Connection", Any, List[bytes]], Awaitable[Any]]

# Write-buffer size above which senders apply backpressure by awaiting
# drain. Below it, writes are fire-and-forget into the transport buffer —
# one syscall per event-loop flush instead of one drain await per message.
DRAIN_HIGH_WATER = 4 * 1024 * 1024


def _pack_msg(kind: int, seq: int, method: str, header: Any,
              bufs: Sequence[bytes]) -> List[bytes]:
    """Frames are any buffer objects (bytes, or LIVE memoryviews on
    the zero-copy data plane — pickle-5 frames, shm chunk slices);
    they ride to the socket as-is, never flattened. Length framing
    uses nbytes: len(memoryview) counts elements, not bytes."""
    body = msgpack.packb([kind, seq, method, header, len(bufs)],
                         use_bin_type=True)
    parts = [_U32.pack(len(body)), body]
    for b in bufs:
        parts.append(_U64.pack(
            b.nbytes if isinstance(b, memoryview) else len(b)))
        parts.append(b)
    return parts


def _parts_len(parts: Sequence[Any]) -> int:
    """Wire bytes of a packed message (telemetry accounting)."""
    return sum(b.nbytes if isinstance(b, memoryview) else len(b)
               for b in parts)


def _try_parse_msg(buf: bytearray, pos: int, env_cache: list):
    """Parse ONE complete message from ``buf`` starting at ``pos``.

    Returns ``(msg, next_pos)`` on success or ``(None, needed)`` where
    ``needed`` is the minimum total buffer length before a retry can
    possibly succeed (so partially-received large frames aren't
    re-parsed on every arriving TCP chunk). Parsing is synchronous —
    the recv loop awaits the socket once per chunk, not per field
    (profiled: readexactly per length prefix cost ~6us/message).

    ``env_cache`` is a one-slot list caching the decoded msgpack
    envelope of the HEAD message across retries: a multi-buf message
    trickling in over several chunks would otherwise re-decode its
    body at every buf-length threshold. The caller clears it when a
    message completes (only the head message is ever parsed)."""
    n = len(buf)
    if n - pos < 4:
        return None, pos + 4
    (body_len,) = _U32.unpack_from(buf, pos)
    p = pos + 4
    if n - p < body_len:
        return None, p + body_len
    if env_cache[0] is not None:
        kind, seq, method, header, nbufs = env_cache[0]
    else:
        kind, seq, method, header, nbufs = env = msgpack.unpackb(
            memoryview(buf)[p:p + body_len], raw=False)
        env_cache[0] = env
    p += body_len
    if nbufs == 0:
        return (kind, seq, method, header, []), p
    bufs = []
    for _ in range(nbufs):
        if n - p < 8:
            return None, p + 8
        (blen,) = _U64.unpack_from(buf, p)
        p += 8
        if n - p < blen:
            return None, p + blen
        bufs.append(bytes(memoryview(buf)[p:p + blen]))
        p += blen
    return (kind, seq, method, header, bufs), p


class Connection:
    """One duplex connection. Used symmetrically: either side can issue
    requests and pushes once established (workers serve PushTask on the same
    connection they used to register, like the reference's bidirectional
    core-worker channels)."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 handlers: Dict[str, Handler], peer_name: str = "?"):
        self.reader = reader
        self.writer = writer
        self.handlers = handlers
        self.peer_name = peer_name
        self._seq = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._closed = False
        # Write coalescing: messages buffer here and flush once per loop
        # iteration — one syscall for a whole burst of small messages
        # instead of one sendmsg each (~120us apiece on this box).
        self._loop = asyncio.get_running_loop()
        self._out: List[bytes] = []
        self._flush_scheduled = False
        # Serializes writer.drain(): pre-3.12 FlowControlMixin supports
        # only ONE drain waiter per transport (single _drain_waiter slot).
        self._drain_lock = asyncio.Lock()
        self.on_disconnect: List[Callable[["Connection"], None]] = []
        # Wire-protocol version negotiated with this peer (see
        # _private/protocol.py). Stamped by the registration handshakes
        # (GCS RegisterNode sets it server-side, the raylet sets it on
        # its gcs_conn from the reply); None = peer never advertised,
        # treat as MIN_PROTOCOL_VERSION. The transport itself is
        # deliberately version-blind — versioning rides header keys,
        # never the envelope, so old and new framing interoperate.
        self.peer_protocol_version: Optional[int] = None
        # Arbitrary per-connection state stamped by services (worker id etc).
        self.tags: Dict[str, Any] = {}
        self._recv_task: Optional[asyncio.Task] = None

    def start(self):
        self._recv_task = asyncio.get_running_loop().create_task(self._recv_loop())

    def _write_nowait(self, parts: List[bytes]):
        """Coalescing buffered write (loop thread only): parts land in the
        out-buffer and flush once per loop iteration."""
        if self._closed:
            raise ConnectionError(f"connection to {self.peer_name} is closed")
        self._out.extend(parts)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            self._loop.call_soon(self._flush)

    def _flush(self):
        self._flush_scheduled = False
        if self._closed or not self._out:
            self._out.clear()
            return
        out, self._out = self._out, []
        try:
            if len(out) > 8 and sum(map(len, out)) < 262144:
                # A burst of small messages: one join + one send beats a
                # long iovec through sendmsg (memcpy is cheaper than the
                # kernel's per-iovec accounting at these sizes). Bursts
                # carrying big raw frames scatter-write instead — no
                # extra copy on the data plane.
                self.writer.write(b"".join(out))
            else:
                self.writer.writelines(out)
        except Exception:
            self._mark_closed()

    def _needs_drain(self) -> bool:
        transport = self.writer.transport
        return (transport is not None and
                transport.get_write_buffer_size() > DRAIN_HIGH_WATER)

    async def _drain(self):
        async with self._drain_lock:
            await self.writer.drain()

    async def _send(self, parts: List[bytes]):
        self._write_nowait(parts)
        if self._needs_drain():
            await self._drain()

    def call_nowait(self, method: str, header: Any = None,
                    bufs: Sequence[bytes] = ()) -> asyncio.Future:
        """Issue a request without a coroutine round trip (loop thread
        only). Returns the reply future; the pending entry is dropped by a
        done callback so abandoned futures don't leak."""
        seq = next(self._seq)
        fut = asyncio.get_running_loop().create_future()
        self._pending[seq] = fut
        if telemetry.enabled:
            # one combined done callback: pending cleanup + client-side
            # per-method latency/outcome accounting (batched transports
            # amortize this — one note per PushTasks batch, never per
            # task)
            t0 = time.monotonic()

            def _done(f, _m=method, _t0=t0, _s=seq):
                self._pending.pop(_s, None)
                telemetry.note_client(_m, time.monotonic() - _t0, f)

            fut.add_done_callback(_done)
        else:
            fut.add_done_callback(lambda f: self._pending.pop(seq, None))
        parts = _pack_msg(KIND_REQUEST, seq, method, header, bufs)
        if telemetry.enabled:
            telemetry.note_client_send(method, _parts_len(parts))
        if faultpoints.armed:
            # fault plane: a dropped request is never written (the
            # caller's timeout governs), a duplicated one is written
            # twice (handler idempotence probe), a severed connection
            # fails every pending future right here. NOTE: this is a
            # sync seam on the loop thread, so an armed ``delay``
            # blocks the WHOLE loop — deliberately: it models loop
            # occupancy/GIL stalls (the failure mode the heartbeat
            # timeout was widened for), not per-message latency.
            act = faultpoints.fire("rpc.call.send", method=method,
                                   peer=self.peer_name)
            if act == "drop":
                return fut
            if act == "sever":
                self._mark_closed()
                return fut
            if act == "duplicate":
                self._write_nowait(list(parts))
        self._write_nowait(parts)
        return fut

    async def call(self, method: str, header: Any = None,
                   bufs: Sequence[bytes] = (), timeout: float | None = None):
        fut = self.call_nowait(method, header, bufs)
        if self._needs_drain():
            await self._drain()
        if timeout is not None:
            return await asyncio.wait_for(fut, timeout)
        return await fut

    async def push(self, method: str, header: Any = None,
                   bufs: Sequence[bytes] = ()):
        """One-way message; no reply expected."""
        parts = _pack_msg(KIND_PUSH, 0, method, header, bufs)
        if telemetry.enabled:
            telemetry.note_push(method, _parts_len(parts))
        await self._send(parts)

    def push_nowait(self, method: str, header: Any = None,
                    bufs: Sequence[bytes] = ()):
        """One-way message from the loop thread, coalesced like replies
        (used for streamed per-task actor results and the streaming-
        lease pushes: GrantLeaseCredits, ReportLeaseDemand). Routes
        through the same ``rpc.call.send`` fault seam as requests so
        chaos schedules can drop/sever/duplicate the one-way lanes too
        — a lost credit grant is a first-class failure mode."""
        parts = _pack_msg(KIND_PUSH, 0, method, header, bufs)
        if telemetry.enabled:
            telemetry.note_push(method, _parts_len(parts))
        if faultpoints.armed:
            act = faultpoints.fire("rpc.call.send", method=method,
                                   peer=self.peer_name)
            if act == "drop":
                return
            if act == "sever":
                self._mark_closed()
                return
            if act == "duplicate":
                self._write_nowait(list(parts))
        self._write_nowait(parts)

    async def _recv_loop(self):
        read = self.reader.read
        buf = bytearray()
        pos = 0
        needed = 4
        env_cache = [None]
        try:
            while True:
                chunk = await read(262144)
                if not chunk:
                    break  # EOF
                # frame-arrival stamp, ONE clock read per chunk (not per
                # message): queueing delay for every request parsed out
                # of this chunk is measured from here to handler start
                arr_ts = time.monotonic() if telemetry.enabled else 0.0
                if pos:
                    del buf[:pos]
                    needed -= pos
                    pos = 0
                buf += chunk
                if len(buf) < needed:
                    continue
                while True:
                    start = pos
                    msg, p = _try_parse_msg(buf, pos, env_cache)
                    if msg is None:
                        needed = p
                        break
                    pos = p
                    env_cache[0] = None
                    self._dispatch(*msg, arr_ts, p - start)
                if pos == len(buf):
                    buf.clear()
                    pos = 0
                    needed = 4
        except (ConnectionError, OSError):
            pass
        except Exception:
            logger.exception("rpc recv loop error (peer %s)", self.peer_name)
        finally:
            self._mark_closed()

    def _dispatch(self, kind, seq, method, header, bufs,
                  arr_ts=0.0, nbytes=0):
        if kind == KIND_REPLY:
            fut = self._pending.get(seq)
            if fut is not None and not fut.done():
                fut.set_result((header, bufs))
        elif kind == KIND_REQUEST:
            if arr_ts:
                telemetry.note_request(method, nbytes)
            handler = self.handlers.get(method)
            if handler is not None and \
                    getattr(handler, "rpc_sync", False):
                # Sync fast path: no per-request asyncio.Task. The
                # handler returns a reply tuple or a Future.
                self._handle_sync(handler, seq, method, header, bufs,
                                  arr_ts)
                return
            spawn_logged(
                self._handle(seq, method, header, bufs, arr_ts),
                f"rpc-handle:{method}", loop=self._loop)
        elif kind == KIND_PUSH:
            handler = self.handlers.get(method)
            if handler is None:
                logger.warning("no handler for push %s", method)
            else:
                spawn_logged(
                    self._run_push(handler, header, bufs),
                    f"rpc-push:{method}", loop=self._loop)
        elif kind == KIND_ERROR:
            fut = self._pending.get(seq)
            if fut is not None and not fut.done():
                fut.set_exception(pickle.loads(bufs[0]))

    async def _run_push(self, handler, header, bufs):
        try:
            await handler(self, header, bufs)
        except Exception:
            logger.exception("push handler error")

    def _reply_nowait(self, seq: int, method: str, result) -> int:
        """Write the reply; returns the wire byte count (0 when the
        reply was faulted away or the connection is gone)."""
        if isinstance(result, tuple) and len(result) == 2 and \
                isinstance(result[1], (list, tuple)):
            rheader, rbufs = result
        else:
            rheader, rbufs = result, ()
        if faultpoints.armed and self._fault_reply(method):
            return 0
        parts = _pack_msg(KIND_REPLY, seq, method, rheader, rbufs)
        try:
            self._write_nowait(parts)
        except (ConnectionError, OSError):
            self._mark_closed()
            return 0
        return _parts_len(parts) if telemetry.enabled else 0

    def _fault_reply(self, method: str) -> bool:
        """Server-side reply fault seam (both the sync fast path and
        the task-wrapped path route through here): True = the reply
        must NOT be sent. ``drop`` loses only the reply — the handler
        already ran, so the caller's retry probes idempotence; ``sever``
        tears the connection down mid-reply (the reference failure for
        "did my mutation land?" client logic). Sync seam on the loop
        thread: an armed ``delay`` here stalls the whole loop by
        design (loop-occupancy fault), like ``rpc.call.send``."""
        act = faultpoints.fire("rpc.reply.send", method=method,
                               peer=self.peer_name)
        if act == "drop":
            return True
        if act == "sever":
            self._mark_closed()
            return True
        return False

    def _reply_error_nowait(self, seq: int, method: str, e: BaseException):
        try:
            payload = cloudpickle.dumps(e)
        except Exception:
            payload = cloudpickle.dumps(RuntimeError(repr(e)))
        try:
            self._write_nowait(_pack_msg(KIND_ERROR, seq, method, None, [payload]))
        except (ConnectionError, OSError):
            self._mark_closed()

    def _handle_sync(self, handler, seq: int, method: str, header, bufs,
                     arr_ts: float = 0.0):
        """Dispatch a handler marked ``rpc_sync``: called inline on the
        recv loop; may return a Future for deferred replies. Queueing
        delay here is parse backlog within the chunk (the Nth request
        of a burst starts after N-1 sync replies)."""
        t0 = time.monotonic()
        queue_dt = t0 - arr_ts if arr_ts else 0.0
        tel = telemetry if telemetry.enabled else None
        try:
            if faultpoints.armed:
                # exec-side fault seam (see _handle): a delay here is a
                # slow HANDLER, attributable by method name
                faultpoints.fire("rpc.handler", method=method,
                                 peer=self.peer_name)
            result = handler(self, header, bufs)
        except Exception as e:  # noqa: BLE001 — propagate to caller
            if tel:
                tel.note_server(method, queue_dt,
                                time.monotonic() - t0, 0, True,
                                self.peer_name)
            elif arr_ts:
                telemetry.note_done(method)
            self._reply_error_nowait(seq, method, e)
            return
        if isinstance(result, asyncio.Future):
            def _on_done(fut: asyncio.Future):
                error = fut.cancelled() or fut.exception() is not None
                nbytes = 0
                if fut.cancelled():
                    self._reply_error_nowait(
                        seq, method, RuntimeError(f"{method} cancelled"))
                elif fut.exception() is not None:
                    self._reply_error_nowait(seq, method, fut.exception())
                else:
                    nbytes = self._reply_nowait(seq, method, fut.result())
                if telemetry.enabled:
                    telemetry.note_server(
                        method, queue_dt, time.monotonic() - t0,
                        nbytes, error, self.peer_name)
                elif arr_ts:
                    telemetry.note_done(method)
            result.add_done_callback(_on_done)
        else:
            nbytes = self._reply_nowait(seq, method, result)
            if tel:
                tel.note_server(method, queue_dt,
                                time.monotonic() - t0, nbytes, False,
                                self.peer_name)
            elif arr_ts:
                telemetry.note_done(method)

    async def _handle(self, seq: int, method: str, header, bufs,
                      arr_ts: float = 0.0):
        handler = self.handlers.get(method)
        # t0 is HANDLER START inside the spawned task: arr_ts -> t0 is
        # the loop's scheduling/queueing delay (the instrumented-asio
        # queue_ms), t0 -> done is handler exec — reported apart so "the
        # loop was busy" never masquerades as "the handler was slow".
        t0 = time.monotonic()
        queue_dt = t0 - arr_ts if arr_ts else 0.0
        exec_dt = 0.0
        nbytes = 0
        error = False
        try:
            if handler is None:
                raise RuntimeError(f"no handler for method {method!r}")
            if faultpoints.armed:
                # exec-side fault seam: an armed ``delay`` is a SYNC
                # sleep inside this handler's task — the handler shows
                # slow (exec) and, the loop being blocked, every
                # concurrently-queued request shows queueing delay:
                # the delay_storm attribution scenario.
                faultpoints.fire("rpc.handler", method=method,
                                 peer=self.peer_name)
            try:
                result = await handler(self, header, bufs)
            finally:
                # raising handlers count too — the misbehaving methods
                # are exactly the ones latency stats must show
                exec_dt = time.monotonic() - t0
            if isinstance(result, tuple) and len(result) == 2 and \
                    isinstance(result[1], (list, tuple)):
                rheader, rbufs = result
            else:
                rheader, rbufs = result, ()
            if faultpoints.armed and self._fault_reply(method):
                return
            parts = _pack_msg(KIND_REPLY, seq, method, rheader, rbufs)
            if telemetry.enabled:
                nbytes = _parts_len(parts)
            await self._send(parts)
        except (ConnectionError, OSError):
            error = True
            self._mark_closed()
        except Exception as e:  # noqa: BLE001 — propagate to caller
            error = True
            if not exec_dt:
                exec_dt = time.monotonic() - t0
            try:
                # raylint: disable=async-blocking — bounded error reply (one exception object)
                payload = cloudpickle.dumps(e)
            except Exception:
                # raylint: disable=async-blocking — same bounded error path
                payload = cloudpickle.dumps(RuntimeError(repr(e)))
            try:
                await self._send(_pack_msg(KIND_ERROR, seq, method, None, [payload]))
            except (ConnectionError, OSError):
                self._mark_closed()
        finally:
            if telemetry.enabled:
                telemetry.note_server(method, queue_dt, exec_dt, nbytes,
                                      error, self.peer_name)
            elif arr_ts:
                # recording was flipped off mid-flight: still balance
                # note_request's in-flight increment
                telemetry.note_done(method)

    def _mark_closed(self):
        if self._closed:
            return
        self._closed = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionError(
                    f"connection to {self.peer_name} lost"))
        self._pending.clear()
        # snapshot: callbacks may unregister themselves (or siblings)
        # from the live list mid-iteration, which would skip entries
        for cb in list(self.on_disconnect):
            try:
                cb(self)
            except Exception:
                logger.exception("on_disconnect callback failed")
        try:
            self.writer.close()
        except (OSError, RuntimeError):
            pass  # transport already torn down / loop already closed

    @property
    def closed(self) -> bool:
        return self._closed

    async def close(self):
        self._mark_closed()
        # Reap the recv loop on an EXTERNAL close: the transport
        # teardown delivers it EOF eventually, but a loop shutting down
        # right after close() (chaos teardown, tests) would otherwise
        # destroy a still-pending task and log noise. The loop's own
        # finally path never reaches here (it IS the current task).
        task = self._recv_task
        if task is not None and not task.done() and \
                task is not asyncio.current_task():
            task.cancel()
            await asyncio.gather(task, return_exceptions=True)


class RpcServer:
    """Listens on tcp://host:port or unix://path; spawns a Connection per
    client, dispatching to ``handlers``."""

    def __init__(self, handlers: Dict[str, Handler], name: str = "server"):
        self.handlers = handlers
        self.name = name
        self.address: str = ""
        self._server: Optional[asyncio.AbstractServer] = None
        self.connections: List[Connection] = []
        self.on_connect: List[Callable[[Connection], None]] = []

    async def _on_client(self, reader, writer):
        conn = Connection(reader, writer, self.handlers,
                          peer_name=f"client-of-{self.name}")
        self.connections.append(conn)
        conn.on_disconnect.append(lambda c: self.connections.remove(c)
                                  if c in self.connections else None)
        for cb in self.on_connect:
            cb(conn)
        conn.start()

    async def listen(self, address: str = "") -> str:
        if address.startswith("unix://"):
            path = address[len("unix://"):]
            if os.path.exists(path):
                os.unlink(path)
            self._server = await asyncio.start_unix_server(self._on_client, path=path)
            self.address = address
        else:
            host, port = "127.0.0.1", 0
            if address.startswith("tcp://"):
                hp = address[len("tcp://"):]
                host, _, p = hp.rpartition(":")
                port = int(p)
            self._server = await asyncio.start_server(
                self._on_client, host=host, port=port,
                family=socket.AF_INET)
            port = self._server.sockets[0].getsockname()[1]
            self.address = f"tcp://{host}:{port}"
        return self.address

    async def close(self):
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for conn in list(self.connections):
            await conn.close()


async def connect(address: str, handlers: Dict[str, Handler] | None = None,
                  timeout: float = 10.0, retry_interval: float = 0.05,
                  peer_name: str = "") -> Connection:
    """Dial an RpcServer, retrying until ``timeout`` (the server process may
    still be booting)."""
    deadline = asyncio.get_running_loop().time() + timeout
    last_err: Exception | None = None
    while True:
        try:
            if address.startswith("unix://"):
                reader, writer = await asyncio.open_unix_connection(
                    address[len("unix://"):])
            else:
                hp = address[len("tcp://"):] if address.startswith("tcp://") else address
                host, _, p = hp.rpartition(":")
                reader, writer = await asyncio.open_connection(host, int(p))
            break
        except (ConnectionError, OSError, FileNotFoundError) as e:
            last_err = e
            if telemetry.enabled:
                # redial accounting: every failed dial attempt counts
                # (a restarting GCS shows as a redial burst here)
                telemetry.note_redial()
            if asyncio.get_running_loop().time() > deadline:
                raise ConnectionError(
                    f"could not connect to {address}: {last_err}") from last_err
            await asyncio.sleep(retry_interval)
    conn = Connection(reader, writer, handlers or {},
                      peer_name=peer_name or address)
    conn.start()
    return conn


class EventLoopThread:
    """A dedicated asyncio loop on a daemon thread.

    The public API (``get``/``put``/``remote``) is synchronous like the
    reference's; all IO runs on this loop (the analog of the core worker's
    internal io_service, reference: src/ray/core_worker/core_worker.h
    io_service_ member).
    """

    def __init__(self, name: str = "ray-tpu-io"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro, timeout: float | None = None):
        """Run a coroutine on the loop from a foreign thread, blocking."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def call_soon(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self):
        async def _drain():
            tasks = [t for t in asyncio.all_tasks(self.loop)
                     if t is not asyncio.current_task()]
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)

        if self.loop.is_running():
            try:
                asyncio.run_coroutine_threadsafe(
                    _drain(), self.loop).result(timeout=3)
            except Exception:
                logger.debug("loop drain at stop failed", exc_info=True)
            self.loop.call_soon_threadsafe(self.loop.stop)
            self._thread.join(timeout=5)
        if not self.loop.is_closed():
            self.loop.close()
