"""In-process memory store for small / direct-return objects.

Role-equivalent of the reference's CoreWorkerMemoryStore (reference:
src/ray/core_worker/store_provider/memory_store/memory_store.h): every
owner keeps its tasks' small return values here; ``get`` blocks on the
owner's event loop until the value lands (the task reply delivers it), and
object-available callbacks feed dependency resolution.

Thread model: reads and ``put``/``delete`` may come from any thread (the
synchronous public API writes small objects without an IO-loop round
trip); blocking ``get`` runs on an event loop. A small lock closes the
check-then-register race between a foreign-thread put and a loop-thread
get, and waiter futures are woken on their own loop.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Callable, Dict, List, Optional

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.serialization import SerializedObject


class InPlasmaSentinel:
    """Marker stored when the real value lives in the shared-memory store;
    readers must fetch from the object plane instead."""

    __slots__ = ()


IN_PLASMA = InPlasmaSentinel()


def _set_result_safe(fut: asyncio.Future, obj) -> None:
    if not fut.done():
        fut.set_result(obj)


def _set_exception_safe(fut: asyncio.Future, err: BaseException) -> None:
    if not fut.done():
        fut.set_exception(err)


class MemoryStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._objects: Dict[ObjectID, object] = {}  # SerializedObject | IN_PLASMA
        self._waiters: Dict[ObjectID, List[asyncio.Future]] = {}
        self._object_added_callbacks: List[Callable[[ObjectID], None]] = []

    def add_object_added_callback(self, cb: Callable[[ObjectID], None]):
        self._object_added_callbacks.append(cb)

    def put(self, object_id: ObjectID, obj) -> None:
        with self._lock:
            self._objects[object_id] = obj
            waiters = self._waiters.pop(object_id, None)
        if waiters:
            try:
                current = asyncio.get_running_loop()
            except RuntimeError:
                current = None
            for fut in waiters:
                floop = fut.get_loop()
                if floop is current:
                    _set_result_safe(fut, obj)
                else:
                    floop.call_soon_threadsafe(_set_result_safe, fut, obj)
        for cb in self._object_added_callbacks:
            cb(object_id)

    def put_many(self, pairs) -> None:
        """Batch put: ONE lock round trip for a whole reply batch (the
        per-task put was ~1us of the drain's completion path)."""
        with self._lock:
            self._objects.update(pairs)
            woken = []
            if self._waiters:
                for oid, obj in pairs:
                    ws = self._waiters.pop(oid, None)
                    if ws:
                        woken.append((ws, obj))
        if woken:
            try:
                current = asyncio.get_running_loop()
            except RuntimeError:
                current = None
            for waiters, obj in woken:
                for fut in waiters:
                    floop = fut.get_loop()
                    if floop is current:
                        _set_result_safe(fut, obj)
                    else:
                        floop.call_soon_threadsafe(_set_result_safe, fut, obj)
        if self._object_added_callbacks:
            for cb in self._object_added_callbacks:
                for oid, _ in pairs:
                    cb(oid)

    def contains(self, object_id: ObjectID) -> bool:
        return object_id in self._objects

    def get_if_exists(self, object_id: ObjectID):
        return self._objects.get(object_id)

    async def get(self, object_id: ObjectID, timeout: float | None = None):
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is not None:
                return obj
            fut = asyncio.get_running_loop().create_future()
            self._waiters.setdefault(object_id, []).append(fut)
        try:
            if timeout is not None:
                return await asyncio.wait_for(fut, timeout)
            return await fut
        finally:
            with self._lock:
                lst = self._waiters.get(object_id)
                if lst and fut in lst:
                    lst.remove(fut)
                    if not lst:
                        del self._waiters[object_id]

    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            self._objects.pop(object_id, None)

    def fail_waiters(self, object_id: ObjectID, error: BaseException) -> None:
        with self._lock:
            waiters = self._waiters.pop(object_id, None)
        if not waiters:
            return
        try:
            current = asyncio.get_running_loop()
        except RuntimeError:
            current = None
        for fut in waiters:
            floop = fut.get_loop()
            if floop is current:
                _set_exception_safe(fut, error)
            else:
                floop.call_soon_threadsafe(_set_exception_safe, fut, error)

    def size(self) -> int:
        return len(self._objects)

    def used_bytes(self) -> int:
        with self._lock:
            objs = list(self._objects.values())
        total = 0
        for obj in objs:
            if isinstance(obj, SerializedObject):
                total += obj.total_bytes()
        return total
