"""In-process memory store for small / direct-return objects.

Role-equivalent of the reference's CoreWorkerMemoryStore (reference:
src/ray/core_worker/store_provider/memory_store/memory_store.h): every
owner keeps its tasks' small return values here; ``get`` blocks on the
owner's event loop until the value lands (the task reply delivers it), and
object-available callbacks feed dependency resolution.

Thread model: reads and ``put``/``delete`` may come from any thread (the
synchronous public API writes small objects without an IO-loop round
trip); blocking ``get`` runs on an event loop. A small lock closes the
check-then-register race between a foreign-thread put and a loop-thread
get, and waiter futures are woken on their own loop.

Keying: the internal tables are keyed by the id's raw 28 bytes, not the
ObjectID wrapper — a bytes key hashes in C (and caches), while hashing an
ObjectID runs a Python ``__hash__`` frame on every dict operation, which
the drain profile showed on 4+ table ops per task.  The public API takes
either an ObjectID or its ``binary()`` bytes.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Callable, Dict, List

from ray_tpu._private.ids import ObjectID, id_key as _key
from ray_tpu._private.serialization import SerializedObject


class InPlasmaSentinel:
    """Marker stored when the real value lives in the shared-memory store;
    readers must fetch from the object plane instead."""

    __slots__ = ()


IN_PLASMA = InPlasmaSentinel()


def _set_result_safe(fut: asyncio.Future, obj) -> None:
    if not fut.done():
        fut.set_result(obj)


def _set_exception_safe(fut: asyncio.Future, err: BaseException) -> None:
    if not fut.done():
        fut.set_exception(err)


class _Barrier:
    """One future covering N missing objects (bulk get): lands cost one
    dict pop + a counter decrement per object instead of a future +
    wait_for machinery per object."""

    __slots__ = ("count", "future")

    def __init__(self, count: int, future: asyncio.Future):
        self.count = count
        self.future = future


class MemoryStore:
    def __init__(self):
        self._lock = threading.Lock()
        # all keyed by the 28 raw id bytes
        self._objects: Dict[bytes, object] = {}  # SerializedObject | IN_PLASMA
        self._waiters: Dict[bytes, List[asyncio.Future]] = {}
        self._barriers: Dict[bytes, List[_Barrier]] = {}
        self._object_added_callbacks: List[Callable] = []

    def add_object_added_callback(self, cb: Callable):
        self._object_added_callbacks.append(cb)

    def put(self, object_id, obj) -> None:
        k = _key(object_id)
        with self._lock:
            self._objects[k] = obj
            waiters = self._waiters.pop(k, None)
        if waiters:
            try:
                current = asyncio.get_running_loop()
            except RuntimeError:
                current = None
            for fut in waiters:
                floop = fut.get_loop()
                if floop is current:
                    _set_result_safe(fut, obj)
                else:
                    floop.call_soon_threadsafe(_set_result_safe, fut, obj)
        if self._barriers:
            try:
                current = asyncio.get_running_loop()
            except RuntimeError:
                current = None
            self._land_for_barriers(k, current)
        if self._object_added_callbacks:
            # callbacks always receive an ObjectID, whatever key form
            # the caller used (same contract as put_many)
            oid = object_id if type(object_id) is not bytes \
                else ObjectID(object_id)
            for cb in self._object_added_callbacks:
                cb(oid)

    def put_many(self, pairs) -> None:
        """Batch put: ONE lock round trip for a whole reply batch (the
        per-task put was ~1us of the drain's completion path).  ``pairs``
        is a list of (id, obj) where the ids are HOMOGENEOUS within one
        batch: all ObjectID or all raw bytes (the native completion path
        passes bytes; the Python fallback passes ObjectID) — the key
        type is sniffed from the first pair."""
        if pairs and type(pairs[0][0]) is not bytes:
            pairs = [(o._bytes, v) for o, v in pairs]
        with self._lock:
            self._objects.update(pairs)
            woken = []
            if self._waiters:
                for k, obj in pairs:
                    ws = self._waiters.pop(k, None)
                    if ws:
                        woken.append((ws, obj))
        if woken:
            try:
                current = asyncio.get_running_loop()
            except RuntimeError:
                current = None
            for waiters, obj in woken:
                for fut in waiters:
                    floop = fut.get_loop()
                    if floop is current:
                        _set_result_safe(fut, obj)
                    else:
                        floop.call_soon_threadsafe(_set_result_safe, fut, obj)
        if self._barriers:
            try:
                current = asyncio.get_running_loop()
            except RuntimeError:
                current = None
            for k, _ in pairs:
                self._land_for_barriers(k, current)
        if self._object_added_callbacks:
            for cb in self._object_added_callbacks:
                for k, _ in pairs:
                    cb(ObjectID(k))

    async def wait_many(self, object_ids, timeout: float | None = None
                        ) -> None:
        """Block until every id has SOME value present (a real object or
        the IN_PLASMA marker).  One barrier future for the whole batch —
        the bulk-get hot path (reference analog: the memory store's
        GetAsync fan-in, memory_store.h:104 — but batched).  Raises
        asyncio.TimeoutError on timeout."""
        keys = [_key(o) for o in object_ids]
        with self._lock:
            objects = self._objects
            missing = [k for k in keys if k not in objects]
            if not missing:
                return
            barrier = _Barrier(len(missing),
                               asyncio.get_running_loop().create_future())
            setd = self._barriers.setdefault
            for k in missing:
                setd(k, []).append(barrier)
        try:
            if timeout is not None:
                await asyncio.wait_for(barrier.future, timeout)
            else:
                await barrier.future
        finally:
            fut = barrier.future
            clean = fut.done() and not fut.cancelled() \
                and fut.exception() is None
            if not clean:
                # timeout / cancellation / failure: unhook every entry
                # still registered (fail_waiters pops only its own key)
                with self._lock:
                    for k in missing:
                        lst = self._barriers.get(k)
                        if lst and barrier in lst:
                            lst.remove(barrier)
                            if not lst:
                                del self._barriers[k]

    def _land_for_barriers(self, k: bytes, current) -> None:
        """Pops barrier entries for a landed id; count decrements happen
        under the store lock (puts race from multiple threads).
        ``current`` is the running loop (or None)."""
        done = None
        with self._lock:
            bs = self._barriers.pop(k, None)
            if bs:
                for b in bs:
                    b.count -= 1
                    if b.count == 0:
                        if done is None:
                            done = []
                        done.append(b)
        if not done:
            return
        for b in done:
            if not b.future.done():
                floop = b.future.get_loop()
                if floop is current:
                    _set_result_safe(b.future, None)
                else:
                    floop.call_soon_threadsafe(_set_result_safe,
                                               b.future, None)

    def contains(self, object_id) -> bool:
        return _key(object_id) in self._objects

    def get_if_exists(self, object_id):
        return self._objects.get(_key(object_id))

    async def get(self, object_id, timeout: float | None = None):
        k = _key(object_id)
        with self._lock:
            obj = self._objects.get(k)
            if obj is not None:
                return obj
            fut = asyncio.get_running_loop().create_future()
            self._waiters.setdefault(k, []).append(fut)
        try:
            if timeout is not None:
                return await asyncio.wait_for(fut, timeout)
            return await fut
        finally:
            with self._lock:
                lst = self._waiters.get(k)
                if lst and fut in lst:
                    lst.remove(fut)
                    if not lst:
                        del self._waiters[k]

    def delete(self, object_id) -> None:
        with self._lock:
            self._objects.pop(_key(object_id), None)

    def fail_waiters(self, object_id, error: BaseException) -> None:
        k = _key(object_id)
        with self._lock:
            waiters = self._waiters.pop(k, None)
            barriers = self._barriers.pop(k, None)
        if not waiters and not barriers:
            return
        try:
            current = asyncio.get_running_loop()
        except RuntimeError:
            current = None
        for fut in waiters or ():
            floop = fut.get_loop()
            if floop is current:
                _set_exception_safe(fut, error)
            else:
                floop.call_soon_threadsafe(_set_exception_safe, fut, error)
        # a failed id can never land: fail the whole batch barrier (the
        # bulk get re-checks per id and surfaces the error path)
        for b in barriers or ():
            fut = b.future
            floop = fut.get_loop()
            if floop is current:
                _set_exception_safe(fut, error)
            else:
                floop.call_soon_threadsafe(_set_exception_safe, fut, error)

    def size(self) -> int:
        return len(self._objects)

    def used_bytes(self) -> int:
        with self._lock:
            objs = list(self._objects.values())
        total = 0
        for obj in objs:
            if isinstance(obj, SerializedObject):
                total += obj.total_bytes()
        return total
