"""In-process memory store for small / direct-return objects.

Role-equivalent of the reference's CoreWorkerMemoryStore (reference:
src/ray/core_worker/store_provider/memory_store/memory_store.h): every
owner keeps its tasks' small return values here; ``get`` blocks on the
owner's event loop until the value lands (the task reply delivers it), and
object-available callbacks feed dependency resolution.
"""

from __future__ import annotations

import asyncio
from typing import Callable, Dict, List, Optional

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.serialization import SerializedObject


class InPlasmaSentinel:
    """Marker stored when the real value lives in the shared-memory store;
    readers must fetch from the object plane instead."""

    __slots__ = ()


IN_PLASMA = InPlasmaSentinel()


class MemoryStore:
    """Async object table with waiters. Must only be touched from the owner
    process's event loop (single-threaded, like the reference's
    instrumented_io_context confinement)."""

    def __init__(self):
        self._objects: Dict[ObjectID, object] = {}  # SerializedObject | IN_PLASMA
        self._waiters: Dict[ObjectID, List[asyncio.Future]] = {}
        self._object_added_callbacks: List[Callable[[ObjectID], None]] = []

    def add_object_added_callback(self, cb: Callable[[ObjectID], None]):
        self._object_added_callbacks.append(cb)

    def put(self, object_id: ObjectID, obj) -> None:
        self._objects[object_id] = obj
        for fut in self._waiters.pop(object_id, []):
            if not fut.done():
                fut.set_result(obj)
        for cb in self._object_added_callbacks:
            cb(object_id)

    def contains(self, object_id: ObjectID) -> bool:
        return object_id in self._objects

    def get_if_exists(self, object_id: ObjectID):
        return self._objects.get(object_id)

    async def get(self, object_id: ObjectID, timeout: float | None = None):
        obj = self._objects.get(object_id)
        if obj is not None:
            return obj
        fut = asyncio.get_running_loop().create_future()
        self._waiters.setdefault(object_id, []).append(fut)
        try:
            if timeout is not None:
                return await asyncio.wait_for(fut, timeout)
            return await fut
        finally:
            lst = self._waiters.get(object_id)
            if lst and fut in lst:
                lst.remove(fut)
                if not lst:
                    del self._waiters[object_id]

    def delete(self, object_id: ObjectID) -> None:
        self._objects.pop(object_id, None)

    def fail_waiters(self, object_id: ObjectID, error: BaseException) -> None:
        for fut in self._waiters.pop(object_id, []):
            if not fut.done():
                fut.set_exception(error)

    def size(self) -> int:
        return len(self._objects)

    def used_bytes(self) -> int:
        total = 0
        for obj in self._objects.values():
            if isinstance(obj, SerializedObject):
                total += obj.total_bytes()
        return total
