"""Object-lifecycle event recording: the object-plane twin of
task_events.py.

Role parity: the reference's object-state surface — ``ray memory``
dumping the ownership table plus the GCS-backed ``list_objects`` state
API (reference: python/ray/util/state over GcsObjectManager, and
src/ray/core_worker/reference_count.h's per-ref bookkeeping). Before
this module the object plane was a black box: the zero-copy data
plane, the segment recycle pool, the memory watchdog and the borrow
protocol all mutate object state, but none of it was observable except
through private-field peeks (``store._lent``, ``_pull_inflight_bytes``)
in chaos tests.

Every object gets a recorded lifecycle, stamped AT THE LAYER THAT OWNS
the transition:

* reference_count.py / core_worker.py — CREATED (ownership
  registered), BORROWED (owner records a borrower / borrower adopts a
  deserialized ref), BORROW_RELEASED, CONTAINED (contained-ref
  adoption), LOCATION_ADDED / LOCATION_DROPPED (the owner-resident
  object directory), OUT_OF_SCOPE (the reference table released the
  object), LINEAGE_RELEASED (a plasma return's creating-task lineage
  unpinned).
* shm_store.py — SEALED, PINNED (primary copy), EXPOSED (a foreign
  mmap may now outlive the free: the segment can never be recycled),
  EVICTED, SPILLED, RESTORED, FREED (data dropped on this node), plus
  the SEGMENT-level events RECYCLED and LEASE_ABORTED (object_id-less;
  they describe the recycle pool, not an object).
* raylet.py — PULLED (a cross-node pull sealed a replica) and the
  leak-detector verdicts LEAKED / LEAK_RECLAIMED / LEAK_CLEARED.

High-volume discipline: the in-process memory store's small objects
(every task return in a 1M-task drain) deliberately do NOT emit
per-release events — the reference counter only records OUT_OF_SCOPE
for refs that ever touched plasma, borrowing, containment or the
location index (see ``reference_count._interesting``). The event
pipeline exists for the objects the store layers fight over; small
in-process values stay visible through the live driver-side ref table
(``ray_tpu.state.memory_summary()`` dumps it; ``list_objects()``
merges its counts into the records the table does carry).

Transitions accumulate in bounded per-process buffers
(``ObjectEventBuffer`` — the same honest-truncation discipline as
``TaskEventBuffer``: drop-newest + counted, never unbounded memory,
never a hot-path RPC) and ship to the GCS ``ObjectTable`` piggybacked
on the existing cadences: workers/drivers flush with the metrics
report loop (``AddObjectEvents``), raylets ride the heartbeat
(``object_events`` header keys). The GCS keeps a capped per-job index
with honest eviction counts; an object's job is read straight off its
id (``ObjectID`` embeds TaskID -> ActorID -> JobID, ids.py), so no
job-upgrade dance is needed.

Recording is ON by default (``object_events_enabled``); bench.py's
``object_events_overhead`` row pins the put/get cost under 5%. All
timestamps are ``time.time()`` so object slices merge with tasks,
spans and pulls on ONE clock in ``ray_tpu.state.timeline()``.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu._private.ids import JOB_ID_SIZE
from ray_tpu._private.task_events import TaskEventBuffer, _hex, _norm_attrs

# Canonical lifecycle states. CREATED/BORROWED/CONTAINED/OUT_OF_SCOPE
# are reference-counter transitions; SEALED..FREED are store
# transitions; PULLED and the LEAK_* verdicts are raylet-stamped.
CREATED = "CREATED"
SEALED = "SEALED"
PINNED = "PINNED"
# The segment name left the store server (a consumer will mmap it):
# zero-copy views may outlive the free, so it can never be recycled.
EXPOSED = "EXPOSED"
BORROWED = "BORROWED"
BORROW_RELEASED = "BORROW_RELEASED"
CONTAINED = "CONTAINED"
LOCATION_ADDED = "LOCATION_ADDED"
LOCATION_DROPPED = "LOCATION_DROPPED"
PULLED = "PULLED"
EVICTED = "EVICTED"
SPILLED = "SPILLED"
RESTORED = "RESTORED"
OUT_OF_SCOPE = "OUT_OF_SCOPE"
LINEAGE_RELEASED = "LINEAGE_RELEASED"
FREED = "FREED"
# Leak-detector verdicts: a store-held segment whose owner no longer
# holds any reference (a lost FreeObject, a SIGKILLed owner).
LEAKED = "LEAKED"
LEAK_RECLAIMED = "LEAK_RECLAIMED"
# A later live verdict retracted a LEAKED flag (the owner was only
# transiently unreachable) — without this the GCS record would report
# a phantom leak until the object is actually freed.
LEAK_CLEARED = "LEAK_CLEARED"
# Segment-level events (empty object_id — they describe the recycle
# pool, not an object): routed into ObjectTable.segment_events.
RECYCLED = "RECYCLED"
LEASE_ABORTED = "LEASE_ABORTED"

SEGMENT_STATES = (RECYCLED, LEASE_ABORTED)
TERMINAL_STATES = (OUT_OF_SCOPE, FREED, LEAK_RECLAIMED)


class ObjectEventBuffer(TaskEventBuffer):
    """Bounded per-process object-event buffer — the TaskEventBuffer
    contract verbatim (GIL-atomic deque append, drop-newest + monotonic
    counted, popleft drain), keyed by object id on the wire."""

    WIRE_KEY = "object_id"


class ObjectTable:
    """GCS-side object table: per-object ordered lifecycle history with
    a capped per-job index (same honest-truncation discipline as
    TaskEventTable — eviction is FIFO per job, COUNTED per job, and
    reporter-side ring drops aggregate into ``dropped_events``).

    The job bucket is derived from the object id itself (the first
    JOB_ID_SIZE bytes: ObjectID embeds TaskID embeds ActorID embeds
    JobID), so raylet-reported events need no job-upgrade pass.
    """

    MAX_SEGMENT_EVENTS = 10_000
    # Per-record event cap: unlike a task's acyclic lifecycle, object
    # transitions CYCLE (evict/restore, borrow/release, location
    # add/drop) — one hot object under sustained pressure would grow
    # its history without bound. Oldest events roll off (the newest
    # carry the current state) and the loss is COUNTED per record.
    MAX_EVENTS_PER_OBJECT = 512

    def __init__(self, max_objects_per_job: int = 8192):
        self.max_objects_per_job = max(1, int(max_objects_per_job))
        # object_id -> record, insertion-ordered (dict semantics).
        self._objects: Dict[bytes, dict] = {}
        # job prefix -> object ids in first-seen order (eviction queue).
        self._per_job: Dict[bytes, List[bytes]] = {}
        self.evicted_objects: Dict[bytes, int] = {}
        self.dropped_events = 0
        self.segment_events: List[dict] = []
        self.segment_events_dropped = 0

    def num_objects(self) -> int:
        return len(self._objects)

    def ingest(self, events, dropped: int = 0) -> None:
        """Fold one reporter batch in (owner metrics-loop flushes and
        raylet heartbeat piggybacks both land here)."""
        self.dropped_events += int(dropped or 0)
        for e in events:
            state = e.get("state")
            attrs = _norm_attrs(e.get("attrs"))
            oid = e.get("object_id") or b""
            if not oid or state in SEGMENT_STATES:
                if len(self.segment_events) >= self.MAX_SEGMENT_EVENTS:
                    self.segment_events_dropped += 1
                else:
                    rec = {"state": state, "ts": e.get("ts", 0.0)}
                    rec.update(attrs or {})
                    self.segment_events.append(rec)
                continue
            rec = self._objects.get(oid)
            if rec is None:
                rec = {"object_id": oid, "owner": "", "size": 0,
                       "events": [], "events_dropped": 0,
                       "state": "", "state_key": (-1.0, False)}
                self._objects[oid] = rec
                self._index(oid)
            if attrs:
                if attrs.get("owner") and not rec["owner"]:
                    rec["owner"] = attrs["owner"]
                size = attrs.get("size") or attrs.get("bytes") or 0
                if size and size > rec["size"]:
                    rec["size"] = size
                # DistributedArray shards: SEALED events carry flat
                # placement attrs (rank / mesh coords); pin them on the
                # record so list_objects() shows shard placement.
                if "rank" in attrs and "coords" in attrs:
                    rec["shard"] = {
                        "rank": attrs["rank"],
                        "coords": attrs.get("coords"),
                        "mesh": attrs.get("mesh"),
                        "array_shape": attrs.get("array_shape"),
                    }
            ts = e.get("ts", 0.0)
            history = rec["events"]
            history.append((state, ts, attrs))
            # current state maintained incrementally (one key compare
            # per event) so summary()/list() never rescan every event
            # of every record per dashboard poll; same ordering rule
            # as _current_state, and eviction below only ever removes
            # the OLDEST event so the cached newest stays correct
            key = (ts, state in TERMINAL_STATES)
            if key >= rec["state_key"]:
                rec["state"] = state
                rec["state_key"] = key
            if len(history) > self.MAX_EVENTS_PER_OBJECT:
                # drop the OLDEST-by-timestamp event (arrival order
                # can interleave reporters) so the current state stays
                # truthful; honest per-record counter
                history.remove(min(history, key=lambda ev: ev[1]))
                rec["events_dropped"] += 1

    def _index(self, oid: bytes) -> None:
        job = oid[:JOB_ID_SIZE]
        order = self._per_job.setdefault(job, [])
        order.append(oid)
        while len(order) > self.max_objects_per_job:
            old = order.pop(0)
            if self._objects.pop(old, None) is not None:
                self.evicted_objects[job] = \
                    self.evicted_objects.get(job, 0) + 1

    def list(self, state: Optional[str] = None,
             owner: Optional[str] = None, node: Optional[str] = None,
             job_id: Optional[str] = None,
             leaked: Optional[bool] = None,
             limit: int = 1000) -> List[dict]:
        """Public-form records, newest-first-seen last. Filters run on
        the RAW records and only the post-limit tail is converted (the
        per-record event sort must not scan the whole table per
        dashboard poll); ``limit`` <= 0 returns nothing — a negative
        limit must never alias to 'the entire table'."""
        try:
            limit = int(limit if limit is not None else 0)
        except (TypeError, ValueError):
            limit = 0
        if limit <= 0:
            return []
        matched = []
        for rec in self._objects.values():
            if owner and owner not in rec["owner"]:
                continue
            if job_id and rec["object_id"][:JOB_ID_SIZE].hex() != job_id:
                continue
            if state or leaked is not None:
                cur = rec.get("state") or _current_state(rec["events"])
                if state and cur != state:
                    continue
                if leaked is not None and (cur == LEAKED) != leaked:
                    continue
            if node and not any(
                    isinstance(e[2], dict) and
                    str(e[2].get("node", "")).startswith(node)
                    for e in rec["events"]):
                continue
            matched.append(rec)
        return [object_record_to_public(r) for r in matched[-limit:]]

    def summary(self) -> dict:
        """Aggregate view for ``summary_objects()`` / the dashboard.
        ``leaked`` counts records CURRENTLY in the LEAKED state — a
        reclaimed (or late-freed) orphan leaves the count, so the chaos
        invariant ``summary_objects()["leaked"] == 0`` asserts a clean
        steady state, not "no leak ever happened" (by_state keeps the
        LEAK_RECLAIMED history)."""
        by_state: Dict[str, int] = {}
        leaked = 0
        total_bytes = 0
        for rec in self._objects.values():
            st = rec.get("state") or _current_state(rec["events"])
            by_state[st] = by_state.get(st, 0) + 1
            if st == LEAKED:
                leaked += 1
            total_bytes += rec["size"]
        return {
            "num_objects": len(self._objects),
            "by_state": by_state,
            "leaked": leaked,
            "total_size_bytes": total_bytes,
            "evicted_objects": {_hex(k): v
                                for k, v in self.evicted_objects.items()},
            "dropped_events": self.dropped_events,
            "num_segment_events": len(self.segment_events),
            "segment_events_dropped": self.segment_events_dropped,
        }


def _current_state(events) -> str:
    """State of the latest-by-timestamp transition; a terminal state
    wins wall-clock ties (a FREED and the sweeping raylet's bookkeeping
    can share a microsecond)."""
    if not events:
        return ""
    best = max(events, key=lambda e: (e[1], e[0] in TERMINAL_STATES))
    return best[0]


def object_record_to_public(rec: dict) -> dict:
    """GCS-internal record -> API/JSON form: hex ids, ts-sorted events
    with per-hop durations, current state and the leaked flag."""
    events = sorted(rec["events"], key=lambda e: e[1])
    out_events = []
    for i, (state, ts, attrs) in enumerate(events):
        dur = events[i + 1][1] - ts if i + 1 < len(events) else None
        out_events.append({"state": state, "ts": ts, "dur": dur,
                           "attrs": attrs})
    cur = rec.get("state") or _current_state(events)
    out = {
        "object_id": _hex(rec["object_id"]),
        "job_id": rec["object_id"][:JOB_ID_SIZE].hex(),
        "owner": rec["owner"],
        "size": rec["size"],
        "state": cur,
        "leaked": cur == LEAKED,
        "events": out_events,
        "events_dropped": rec.get("events_dropped", 0),
    }
    if rec.get("shard") is not None:
        out["shard"] = rec["shard"]
    return out
