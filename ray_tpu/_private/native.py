"""Build-and-load for the optional native (C) hot paths.

The C sources live in ``cpp/`` (the same tree as the C++ cross-language
client); they are compiled on first use into a per-interpreter cache
directory inside the package, keyed by source hash, so editing the C
source invalidates stale builds automatically.  Everything degrades to
the pure-Python implementations when a compiler or the CPython headers
are unavailable (``RAY_TPU_NO_NATIVE=1`` forces that off-switch), so the
native path is a performance tier, never a correctness dependency.

Reference role parity: the reference runs its whole submission path as
C++ behind Cython (python/ray/_raylet.pyx); here only the measured hot
chain is native and the orchestration stays Python.
"""

from __future__ import annotations

import hashlib
import importlib.util
import logging
import os
import subprocess
import sys
import sysconfig
import threading

logger = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "cpp", "fastpath.c")
_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_native_cache")

_lock = threading.Lock()
_mod = None
_tried = False


def load_fastpath():
    """The ``_rtpu_fastpath`` extension module, or None (cached)."""
    global _mod, _tried
    if _tried:
        return _mod
    with _lock:
        if _tried:
            return _mod
        if os.environ.get("RAY_TPU_NO_NATIVE"):
            _tried = True
            return None
        try:
            _mod = _build_and_load()
            logger.debug("native fastpath loaded: %s", _mod.__file__)
        except Exception as e:  # noqa: BLE001 — fall back to pure Python
            logger.debug("native fastpath unavailable: %s", e)
            _mod = None
        _tried = True
        return _mod


def _build_and_load():
    with open(_SRC, "rb") as f:
        src = f.read()
    tag = "%s-%s" % (hashlib.sha256(src).hexdigest()[:12],
                     sys.implementation.cache_tag)
    if os.environ.get("RAY_TPU_NATIVE_SANITIZE"):
        tag += "-san"
    so_path = os.path.join(_CACHE_DIR, "_rtpu_fastpath-%s.so" % tag)
    if not os.path.exists(so_path):
        _compile(so_path)
    spec = importlib.util.spec_from_file_location("_rtpu_fastpath", so_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _compile(so_path: str) -> None:
    os.makedirs(_CACHE_DIR, exist_ok=True)
    lock_path = os.path.join(_CACHE_DIR, ".build.lock")
    import fcntl

    with open(lock_path, "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)  # serialize concurrent workers
        if os.path.exists(so_path):  # another process won the race
            return
        cc = (os.environ.get("CC") or "cc")
        include = sysconfig.get_paths()["include"]
        tmp = so_path + ".tmp.%d" % os.getpid()
        cmd = [cc, "-O2", "-fPIC", "-shared", "-I", include, _SRC,
               "-o", tmp]
        if os.environ.get("RAY_TPU_NATIVE_SANITIZE"):
            # ci/sanitize.sh: ASAN+UBSAN instrumented tier (needs
            # LD_PRELOADed libasan in the hosting interpreter).
            cmd[1:1] = ["-g", "-fsanitize=address,undefined",
                        "-fno-sanitize-recover=undefined"]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
        if proc.returncode != 0:
            raise RuntimeError(
                "fastpath compile failed (%s): %s" % (cc, proc.stderr[-2000:]))
        os.replace(tmp, so_path)  # atomic publish
