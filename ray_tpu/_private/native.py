"""Build-and-load for the optional native (C) hot paths.

The C sources live in ``cpp/`` (the same tree as the C++ cross-language
client); they are compiled on first use into a per-interpreter cache
directory inside the package, keyed by source hash, so editing the C
source invalidates stale builds automatically.  Everything degrades to
the pure-Python implementations when a compiler or the CPython headers
are unavailable (``RAY_TPU_NO_NATIVE=1`` forces that off-switch), so the
native path is a performance tier, never a correctness dependency.

Reference role parity: the reference runs its whole submission path as
C++ behind Cython (python/ray/_raylet.pyx); here only the measured hot
chain is native and the orchestration stays Python.
"""

from __future__ import annotations

import hashlib
import importlib.util
import logging
import os
import subprocess
import sys
import sysconfig
import threading

logger = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "cpp", "fastpath.c")
_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_native_cache")

_lock = threading.Lock()
_mod = None
_tried = False


def load_fastpath():
    """The ``_rtpu_fastpath`` extension module, or None (cached).

    May COMPILE on a cold cache (a subprocess cc run, seconds): call it
    from process startup or an executor thread, never from an event
    loop. The data-plane hot path (``copy_into``) deliberately goes
    through :func:`loaded_fastpath` instead, so a cold cache can only
    ever cost a pure-Python copy — not a compiler run on the loop."""
    global _mod, _tried
    if _tried:
        return _mod
    with _lock:
        if _tried:
            return _mod
        if os.environ.get("RAY_TPU_NO_NATIVE"):
            _tried = True
            return None
        try:
            _mod = _build_and_load()
            logger.debug("native fastpath loaded: %s", _mod.__file__)
        except Exception as e:  # noqa: BLE001 — fall back to pure Python
            logger.debug("native fastpath unavailable: %s", e)
            _mod = None
        _tried = True
        return _mod


def loaded_fastpath():
    """The already-loaded extension module or None — never builds.
    Processes opt into the native tier by warming ``load_fastpath()``
    once at boot (raylet start does it in an executor, worker_main and
    CoreWorker before their loops exist)."""
    return _mod


# --------------------------------------------------------------------------
# Data-plane copy engine (zero-copy put pipeline).
#
# ``copy_into(dst, dst_off, src)`` is the single seam every bulk byte
# move on the object-plane write path goes through (shm segment fills,
# chunked node-to-node pull writes).  Native tier: the GIL-releasing C
# memcpy in cpp/fastpath.c, with copies above ``parallel_copy_threshold``
# striped across a small daemon thread pool so page faults on fresh shm
# pages and the memcpy itself overlap across cores — and so a multi-GiB
# put never parks every other driver thread behind the GIL.  Fallback:
# one pure-Python ``memoryview[slice] = view`` assignment (still a
# single C-level memcpy, just GIL-held and single-threaded).
# --------------------------------------------------------------------------

# Stripe size for splitting one huge copy across the pool. Kept small
# enough that a 2 GiB frame becomes many stripes (tests shrink it via
# RAY_TPU_COPY_CHUNK_MB to exercise the chunking path cheaply).
COPY_CHUNK_BYTES = max(1, int(os.environ.get(
    "RAY_TPU_COPY_CHUNK_MB", "16"))) * 1024 * 1024
# Mild oversubscription on purpose: stripes alternate between faulting
# pages (kernel time) and streaming copies, so 2x cores keeps the
# memory bus busy (measured 9.5 vs 7.2 GB/s warm on the 2-core box).
_COPY_THREADS = max(2, min(8, 2 * (os.cpu_count() or 1)))

_copy_pool = None
_copy_pool_lock = threading.Lock()

# Observability (asserted by tests, reported by stores): how many bulk
# copies ran native / striped / pure-Python.
copy_stats = {"native": 0, "striped": 0, "fallback": 0}

# Receive half of the striped data plane (data_channel.py): how many
# socket->buffer receives ran native vs pure-Python. Both tiers land
# DIRECTLY in the caller's buffer — the counter is a tier probe, not a
# copy count (each receive is exactly one kernel->buffer copy).
recv_stats = {"native": 0, "fallback": 0}

# Fold half of the ring-collective path (raylet RingStep / GatherShards
# reduce leg): how many scratch-window folds ran through the
# GIL-releasing C kernel vs the numpy fallback.
reduce_stats = {"native": 0, "fallback": 0}

# Wire codes of cpp/fastpath.c reduce_into. Other numeric dtypes are
# legal — they just always take the numpy tier.
_REDUCE_DTYPE_CODES = {"float32": 0, "float64": 1, "int32": 2, "int64": 3}
_REDUCE_OP_CODES = {"sum": 0, "min": 1, "max": 2}


def have_native_copy() -> bool:
    mod = load_fastpath()
    return mod is not None and hasattr(mod, "copy_into")


def _pool():
    global _copy_pool
    if _copy_pool is None:
        with _copy_pool_lock:
            if _copy_pool is None:
                from concurrent.futures import ThreadPoolExecutor
                _copy_pool = ThreadPoolExecutor(
                    max_workers=_COPY_THREADS,
                    thread_name_prefix="rtpu-copy")
    return _copy_pool


def _as_byte_view(buf) -> memoryview:
    """A flat uint8 view of any contiguous buffer, copy-free."""
    mv = buf if isinstance(buf, memoryview) else memoryview(buf)
    if mv.format != "B" or mv.ndim != 1:
        mv = mv.cast("B")
    return mv


def copy_into(dst, dst_off: int, src, chunk_bytes: int | None = None) -> int:
    """Copy all of ``src`` (any contiguous buffer) into ``dst`` at
    ``dst_off``; returns bytes copied. Never materializes intermediate
    ``bytes``. ``chunk_bytes`` overrides the stripe size (tests).

    Uses only the ALREADY-loaded native module: raylint's transitive
    async-blocking pass proved the old lazy ``load_fastpath()`` here
    could inject a cold-cache compiler run (subprocess cc, seconds)
    into the raylet event loop via the chunked-pull path. A process
    that never warmed the native tier gets the pure-Python copy."""
    mod = loaded_fastpath()
    native = mod.copy_into if mod is not None and \
        hasattr(mod, "copy_into") else None
    chunk = chunk_bytes or COPY_CHUNK_BYTES
    if native is not None:
        try:
            src_view = src
            # nbytes, never len(): len() counts ELEMENTS for array-like
            # buffers (1/8 of the bytes for float64) — the C entry
            # copies raw bytes
            n = getattr(src, "nbytes", None)
            if n is None:
                n = len(src)
            if n > chunk and _COPY_THREADS > 1:
                # Stripe the copy: each worker's native call drops the
                # GIL, so stripes genuinely overlap.
                futs = [
                    _pool().submit(native, dst, dst_off + off,
                                   src_view, off,
                                   min(chunk, n - off))
                    for off in range(0, n, chunk)]
                for f in futs:
                    # raylint: disable=async-blocking — bounded stripe join: workers are pure GIL-releasing memcpy, so the join lasts only as long as the overlapped copy (ms); an executor hop here would add latency to every large data-plane copy
                    f.result()
                copy_stats["striped"] += 1
                return n
            copied = native(dst, dst_off, src_view, 0, n)
            copy_stats["native"] += 1
            return copied
        except (BufferError, TypeError, ValueError):
            pass  # non-contiguous/exotic buffer: pure-Python path
    sv = _as_byte_view(src)
    dv = _as_byte_view(dst)
    dv[dst_off:dst_off + sv.nbytes] = sv
    copy_stats["fallback"] += 1
    return sv.nbytes


def sock_recv_into(sock, dst, dst_off: int, nbytes: int) -> int:
    """One receive from ``sock`` straight into ``dst[dst_off:dst_off+
    nbytes]``; returns the byte count received (short reads are normal
    — callers loop), ``0`` on orderly peer EOF, or ``-1`` when the
    non-blocking socket has no data ready (the caller awaits loop
    readability and retries).

    This is the single-copy seam of the cross-node data plane: the
    destination is the puller's mapped shm segment, so object bytes go
    kernel socket buffer -> segment pages with no intermediate
    ``bytes``. Native tier: the GIL-releasing ``recv(2)`` in
    cpp/fastpath.c (already-loaded module only, same discipline as
    :func:`copy_into`). Fallback: ``socket.recv_into`` on a zero-copy
    memoryview slice of the destination — still one copy, just via the
    socket object's own machinery."""
    mod = loaded_fastpath()
    if mod is not None and hasattr(mod, "recv_into"):
        try:
            n = mod.recv_into(sock.fileno(), dst, dst_off, nbytes)
        except (BufferError, TypeError):
            pass  # exotic destination buffer: pure-Python path
        else:
            recv_stats["native"] += 1
            return n
    view = _as_byte_view(dst)
    try:
        n = sock.recv_into(view[dst_off:dst_off + nbytes])
    except (BlockingIOError, InterruptedError):
        return -1
    recv_stats["fallback"] += 1
    return n


def reduce_into(dst, dst_off: int, src, dtype, op: str = "sum") -> int:
    """Fold ALL of ``src`` element-wise into ``dst`` at byte offset
    ``dst_off`` (``dst[i] = dst[i] op src[i]``); returns the element
    count folded. The fold seam of the ring collectives: the raylet's
    RingStep executor fold and the GatherShards reduce leg both land
    here, so one call covers native tier, tier accounting and the
    numpy fallback.

    Native tier: the GIL-releasing C loop in cpp/fastpath.c for
    {f32, f64, i32, i64} x {sum, min, max} (already-loaded module only
    — same no-build-on-hot-path discipline as :func:`copy_into`).
    Fallback: ``np.frombuffer`` views created AND dropped inside this
    call, so no array export outlives it to pin the destination
    mapping (the BufferError footgun the native kernel exists to
    kill). Out-of-bounds offsets/lengths raise ValueError from either
    tier; unknown ops raise ValueError; dtypes outside the native set
    silently take the numpy tier."""
    op_code = _REDUCE_OP_CODES.get(op)
    if op_code is None:
        raise ValueError(f"unsupported reduce op: {op!r}")
    dtype_str = str(dtype)
    mod = loaded_fastpath()
    dtype_code = _REDUCE_DTYPE_CODES.get(dtype_str)
    if mod is not None and dtype_code is not None and \
            hasattr(mod, "reduce_into"):
        try:
            n = mod.reduce_into(dst, dst_off, src, dtype_code, op_code)
        except (BufferError, TypeError):
            pass  # exotic/misaligned buffer: numpy tier below
        else:
            reduce_stats["native"] += 1
            return n
    import numpy as np
    dt = np.dtype(dtype_str)
    sv = _as_byte_view(src)
    if sv.nbytes % dt.itemsize:
        raise ValueError(
            f"reduce_into: {sv.nbytes} source bytes is not a whole "
            f"number of {dt.itemsize}-byte elements")
    count = sv.nbytes // dt.itemsize
    dv = _as_byte_view(dst)
    if dst_off < 0 or sv.nbytes > dv.nbytes - dst_off:
        raise ValueError("reduce_into: offset/length out of bounds")
    d = np.frombuffer(dv, dtype=dt, count=count, offset=dst_off)
    s = np.frombuffer(sv, dtype=dt, count=count)
    ufunc = {"sum": np.add, "min": np.minimum, "max": np.maximum}[op]
    ufunc(d, s, d)
    del d, s, dv, sv
    reduce_stats["fallback"] += 1
    return count


def _build_and_load():
    with open(_SRC, "rb") as f:
        src = f.read()
    tag = "%s-%s" % (hashlib.sha256(src).hexdigest()[:12],
                     sys.implementation.cache_tag)
    san = os.environ.get("RAY_TPU_NATIVE_SANITIZE")
    if san:
        tag += "-tsan" if san == "tsan" else "-san"
    so_path = os.path.join(_CACHE_DIR, "_rtpu_fastpath-%s.so" % tag)
    if not os.path.exists(so_path):
        _compile(so_path)
    spec = importlib.util.spec_from_file_location("_rtpu_fastpath", so_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _compile(so_path: str) -> None:
    os.makedirs(_CACHE_DIR, exist_ok=True)
    lock_path = os.path.join(_CACHE_DIR, ".build.lock")
    import fcntl

    with open(lock_path, "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)  # serialize concurrent workers
        if os.path.exists(so_path):  # another process won the race
            return
        cc = (os.environ.get("CC") or "cc")
        include = sysconfig.get_paths()["include"]
        tmp = so_path + ".tmp.%d" % os.getpid()
        cmd = [cc, "-O2", "-fPIC", "-shared", "-I", include, _SRC,
               "-o", tmp]
        san = os.environ.get("RAY_TPU_NATIVE_SANITIZE")
        if san == "tsan":
            # ci/sanitize.sh step 6: TSAN tier for the threaded
            # copy_into stripes (needs LD_PRELOADed libtsan).
            cmd[1:1] = ["-g", "-fsanitize=thread"]
        elif san:
            # ci/sanitize.sh: ASAN+UBSAN instrumented tier (needs
            # LD_PRELOADed libasan in the hosting interpreter).
            cmd[1:1] = ["-g", "-fsanitize=address,undefined",
                        "-fno-sanitize-recover=undefined"]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
        if proc.returncode != 0:
            raise RuntimeError(
                "fastpath compile failed (%s): %s" % (cc, proc.stderr[-2000:]))
        os.replace(tmp, so_path)  # atomic publish
