"""Core worker: the per-process ownership facade.

Role parity: reference CoreWorker (src/ray/core_worker/core_worker.h) —
embedded in every driver and worker process. Owns:

  * the in-process memory store (small objects) and the shm-store client
  * the reference counter (ownership + borrowing)
  * the task manager (pending tasks, retries, error objects)
  * the direct task submitter (leases from raylets, pipelined pushes
    straight to leased workers — reference: transport/direct_task_transport.h)
  * the direct actor submitter (per-actor ordered queues with sequence
    numbers — reference: transport/direct_actor_transport.h)
  * the owner RPC services other processes call: GetObject,
    GetObjectLocations, AddBorrower/RemoveBorrower

The synchronous public API (get/put/wait/submit) marshals onto a dedicated
asyncio IO loop, the analog of the reference core worker's io_service.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import itertools
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu import exceptions as exc
from ray_tpu._private import protocol, rpc
from ray_tpu._private.config import RayTpuConfig
from ray_tpu._private.function_manager import FunctionManager
from ray_tpu._private.ids import (
    ACTOR_ID_SIZE, TASK_ID_SIZE, ActorID, JobID, ObjectID, TaskID,
    WorkerID, make_task_id_bytes, return_object_id_bytes,
)
from ray_tpu._private.memory_store import IN_PLASMA, MemoryStore
from ray_tpu._private.object_events import (
    LINEAGE_RELEASED, ObjectEventBuffer,
)
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.reference_count import Reference, ReferenceCounter
from ray_tpu._private.serialization import (
    META_ERROR, SerializationContext, SerializedObject,
)
from ray_tpu._private.shm_store import (
    RECYCLE_MIN_BYTES, AttachedObject, plan_segment, write_segment,
)
from ray_tpu._private.task_events import (
    CREDIT_DISPATCHED, DISPATCHED, FAILED, PENDING_ARGS, RETRY, SUBMITTED,
    TaskEventBuffer,
)
from ray_tpu._private.task_spec import (
    ARG_REF, ARG_VALUE, REPLY_ACTOR_RESTARTING, REPLY_ERROR, REPLY_STOLEN,
    TASK_ACTOR, TASK_ACTOR_CREATION, TASK_NORMAL, TaskArg, TaskSpec,
)

logger = logging.getLogger(__name__)


_tracing_mod = None


def _trace_ctx():
    """Span context for a submission, or None when tracing is off.

    Off-path cost is one global read: ``ray_tpu.util.tracing`` REGISTERS
    itself into ``_tracing_mod`` at import time (the prior sys.modules
    probe here cost ~0.4us/task on the submit hot path) — enable()
    imports it in the driver, CoreWorker.__init__ imports it when
    RAY_TPU_TRACE=1 was set in the environment, and workers import it in
    ``_exec_span`` the moment a traced spec arrives, before any nested
    submission."""
    m = _tracing_mod
    if m is None:
        return None
    return m.inject_context() if m.enabled() else None


class PendingTaskEntry:
    """Owner-side record of one submitted task (reference: TaskManager's
    pending-task table, src/ray/core_worker/task_manager.h)."""

    __slots__ = ("spec", "num_retries_left", "return_ids", "dep_ids",
                 "lineage_pinned", "recovery_waiter", "oom_retries_left",
                 "oom_backoff")

    def __init__(self, spec: TaskSpec, return_ids: List[ObjectID]):
        self.spec = spec
        self.num_retries_left = spec.max_retries
        self.return_ids = return_ids
        # args=() is the submit hot path: skip the dependency scan.
        self.dep_ids = [ObjectID(b) for b in spec.dependency_ids()] \
            if spec.args else ()
        self.lineage_pinned = False
        # Future resolved on the next completion of this task (set by
        # object recovery while it waits for the re-execution).
        self.recovery_waiter = None
        # Dedicated memory-watchdog retry budget (config
        # task_oom_retries), SEPARATE from num_retries_left: an OOM
        # kill is the node's fault, not the task's. None = not yet
        # initialized — the first OOM kill fills it from config, and
        # the C fastpath (cpp/fastpath.c) leaves these two slots UNSET
        # entirely, so every reader goes through getattr defaults.
        self.oom_retries_left = None
        self.oom_backoff = None


class LeasedWorker:
    __slots__ = ("address", "lease_id", "node_id", "conn", "inflight",
                 "raylet_address", "worker_id", "idle_timer",
                 "via_credit", "on_drop", "gang")

    def __init__(self, address, lease_id, node_id, conn, raylet_address, worker_id):
        self.address = address
        self.lease_id = lease_id
        self.node_id = node_id
        self.conn = conn
        self.raylet_address = raylet_address
        self.worker_id = worker_id
        self.inflight = 0
        # cancellable keepalive TimerHandle while idle (exactly one)
        self.idle_timer = None
        # True when this worker arrived as a streamed lease credit
        # (GrantLeaseCredits) rather than a RequestWorkerLease grant —
        # its dispatches count as credit hits and stamp
        # CREDIT_DISPATCHED, and RevokeLeaseCredits may reclaim it.
        self.via_credit = False
        # the on_disconnect callback registered for this worker, kept
        # so deliberate teardown (idle return, credit revocation) can
        # unregister it — a revoked credit must not fire the
        # worker-died retry path against a healthy worker
        self.on_drop = None
        # owning SpmdGang when this lease is a gang member (rank-pinned
        # dispatch; never idle-returned — the gang release path owns
        # the lease lifetime, see _schedule_idle_return)
        self.gang = None


class SchedulingKeyState:
    """Per scheduling-class submission state (reference: SchedulingKey
    queues in direct_task_transport.h)."""

    __slots__ = ("queue", "workers", "pending_lease", "resources",
                 "steal_pending", "reassigned", "last_grant_ts",
                 "credit_target", "cluster_slots", "last_demand_ts",
                 "activating")

    def __init__(self, resources):
        self.queue: deque[TaskSpec] = deque()
        self.workers: List[LeasedWorker] = []
        self.pending_lease = 0
        self.resources = resources
        # Streaming-lease window target announced by the raylet
        # (GrantLeaseCredits.window_target): the breadth this class may
        # hold. -1 = unknown (probe with ONE legacy request — it
        # carries the backlog that opens the window). Bounds how many
        # legacy lease requests the pump parks at the raylet; parked
        # requests beyond the cluster's capacity were exactly the
        # 200-700ms grant_wait tail streaming leases exist to kill.
        self.credit_target = -1
        # cluster-wide slot bound from the same push: how many legacy
        # requests may park at the raylet for spillback BEYOND the
        # streamed local slots (remote capacity still flows through
        # the existing spill machinery)
        self.cluster_slots = -1
        # last ReportLeaseDemand push (paced refresh, see the pump)
        self.last_demand_ts = 0.0
        # credits announced for this class whose worker dial is still
        # in flight: counted as expected breadth by the pump so a
        # racing legacy request doesn't grab the same pool slot
        self.activating = 0
        # Work stealing (reference: direct_task_transport.h:57): at most
        # one outstanding StealTasks per key. ``reassigned`` maps a
        # stolen task_id -> a multiset (list, repeats allowed) of VICTIM
        # worker_ids — a task stolen twice has two victim slots, and
        # both steals can even be from the same worker. Each victim's
        # batch slot (stolen marker, or victim death) must be skipped
        # exactly once, but a THIEF dying while executing the stolen
        # task must still retry.
        self.steal_pending = False
        self.reassigned: Dict[bytes, List[bytes]] = {}
        # when the last lease grant landed (breadth/depth phase signal)
        self.last_grant_ts = 0.0


class ActorQueueState:
    """Per-actor client-side queue (reference:
    CoreWorkerDirectActorTaskSubmitter per-actor state)."""

    __slots__ = ("actor_id", "seqno", "conn", "address", "state", "buffer",
                 "inflight", "resolving", "incarnation", "death_cause",
                 "death_info", "max_pending", "creation_arg_holds")

    def __init__(self, actor_id: bytes):
        self.actor_id = actor_id
        self.seqno = 0
        self.conn: Optional[rpc.Connection] = None
        self.address = ""
        self.state = "UNRESOLVED"
        # (spec, seqno) awaiting send
        self.buffer: deque[Tuple[TaskSpec, int]] = deque()
        self.inflight: Dict[int, Tuple[TaskSpec, int]] = {}  # seqno -> (spec, retries)
        self.resolving = False
        self.incarnation = -1
        self.death_cause = ""
        # structured death cause from the GCS actor table (see
        # exceptions.ActorDiedError.cause) — attached to every
        # ActorDiedError this queue raises
        self.death_info: dict = {}
        self.max_pending = -1


class SpmdGang:
    """Driver handle to a gang-scheduled SPMD worker group.

    Formation books ``world_size`` workers across the cluster in ONE
    all-or-nothing lease round (``RequestGangLease`` — the home raylet
    fans peer bookings out itself, so rpc telemetry shows exactly one
    gang-lease call, never N ``RequestWorkerLease``s). Members are
    adopted as rank-pinned :class:`LeasedWorker`s: ``run()`` pushes one
    ``max_retries=0`` task per rank straight to its member, so a step
    is deterministic in placement and a dead member fails its task with
    :class:`~ray_tpu.exceptions.WorkerCrashedError` instead of silently
    re-running elsewhere. Incarnations are epoch-fenced like actors: a
    member death marks the gang broken; ``reform()`` books epoch+1 and
    the raylet rejects any stale push from the previous incarnation."""

    def __init__(self, core: "CoreWorker", world_size: int,
                 resources: Dict[str, float], runtime_env):
        self.core = core
        self.gang_id = os.urandom(16)
        self.world_size = world_size
        self.resources = resources
        self.runtime_env = runtime_env
        self.epoch = 0
        self.broken = False
        self.members: List[LeasedWorker] = []  # rank-ordered
        self._released = False
        # private dispatch state, NEVER registered in scheduling_keys:
        # the pump must not see gang members as general-purpose breadth
        self._state = SchedulingKeyState(dict(resources))

    # -- formation ------------------------------------------------------

    async def _form(self) -> "SpmdGang":
        from ray_tpu._private import backoff as backoff_mod

        core = self.core
        cfg = core.config
        epoch = self.epoch + 1
        base = max(cfg.gang_lease_retry_backoff_s, 1e-3)
        bo = backoff_mod.Backoff(
            base_s=base, cap_s=max(cfg.retry_backoff_cap_s, base),
            multiplier=cfg.retry_backoff_multiplier)
        reply: dict = {}
        for attempt in range(1 + max(0, cfg.gang_lease_retry_attempts)):
            if attempt:
                await bo.sleep()
            reply, _ = await core.raylet_conn.call(
                "RequestGangLease",
                protocol.RequestGangLeaseRequest(
                    gang_id=self.gang_id, epoch=epoch,
                    count=self.world_size,
                    resources=dict(self.resources),
                    runtime_env=self.runtime_env).to_header())
            if reply.get("granted"):
                break
            if reply.get("stale_epoch"):
                # another incarnation of this gang_id advanced past us
                # — unreachable through the public API (epochs only
                # move through this handle) but fenced anyway
                raise exc.GangPlacementError(
                    f"gang epoch {epoch} is stale (raylet has "
                    f"{reply.get('current_epoch')})")
        else:
            raise exc.GangPlacementError(
                f"could not book {self.world_size} workers in one "
                f"round after {1 + max(0, cfg.gang_lease_retry_attempts)}"
                f" attempts: {reply.get('reason', 'unknown')}")

        async def _dial(m: dict) -> LeasedWorker:
            conn = await rpc.connect(
                m["worker_address"], peer_name="gang-member",
                timeout=cfg.gang_member_dial_timeout_s)
            lw = LeasedWorker(m["worker_address"], m["lease_id"],
                              m["node_id"], conn, core.raylet_address,
                              m["worker_id"])
            lw.gang = self

            def _on_drop(c, _lw=lw):
                self._member_died(_lw)

            lw.on_drop = _on_drop
            conn.on_disconnect.append(_on_drop)
            return lw

        members = sorted(reply["members"], key=lambda m: m["rank"])
        dials = [asyncio.ensure_future(_dial(m)) for m in members]
        results = await asyncio.gather(*dials, return_exceptions=True)
        failed = [r for r in results if isinstance(r, BaseException)]
        if failed:
            # all-or-nothing extends to adoption: kill-release the
            # whole booking (a member that died before its first dial
            # may be mid-fork wreckage) and close the dials that DID
            # land
            for r in results:
                if isinstance(r, LeasedWorker):
                    await self._close_member(r)
            try:
                await core.raylet_conn.call(
                    "ReleaseGangLease",
                    protocol.ReleaseGangLeaseRequest(
                        gang_id=self.gang_id, epoch=epoch,
                        kill=True).to_header())
            except ConnectionError:
                pass  # raylet gone; owner-liveness watch reclaims
            raise exc.GangPlacementError(
                f"gang member adoption failed: {failed[0]}")
        self.epoch = epoch
        self.broken = False
        self._released = False
        self.members = list(results)
        self._state.workers = list(self.members)
        return self

    def _member_died(self, lw: LeasedWorker) -> None:
        # a dead member invalidates the WHOLE step: in-flight push
        # futures on its conn error out and fail their tasks with
        # WorkerCrashedError (max_retries=0); surviving ranks' results
        # still land, but the epoch fence stops any further steps
        if not self._released:
            self.broken = True

    # -- steps ----------------------------------------------------------

    def run(self, fn, args_per_rank: Optional[Sequence] = None,
            name: Optional[str] = None) -> List[ObjectRef]:
        """Run ``fn`` once per rank, pinned to the gang's members.

        ``args_per_rank[rank]`` (a tuple/list) becomes the call args for
        that rank; with the default None each rank is called as
        ``fn(rank)``. Returns the rank-ordered list of result refs.
        Step tasks run with ``max_retries=0``: a dead member fails its
        slot with WorkerCrashedError and breaks the gang."""
        if args_per_rank is not None and \
                len(args_per_rank) != self.world_size:
            raise ValueError(
                f"args_per_rank has {len(args_per_rank)} entries for a "
                f"{self.world_size}-rank gang")
        # export on the CALLER thread (export_prepickled round-trips
        # the GCS through the sync KV facade, illegal from the loop) —
        # exactly where remote_function does it for pumped tasks
        fn_key, pickled = self.core.function_manager.prepare(fn)
        self.core.function_manager.export_prepickled(fn_key, pickled, fn)
        return self.core._run(
            self._run_step(fn, fn_key, args_per_rank, name))

    async def _run_step(self, fn, fn_key, args_per_rank, name):
        if self._released:
            raise exc.GangBrokenError("gang already released")
        if self.broken:
            raise exc.GangBrokenError(
                f"gang epoch {self.epoch} lost a member; reform() "
                f"books a fresh incarnation")
        core = self.core
        per_rank = [list(args_per_rank[r]) if args_per_rank is not None
                    else [r] for r in range(self.world_size)]
        # owned-arg readiness, as _submit_when_ready does for pumped
        # tasks (borrowed args resolve at the executing worker)
        for args in per_rank:
            for a in args:
                if isinstance(a, ObjectRef) and \
                        core.reference_counter.is_owned(a.object_id):
                    try:
                        await core.memory_store.get(a.object_id)
                    # raylint: disable=exception-hygiene — errored deps surface at the executing worker
                    except Exception:
                        pass
        return core._submit_gang_step(
            self, fn_key, name or getattr(fn, "__name__", "gang_step"),
            per_rank)

    # -- teardown / re-formation ---------------------------------------

    async def _close_member(self, lw: LeasedWorker) -> None:
        if lw.on_drop is not None and not lw.conn.closed and \
                lw.on_drop in lw.conn.on_disconnect:
            lw.conn.on_disconnect.remove(lw.on_drop)
        if not lw.conn.closed:
            await lw.conn.close()

    def reform(self) -> "SpmdGang":
        """Book a fresh incarnation at epoch+1. The raylet releases the
        previous incarnation's bookings first (kill-releasing broken
        members — they may be mid-step wreckage) and fences every stale
        push from the old epoch."""
        return self.core._run(self._reform_async())

    async def _reform_async(self) -> "SpmdGang":
        for lw in self.members:
            await self._close_member(lw)
        self.members = []
        self._state.workers = []
        return await self._form()

    def release(self) -> None:
        """Tear the gang down: one ReleaseGangLease to the home raylet
        releases every member cluster-wide (kill when broken — a
        possibly mid-step worker must not be recycled as idle)."""
        self.core._run(self._release_async())

    shutdown = release

    async def _release_async(self) -> None:
        if self._released:
            return
        self._released = True
        for lw in self.members:
            await self._close_member(lw)
        try:
            await self.core.raylet_conn.call(
                "ReleaseGangLease",
                protocol.ReleaseGangLeaseRequest(
                    gang_id=self.gang_id, epoch=self.epoch,
                    kill=self.broken).to_header())
        except ConnectionError:
            pass  # raylet gone; its teardown reclaimed everything
        self.members = []
        self._state.workers = []


class CoreWorker:
    def __init__(self, mode: str, config: RayTpuConfig,
                 gcs_address: str, raylet_address: str,
                 session_dir: str, job_id: bytes = b"",
                 worker_id: bytes = b"", node_id: bytes = b"",
                 loop: Optional[asyncio.AbstractEventLoop] = None,
                 log_to_driver: bool = False):
        assert mode in ("driver", "worker")
        if os.environ.get("RAY_TPU_TRACE", "") not in ("", "0"):
            # same truthiness predicate as tracing.enabled()
            # honor env-var-only activation (tracing.py's documented
            # contract): importing registers the module into
            # _tracing_mod, arming _trace_ctx without putting
            # os.environ on the hot path
            from ray_tpu.util import tracing  # noqa: F401
        self.mode = mode
        self.log_to_driver = log_to_driver
        self.config = config
        self.gcs_address = gcs_address
        self.raylet_address = raylet_address
        self.session_dir = session_dir
        self.worker_id = worker_id or WorkerID.from_random().binary()
        self.node_id = node_id
        self.job_id = job_id

        if loop is None:
            self._loop_thread = rpc.EventLoopThread(f"rtpu-{mode}-io")
            self.loop = self._loop_thread.loop
        else:
            self._loop_thread = None
            self.loop = loop

        # Warm the native copy tier at process boot (copy_into itself
        # never builds — a cold-cache compile must not reach any event
        # loop; here we are still on the constructing thread).
        from ray_tpu._private import native as _native
        _native.load_fastpath()

        self.memory_store = MemoryStore()
        self.reference_counter = ReferenceCounter()
        self.serialization_context = SerializationContext()
        self.serialization_context.set_object_ref_reducer(
            self._serialize_ref, self._deserialize_ref)
        self.serialization_context.set_actor_handle_reducer(
            self._serialize_actor_handle, self._deserialize_actor_handle)

        self.pending_tasks: Dict[bytes, PendingTaskEntry] = {}
        self.scheduling_keys: Dict[int, SchedulingKeyState] = {}
        self.actor_queues: Dict[bytes, ActorQueueState] = {}
        self.actor_handles: Dict[bytes, Any] = {}

        self.gcs_conn: Optional[rpc.Connection] = None
        self._gcs_reconnect_lock = asyncio.Lock()
        self.raylet_conn: Optional[rpc.Connection] = None
        # worker_id -> (monotonic ts, structured WORKER_OOM cause),
        # recorded by a raylet's WorkerOOMKilled call before its memory
        # watchdog kills a worker this owner leased (the ack-then-kill
        # ordering means the cause is here before the worker socket
        # drops). Bounded, and time-bounded at lookup: a kill the
        # raylet's re-grant guard ABORTED leaves an entry with no
        # matching death — without the age check, that worker's later
        # unrelated crash would be misclassified as an OOM kill.
        self._oom_worker_kills: Dict[bytes, tuple] = {}
        self._server = rpc.RpcServer(self._owner_handlers(), name=f"cw-{mode}")
        self.address = ""
        self._owner_conns: Dict[str, rpc.Connection] = {}
        # Cached control-plane connections to REMOTE raylets hosting ring
        # collective members (the local raylet rides raylet_conn). Keyed
        # by raylet address; closed with the owner connections.
        self._ring_conns: Dict[str, rpc.Connection] = {}
        self._attached: Dict[ObjectID, AttachedObject] = {}
        self._attached_lock = threading.Lock()
        self.function_manager = FunctionManager(self._kv_put_sync, self._kv_get_sync)
        # runtime envs: job-level default + per-driver upload dedupe cache
        self.job_runtime_env: Optional[Dict] = None
        self._uploaded_working_dirs: Dict[str, str] = {}
        self._task_counter = itertools.count(1)
        self._put_counter = itertools.count(1)
        # Submission batching: the caller thread appends specs here and
        # schedules ONE loop wakeup per burst instead of one
        # run_coroutine_threadsafe per task (the round-1 hot-path cost).
        self._submit_buffer: deque = deque()
        self._submit_scheduled = False
        # Batched local-ref decrefs: ObjectRef.__del__ is a per-object
        # hot path (dropping a list of 1M refs); it appends here
        # (GIL-atomic) and the loop drains under ONE lock round trip.
        self._decref_buffer: deque = deque()
        self._decref_scheduled = False
        self._current_task_id: bytes = b""
        # Cached cluster node table for locality lease targeting.
        self._node_table: Dict[bytes, str] = {}
        self._node_table_ts = -1e9
        self._shutdown = False
        self.task_executor = None   # set in worker mode by worker_main
        # Task-lifecycle recorder (task_events.py): owner-side
        # transitions land here and flush with the metrics report loop.
        # The executor (worker mode) records RUNNING/FINISHED/FAILED
        # into the same buffer.
        self.task_events = TaskEventBuffer(
            config.task_events_buffer_size,
            enabled=config.task_events_enabled)
        # Object-lifecycle recorder (object_events.py): the reference
        # counter stamps CREATED/BORROWED/CONTAINED/location/
        # OUT_OF_SCOPE transitions into this buffer; flushed with the
        # same metrics-report cadence (AddObjectEvents).
        self.object_events = ObjectEventBuffer(
            config.object_events_buffer_size,
            enabled=config.object_events_enabled)
        self.reference_counter.events = self.object_events
        # Cluster-event plane (events.py): this process's emitter feeds
        # a bounded buffer flushed on the metrics-report cadence
        # (AddClusterEvents) — driver/worker-side structured events
        # reach the GCS table without their own RPC.
        from ray_tpu._private.events import ClusterEventBuffer, EventEmitter
        self.cluster_events = ClusterEventBuffer(
            getattr(config, "cluster_event_buffer_size", 4096))
        self.events = EventEmitter(
            mode, os.path.join(session_dir, "logs")
            if config.event_log_enabled else None,
            buffer=self.cluster_events)
        # Control-plane flight recorder config for this process
        # (per-method RPC telemetry + loop-lag probe, rpc.py).
        rpc.telemetry.configure(config)
        self._task_events: List[dict] = []
        self._profile_flush_task = None
        self._metrics_report_task = None
        # Set by the actor module so the core worker can build handles
        # without import cycles.
        self._actor_handle_factory: Optional[Callable] = None

        self.stats = {"tasks_submitted": 0, "tasks_finished": 0,
                      "tasks_retried": 0, "tasks_stolen": 0,
                      "actor_tasks_submitted": 0,
                      "puts": 0, "gets": 0,
                      # streaming leases: per-task dispatch split (the
                      # owner-side credit hit-rate) + window traffic
                      "credit_dispatches": 0, "legacy_dispatches": 0,
                      "lease_credits_received": 0,
                      "lease_credits_activated": 0,
                      "lease_credits_revoked": 0}
        # lease_ids of credits whose worker connect is still in flight:
        # a concurrent RevokeLeaseCredits must not report these as
        # released (the raylet would re-lease the worker under us)
        self._activating_credits: set = set()

        # Native fused submit path (cpp/fastpath.c), created lazily on
        # the first template submission (needs self.address, i.e. post-
        # connect). None until then; False-y sentinel on init failure.
        self._fast_ctx = None
        self._fast_ctx_failed = False

    # ------------------------------------------------------------ lifecycle

    def connect(self):
        self._run(self._connect_async())

    async def _connect_async(self):
        sock_dir = os.path.join(self.session_dir, "sockets")
        os.makedirs(sock_dir, exist_ok=True)
        self.address = await self._server.listen(
            f"unix://{sock_dir}/cw-{WorkerID(self.worker_id).hex()[:12]}")
        self.reference_counter.own_address = self.address
        self.reference_counter.add_release_callback(self._on_object_released)
        self.reference_counter.add_borrow_removed_callback(self._on_borrow_removed)
        self.gcs_conn = await rpc.connect(
            self.gcs_address,
            handlers={"Published": self._handle_published},
            peer_name="gcs")
        if self.mode == "driver":
            reply, _ = await self.gcs_conn.call("AddJob", {
                "driver_address": self.address})
            self.job_id = reply["job_id"]
        # Share the server's handler dict: the raylet pushes CreateActor /
        # PushTask over this connection (workers), and the TaskExecutor
        # registers its handlers into the same dict.
        self.raylet_conn = await rpc.connect(
            self.raylet_address, handlers=self._server.handlers,
            peer_name="raylet")
        await self.gcs_conn.call("Subscribe", {"channel": "ACTOR"})
        if self.mode == "driver" and self.log_to_driver:
            await self.gcs_conn.call("Subscribe", {"channel": "LOGS"})
        self._driver_task_id = TaskID.for_driver(JobID(self.job_id)) \
            if self.job_id else TaskID.from_random()
        # cached lineage prefix for the raw-bytes submit hot path
        self._task_lineage_prefix = \
            self._driver_task_id.binary()[:ACTOR_ID_SIZE]
        if self.config.profiling_enabled:
            self._profile_flush_task = self.loop.create_task(
                self._profile_flush_loop())
        # Claim the process's shipper role BEFORE the first report
        # period elapses: an in-process raylet's early heartbeats would
        # otherwise ship the shared process telemetry/registry under a
        # second (node-) reporter id for the first period.
        from ray_tpu._private import metrics as metrics_mod
        metrics_mod.mark_core_reporter()
        self._metrics_report_task = self.loop.create_task(
            self._metrics_report_loop())

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True
        try:
            self._run(self._shutdown_async(), timeout=5)
        except Exception:
            logger.debug("async shutdown incomplete", exc_info=True)
        if self._loop_thread is not None:
            self._loop_thread.stop()

    async def _shutdown_async(self):
        if self._profile_flush_task:
            self._profile_flush_task.cancel()
        if getattr(self, "_metrics_report_task", None):
            self._metrics_report_task.cancel()
        if self.gcs_conn and not self.gcs_conn.closed:
            # last task-event flush: terminal transitions observed since
            # the previous periodic flush should outlive this process
            # independent try blocks: a hung task-event flush must not
            # also cost the object-event batch (and vice versa)
            try:
                await asyncio.wait_for(self._flush_task_events(), timeout=2)
            except (asyncio.TimeoutError, ConnectionError):
                pass
            try:
                await asyncio.wait_for(self._flush_object_events(),
                                       timeout=2)
            except Exception:  # noqa: BLE001 — shutdown must reach MarkJobFinished
                logger.debug("object-event flush at shutdown failed",
                             exc_info=True)
            try:
                await asyncio.wait_for(self._flush_cluster_events(),
                                       timeout=2)
            except Exception:  # noqa: BLE001 — shutdown must reach MarkJobFinished
                logger.debug("cluster-event flush at shutdown failed",
                             exc_info=True)
        if self.mode == "driver" and self.gcs_conn and not self.gcs_conn.closed:
            try:
                await self.gcs_conn.call("MarkJobFinished",
                                         {"job_id": self.job_id}, timeout=2)
            except Exception:
                logger.debug("MarkJobFinished at shutdown failed",
                             exc_info=True)
        for key_state in self.scheduling_keys.values():
            for lw in key_state.workers:
                try:
                    await self._return_lease(lw)
                except Exception:
                    logger.debug("lease return at shutdown failed",
                                 exc_info=True)
        await self._server.close()
        for conn in list(self._owner_conns.values()):
            await conn.close()
        for conn in list(self._ring_conns.values()):
            await conn.close()
        if self.gcs_conn:
            await self.gcs_conn.close()
        if self.raylet_conn:
            await self.raylet_conn.close()
        with self._attached_lock:
            for att in self._attached.values():
                att.close()
            self._attached.clear()

    def _run(self, coro, timeout=None):
        """Run a coroutine on the IO loop from any thread (never from the
        loop thread itself — that would deadlock)."""
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is self.loop:
            coro.close()
            raise RuntimeError("sync API called from the IO loop thread")
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    async def _gcs_call(self, method: str, header=None, bufs=(),
                        timeout=None):
        """GCS RPC with transparent redial: a restarted GCS (journal
        replay) drops every connection; callers should not fail for that
        (reference: workers re-resolve the GCS address on failover,
        core_worker/gcs_server_address_updater.cc). Retried methods must
        be idempotent server-side (RegisterActor dedupes by actor id).
        Redial attempts repeat within ``gcs_reconnect_timeout_s``: a
        SIGKILLed GCS's listen socket can still accept for a beat, so a
        single reconnect may land on the dying process and lose its
        retried call too — keep going until the budget, not one shot."""
        try:
            return await self.gcs_conn.call(method, header, bufs=bufs,
                                            timeout=timeout)
        except ConnectionError:
            if self._shutdown:
                raise
            loop = asyncio.get_running_loop()
            deadline = loop.time() + max(
                self.config.gcs_reconnect_timeout_s, 0.1)
            while True:
                try:
                    # One reconnect at a time: concurrent failures reuse
                    # the winner's connection instead of each dialing
                    # (and double-subscribing) their own.
                    async with self._gcs_reconnect_lock:
                        if self.gcs_conn is None or self.gcs_conn.closed:
                            conn = await rpc.connect(
                                self.gcs_address,
                                handlers={
                                    "Published": self._handle_published},
                                peer_name="gcs")
                            await conn.call("Subscribe",
                                            {"channel": "ACTOR"})
                            self.gcs_conn = conn
                    return await self.gcs_conn.call(method, header,
                                                    bufs=bufs,
                                                    timeout=timeout)
                except ConnectionError:
                    if self._shutdown or loop.time() >= deadline:
                        raise
                    await asyncio.sleep(0.1)

    # ------------------------------------------------------------ KV helpers

    def gcs_call_sync(self, method: str, header: dict) -> dict:
        """Generic blocking GCS RPC from API threads (state dumps)."""
        reply, _ = self._run(self._gcs_call(method, header))
        return reply

    def _kv_put_sync(self, key: bytes, value: bytes):
        self._run(self._gcs_call(
            "KVPut", protocol.KVPutRequest(key=key).to_header(),
            bufs=[value]))

    def _kv_get_sync(self, key: bytes) -> Optional[bytes]:
        header, bufs = self._run(self._gcs_call(
            "KVGet", protocol.KVGetRequest(key=key).to_header()))
        return bufs[0] if header.get("found") else None

    # --------------------------------------------------------- ref reducers

    def _serialize_ref(self, ref: ObjectRef):
        owner = ref.owner_address or \
            self.reference_counter.owner_address_of(ref.object_id) or self.address
        return (ref.object_id.binary(), owner)

    def _deserialize_ref(self, state):
        oid_b, owner = state
        oid = ObjectID(oid_b)
        # Record the borrow BEFORE constructing the ObjectRef: the ctor
        # increments local_refs, which would defeat add_borrowed_object's
        # first-borrow detection and the AddBorrower RPC would never fire.
        if owner and owner != self.address:
            first = self.reference_counter.add_borrowed_object(oid, owner)
            if first:
                self._fire_and_forget(self._notify_add_borrower(oid, owner))
        return ObjectRef(oid, owner_address=owner, worker=self)

    def _serialize_actor_handle(self, handle):
        return handle._serialization_state()

    def _deserialize_actor_handle(self, state):
        if self._actor_handle_factory is None:
            raise RuntimeError("actor handle factory not registered")
        return self._actor_handle_factory(self, state)

    async def _notify_add_borrower(self, oid: ObjectID, owner: str):
        try:
            conn = await self._get_owner_conn(owner)
            await conn.call("AddBorrower", {"object_id": oid.binary(),
                                            "borrower": self.address})
        except ConnectionError:
            pass

    def _fire_and_forget(self, coro):
        if self.loop.is_running():
            asyncio.run_coroutine_threadsafe(coro, self.loop)
        else:
            coro.close()  # interpreter teardown: drop without a warning

    def kv_put_nowait(self, key: bytes, value: bytes) -> None:
        """Fire-and-forget internal-KV put (tracing/telemetry export —
        must never block or fail the caller's thread)."""
        self._fire_and_forget(self._gcs_call(
            "KVPut",
            protocol.KVPutRequest(key=key, overwrite=True).to_header(),
            bufs=[value]))

    async def _get_owner_conn(self, address: str) -> rpc.Connection:
        if address == self.address:
            raise RuntimeError("attempted self-connection for owner RPC")
        conn = self._owner_conns.get(address)
        if conn is None or conn.closed:
            # Share the server's handler dict (same as raylet_conn): a
            # REMOTE raylet this owner leased from must be able to call
            # back over this pipe — e.g. WorkerOOMKilled before a
            # watchdog kill, which classifies the death as a retriable
            # OutOfMemoryError instead of a generic worker crash.
            conn = await rpc.connect(address,
                                     handlers=self._server.handlers,
                                     peer_name=f"owner@{address}")
            self._owner_conns[address] = conn
        return conn

    # --------------------------------------------------------- owner services

    def _owner_handlers(self):
        handlers = {
            "GetObject": self._handle_get_object,
            "GetObjectLocations": self._handle_get_object_locations,
            "AddObjectLocation": self._handle_add_object_location,
            "AddBorrower": self._handle_add_borrower,
            "RemoveBorrower": self._handle_remove_borrower,
            "WorkerOOMKilled": self._handle_worker_oom_killed,
            "ProbeObjectLiveness": self._handle_probe_object_liveness,
            "GrantLeaseCredits": self._handle_grant_lease_credits,
            "RevokeLeaseCredits": self._handle_revoke_lease_credits,
            "Ping": self._handle_ping,
        }
        return handlers

    # ------------------------------------------------- streaming leases

    async def _handle_grant_lease_credits(self, conn, header, bufs):
        """Raylet push: pre-granted worker slots for one scheduling
        class plus the window target. Each credit is activated (worker
        socket dialed) EAGERLY here, off the submit path — by the time
        the pump dispatches against it there is zero control-plane work
        left, which is the whole point of the stream."""
        if self._shutdown:
            return {}
        req = protocol.GrantLeaseCreditsRequest.from_header(header)
        sc = req.sched_class
        state = self.scheduling_keys.get(sc)
        if state is None:
            state = self.scheduling_keys[sc] = SchedulingKeyState(
                req.get("resources") or {})
        if req.raylet_address == self.raylet_address:
            # Only the HOME raylet's window sizes the pump's stream
            # floor and legacy-band clamp: in spillback clusters a
            # remote raylet pushes its own (differently-sized) window
            # each beat, and last-push-wins would flap the breadth
            # every heartbeat. Remote credits still activate below —
            # they just don't steer the local policy.
            state.credit_target = int(req.window_target)
            state.cluster_slots = int(req.get(
                "cluster_slots", req.window_target))
        for cr in req.get("credits", ()):
            self.stats["lease_credits_received"] += 1
            self._activating_credits.add(cr["lease_id"])
            state.activating += 1
            rpc.spawn_logged(
                self._activate_credit(sc, state, cr,
                                      req.raylet_address),
                "worker-activate-credit")
        return {}

    async def _activate_credit(self, sc: int, state: SchedulingKeyState,
                               cr: dict, raylet_address: str) -> None:
        lid = cr["lease_id"]
        try:
            try:
                wconn = await rpc.connect(cr["worker_address"],
                                          peer_name="leased-worker")
            except ConnectionError:
                state.activating = max(0, state.activating - 1)
                if state.queue:
                    # the expected breadth shrank: re-evaluate (the
                    # pump may now fire a legacy fallback request)
                    self._pump_scheduling_key(sc, state)
                # dead worker (or its whole node): hand the slot back
                # so it isn't parked; a dead raylet makes this a no-op
                # and its conn-drop already reclaimed everything.
                # worker_died=True: the dial failed, so this is a death
                # report, NOT a voluntary return — it must neither
                # decay the window's demand (the backlog is still
                # there) nor mark a dead worker idle for re-grant.
                self._activating_credits.discard(lid)
                try:
                    if raylet_address == self.raylet_address:
                        rconn = self.raylet_conn
                    else:
                        rconn = await self._get_owner_conn(raylet_address)
                    await rconn.call(
                        "ReturnWorker",
                        protocol.ReturnWorkerRequest(
                            lease_id=lid,
                            worker_died=True).to_header())
                except (ConnectionError, RuntimeError):
                    pass
                return
            if lid not in self._activating_credits or self._shutdown:
                # revoked (or shutting down) while the dial was in
                # flight: don't adopt a worker the raylet reclaimed
                state.activating = max(0, state.activating - 1)
                await wconn.close()
                return
            state.activating = max(0, state.activating - 1)
            lw = LeasedWorker(cr["worker_address"], lid, cr["node_id"],
                              wconn, raylet_address, cr["worker_id"])
            lw.via_credit = True
            state.workers.append(lw)
            state.last_grant_ts = time.monotonic()

            def _on_drop(c, _lw=lw):
                self._on_leased_worker_died(sc, state, _lw)

            lw.on_drop = _on_drop
            wconn.on_disconnect.append(_on_drop)
            self.stats["lease_credits_activated"] += 1
            if state.queue:
                self._pump_scheduling_key(sc, state)
            elif not self._try_steal(sc, state):
                self._schedule_idle_return(sc, state, lw)
        finally:
            self._activating_credits.discard(lid)

    async def _handle_revoke_lease_credits(self, conn, header, bufs):
        """Raylet call: give back up to ``max_release`` of the listed
        credits. Only credits NOT in use are relinquished — in-flight
        batches finish and busy workers stay leased (the raylet
        re-offers on a later beat). Under ``memory_pressure`` idle
        credits are released even when this class still has backlog:
        the queue falls back to legacy requests, which the pressured
        raylet answers with spill/retry-later — draining work off the
        hot node is the recovery, so the owner must not cling to its
        slots there. Ids we never saw (a chaos-dropped grant push) or
        already returned are confirmed released so the raylet's ledger
        reconciles."""
        req = protocol.RevokeLeaseCreditsRequest.from_header(header)
        ids = set(req.lease_ids)
        try:
            max_release = int(req.get("max_release", len(ids)))
        except (TypeError, ValueError):
            max_release = len(ids)
        aggressive = req.get("reason") == "memory_pressure"
        released: List[int] = []
        seen: set = set()
        # snapshot: the awaited conn.close below yields to the loop,
        # where a first-submit of a new remote function may create a
        # scheduling class mid-iteration
        for sc, state in list(self.scheduling_keys.items()):
            for lw in list(state.workers):
                if lw.lease_id not in ids or not lw.via_credit:
                    continue
                seen.add(lw.lease_id)
                if len(released) >= max_release or lw.inflight > 0:
                    continue
                if state.queue and not aggressive:
                    continue  # about to be used; keep it
                if not aggressive and lw.idle_timer is not None:
                    # inside its idle-keepalive grace: the keepalive's
                    # own ReturnWorker (or the next burst) decides,
                    # exactly like a legacy lease — the raylet's
                    # periodic reconcile offer must not defeat
                    # warm-lease reuse for sync-loop callers
                    continue
                state.workers.remove(lw)
                if lw.idle_timer is not None:
                    lw.idle_timer.cancel()
                    lw.idle_timer = None
                # unregister the death watch FIRST: this close is a
                # revocation, not a worker death — firing the retry
                # path would double-return the lease as worker_died
                # and strand a healthy worker in the LEASED state
                if lw.on_drop is not None and \
                        lw.on_drop in lw.conn.on_disconnect:
                    lw.conn.on_disconnect.remove(lw.on_drop)
                await lw.conn.close()
                released.append(lw.lease_id)
        for lid in ids - seen:
            if lid not in self._activating_credits and \
                    len(released) < max_release:
                released.append(lid)
        self.stats["lease_credits_revoked"] += len(released)
        return protocol.RevokeLeaseCreditsReply(
            released=released).to_header()

    async def _handle_worker_oom_killed(self, conn, header, bufs):
        """Raylet push: the node memory watchdog is killing a worker
        this owner leased. Recording the cause BEFORE the worker socket
        drops lets _retry_or_fail_after_worker_death classify the death
        as a retriable OutOfMemoryError (dedicated task_oom_retries
        budget) instead of a generic worker crash."""
        cause = header.get("cause") or {"kind": "WORKER_OOM"}
        self._oom_worker_kills[header["worker_id"]] = \
            (time.monotonic(), cause)
        while len(self._oom_worker_kills) > 64:
            self._oom_worker_kills.pop(
                next(iter(self._oom_worker_kills)))
        return {}

    async def _handle_ping(self, conn, header, bufs):
        return {"ok": True, "mode": self.mode}

    async def _handle_probe_object_liveness(self, conn, header, bufs):
        """Raylet leak-detector probe: for each object id, does this
        owner still hold ANY reference (local/submitted/borrowed)?
        ``False`` means the owner released it — a store still holding
        its segment missed the FreeObject and is leaking. One batched
        call per (raylet, owner) per sweep; has_reference is a
        GIL-atomic dict probe, so a large batch is cheap."""
        has = self.reference_counter.has_reference
        return {"live": [bool(has(ObjectID(b)))
                         for b in header.get("object_ids", ())]}

    async def _handle_get_object(self, conn, header, bufs):
        oid = ObjectID(header["object_id"])
        timeout = header.get("timeout", 60.0)
        try:
            obj = await self.memory_store.get(oid, timeout=timeout)
        except asyncio.TimeoutError:
            return {"found": False}
        if obj is IN_PLASMA:
            return {"found": True, "in_plasma": True,
                    "locations": sorted(
                        self.reference_counter.get_locations(oid))}
        assert isinstance(obj, SerializedObject)
        # SNAPSHOT: a locally-put small value's frames alias the
        # caller's buffers, which user code may mutate while the
        # coalesced reply flush is pending — live views could send
        # torn bytes. Small objects only (large ones are IN_PLASMA).
        meta, frames = obj.to_wire()
        return {"found": True, "in_plasma": False, "metadata": meta,
                "contained": [r.binary() for r in obj.contained_refs]}, frames

    async def _handle_get_object_locations(self, conn, header, bufs):
        oid = ObjectID(header["object_id"])
        return {"locations": sorted(self.reference_counter.get_locations(oid))}

    async def _handle_add_object_location(self, conn, header, bufs):
        """A raylet pulled a replica: keep the owner's location index
        complete so release-time frees reach every copy. Replies
        ok=False if the ref was already released (the report lost the
        race with the final release) so the raylet frees its copy."""
        ok = self.reference_counter.add_location_if_tracked(
            ObjectID(header["object_id"]), header["node_id"])
        return {"ok": ok}

    async def _handle_add_borrower(self, conn, header, bufs):
        self.reference_counter.add_borrower(
            ObjectID(header["object_id"]), header["borrower"])
        return {"ok": True}

    async def _handle_remove_borrower(self, conn, header, bufs):
        self.reference_counter.remove_borrower(
            ObjectID(header["object_id"]), header["borrower"])
        return {"ok": True}

    # -------------------------------------------------------- release paths

    def _on_object_released(self, oid: ObjectID, record):
        """Last reference anywhere dropped: delete the value everywhere.
        ``record`` is the popped Reference — the live table no longer has
        this id, so ownership/locations must come from the record."""
        self.memory_store.delete(oid)
        with self._attached_lock:
            att = self._attached.pop(oid, None)
        if att is not None:
            att.close()
        if record.owned:
            self._release_lineage(oid)
            if record.in_plasma and record.pinned_lineage and \
                    self.object_events.enabled:
                # lineage-pin transition, plasma returns only (a 1M
                # drain of small returns must not flood the buffer):
                # the creating task's lineage retention just ended
                self.object_events.record(
                    oid.binary(), LINEAGE_RELEASED,
                    {"task": oid.binary()[:TASK_ID_SIZE].hex()})
        if record.owned and record.in_plasma:
            locations = sorted(record.locations or ())
            self._fire_and_forget(self._free_remote(oid, locations))

    def _release_lineage(self, oid: ObjectID) -> None:
        """Last reference to an owned return object dropped: release the
        creating task's lineage once NO return of that task can still
        need reconstruction (reference:
        TaskManager::RemoveLineageReference,
        src/ray/core_worker/task_manager.cc). PendingTaskEntry's
        ``lineage_pinned`` is the lifecycle flag: False = in flight,
        True = completed + retained only for lineage, None = in flight
        but all returns already dead (completion drops the entry)."""
        me = oid.binary()
        tid_b = me[:TASK_ID_SIZE]  # release path is per-call hot
        entry = self.pending_tasks.get(tid_b)
        if entry is None:
            return
        if len(entry.return_ids) > 1:
            for rid in entry.return_ids:
                if rid.binary() != me and \
                        self.reference_counter.has_reference(rid):
                    return  # a sibling return is still reachable
        if entry.lineage_pinned:
            self.pending_tasks.pop(tid_b, None)
        elif entry.lineage_pinned is False:
            entry.lineage_pinned = None

    async def _free_remote(self, oid: ObjectID, locations):
        # Primary copy may live on remote nodes too: the local raylet frees
        # its own copy and forwards FreeObject to every listed location
        # (reference: ReferenceCounter release → plasma delete on all nodes).
        try:
            if self.raylet_conn and not self.raylet_conn.closed:
                await self.raylet_conn.call("FreeObject", {
                    "object_id": oid.binary(),
                    "locations": sorted(locations) if locations else []})
        except ConnectionError:
            pass

    def _on_borrow_removed(self, oid: ObjectID, owner_address: str):
        async def _notify():
            try:
                conn = await self._get_owner_conn(owner_address)
                await conn.call("RemoveBorrower", {
                    "object_id": oid.binary(), "borrower": self.address})
            except (ConnectionError, RuntimeError):
                pass
        self._fire_and_forget(_notify())

    # ---------------------------------------------------------------- put

    def put(self, value: Any, _owner_ref: Optional[ObjectRef] = None) -> ObjectRef:
        serialized = self.serialization_context.serialize(value)
        oid = self._next_put_id()
        self.stats["puts"] += 1
        if serialized.total_bytes() <= self.config.max_direct_call_object_size:
            # Small object: entirely in-process — no IO-loop round trip.
            self.reference_counter.add_owned_with_local_ref(oid)
            if serialized.contained_refs:
                self.reference_counter.add_contained_refs(
                    oid, serialized.contained_refs)
            self.memory_store.put(oid, serialized)
            return ObjectRef(oid, owner_address=self.address, worker=self,
                             call_site="put", skip_adding_local_ref=True)
        self._run(self._put_serialized(oid, serialized))
        return ObjectRef(oid, owner_address=self.address, worker=self,
                         call_site="put")

    def put_async(self, value: Any):
        """Non-blocking put for async-actor callers — the HTTP proxy's
        zero-copy ingress. ``put`` blocks its calling thread on the IO
        loop's seal round trip, which inside an async actor would stall
        the user loop and every other request coroutine on it; here the
        serialize happens on the calling thread (bytes bodies are
        META_RAW: no copy) and the AllocSegment-lease write + seal are
        scheduled onto the IO loop. Returns ``(ref, done)`` where
        ``done`` is a concurrent.futures.Future the caller must await
        (``asyncio.wrap_future``) before shipping the ref — a failed
        seal (store full) surfaces there, typed."""
        serialized = self.serialization_context.serialize(value)
        oid = self._next_put_id()
        self.stats["puts"] += 1
        if serialized.total_bytes() <= \
                self.config.max_direct_call_object_size:
            self.reference_counter.add_owned_with_local_ref(oid)
            if serialized.contained_refs:
                self.reference_counter.add_contained_refs(
                    oid, serialized.contained_refs)
            self.memory_store.put(oid, serialized)
            done: "concurrent.futures.Future" = concurrent.futures.Future()
            done.set_result(None)
            return ObjectRef(oid, owner_address=self.address, worker=self,
                             call_site="put",
                             skip_adding_local_ref=True), done
        done = asyncio.run_coroutine_threadsafe(
            self._put_serialized(oid, serialized), self.loop)
        return ObjectRef(oid, owner_address=self.address, worker=self,
                         call_site="put"), done

    def _next_put_id(self) -> ObjectID:
        # Put ids live in the current task's index space after returns
        # (reference: ObjectID::FromIndex with put_index offset).
        base = TaskID(self._current_task_id) if self._current_task_id \
            else self._driver_task_id
        return base.object_id(100_000 + next(self._put_counter))

    async def _put_serialized(self, oid: ObjectID, serialized: SerializedObject,
                              pin: bool = True):
        self.reference_counter.add_owned_object(oid)
        if serialized.contained_refs:
            self.reference_counter.add_contained_refs(
                oid, serialized.contained_refs)
        if serialized.total_bytes() <= self.config.max_direct_call_object_size:
            self.memory_store.put(oid, serialized)
            return
        segment, size = await self._write_segment_async(serialized)
        # owner_address feeds the raylet's leak detector: the sweep
        # probes this owner's live references against the stored
        # segment (object_events.py).
        reply, _ = await self.raylet_conn.call(
            "SealObject", protocol.SealObjectRequest(
                object_id=oid.binary(), segment=segment, size=size,
                pin=pin, owner_address=self.address).to_header())
        if not reply.get("ok"):
            raise exc.ObjectStoreFullError(
                f"object {oid.hex()} ({size} bytes) does not fit in the store")
        self.reference_counter.add_location(oid, reply["node_id"], size)
        self.memory_store.put(oid, IN_PLASMA)

    async def _write_segment_async(self, serialized: SerializedObject):
        """Zero-copy segment write: lease a recycled warm segment from
        the raylet when one fits (AllocSegment — fresh tmpfs pages are
        the dominant cost of a cold large put), and run the fill in an
        executor thread so the IO loop keeps pumping while the
        (GIL-releasing, striped) memcpy of a huge object runs. The plan
        is computed once and shared with write_segment."""
        plan = plan_segment(serialized)
        size = plan[3]
        alloc = None
        if size >= RECYCLE_MIN_BYTES and self.raylet_conn is not None:
            try:
                reply, _ = await self.raylet_conn.call(
                    "AllocSegment", {"size": size}, timeout=5)
                if reply.get("found"):
                    alloc = (reply["segment"], reply["size"])
            except (ConnectionError, asyncio.TimeoutError):
                pass  # fresh segment below — the lease is an optimization
        try:
            if size >= RECYCLE_MIN_BYTES:
                return await asyncio.get_running_loop().run_in_executor(
                    None, write_segment, serialized, alloc, plan)
            return write_segment(serialized, alloc, plan)
        except BaseException:
            # Seal-or-abort: a failed fill must hand the lease back, or
            # its pages sit in the store's _lent table until the stale
            # sweep (raylint shm-lifecycle). Best-effort one-way push —
            # the sweep remains the backstop if the raylet is gone.
            if alloc is not None and self.raylet_conn is not None \
                    and not self.raylet_conn.closed:
                try:
                    await self.raylet_conn.push(
                        "AbortSegment", {"segment": alloc[0]})
                except (ConnectionError, OSError):
                    pass  # raylet gone; stale-lease sweep reclaims
            raise

    def write_segment_sync(self, serialized: SerializedObject):
        """Blocking variant for executor-pool callers (task returns in
        the worker): same AllocSegment lease + direct-write pipeline."""
        return self._run(self._write_segment_async(serialized))

    # ---------------------------------------------------------------- get

    def get(self, refs: Sequence[ObjectRef], timeout: float | None = None):
        self.stats["gets"] += len(refs)
        # Fast path: every value already local and in-process — deserialize
        # on the caller thread, skipping the IO-loop round trip.
        objs = []
        for ref in refs:
            obj = self.memory_store.get_if_exists(ref.object_id)
            if obj is None or obj is IN_PLASMA:
                objs = None
                break
            objs.append(obj)
        if objs is not None:
            return [self._deserialize_obj(o) for o in objs]
        return self._run(self.get_objects_async(refs, timeout=timeout))

    def get_async(self, ref: ObjectRef) -> asyncio.Future:
        """Future on the IO loop (for ``await ref`` inside async actors)."""
        return asyncio.run_coroutine_threadsafe(
            self._get_one(ref, None), self.loop)

    # concurrent.futures alias used by ObjectRef.future().
    get_future = get_async

    async def get_objects_async(self, refs: Sequence[ObjectRef],
                                timeout: float | None = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        # Bulk barrier: for OWNED ids still in flight, one future covers
        # the whole batch (memory_store.wait_many) instead of a future +
        # wait_for per ref — the 1M-drain get side was ~3us/task of
        # per-ref coroutine machinery.  Non-owned / plasma ids take the
        # per-ref path below as before.
        store_get = self.memory_store.get_if_exists
        is_owned = self.reference_counter.is_owned
        waitable = [ref.object_id for ref in refs
                    if store_get(ref.object_id) is None
                    and is_owned(ref.object_id)]
        if waitable:
            try:
                await self.memory_store.wait_many(
                    waitable,
                    None if deadline is None
                    else max(0.0, deadline - time.monotonic()))
            except asyncio.TimeoutError:
                raise exc.GetTimeoutError(
                    f"get() timed out waiting for "
                    f"{len(waitable)} objects") from None
        out = []
        deserialize = self.serialization_context.deserialize
        for ref in refs:
            obj = store_get(ref.object_id)
            if obj is not None and obj is not IN_PLASMA:
                out.append(deserialize(obj.metadata, obj.frames))
                continue
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise exc.GetTimeoutError(
                    f"get() timed out waiting for {ref.hex()}")
            out.append(await self._get_one(ref, remaining))
        return out

    async def _get_one(self, ref: ObjectRef, timeout: float | None):
        oid = ref.object_id
        owned = self.reference_counter.is_owned(oid)
        if owned or self.memory_store.contains(oid):
            try:
                obj = await self.memory_store.get(oid, timeout=timeout)
            except asyncio.TimeoutError:
                raise exc.GetTimeoutError(
                    f"get() timed out waiting for {oid.hex()}") from None
            if obj is IN_PLASMA:
                return await self._get_from_plasma(oid, ref.owner_address)
            return self._deserialize_obj(obj)
        # Borrowed: ask the owner.
        owner = ref.owner_address or self.reference_counter.owner_address_of(oid)
        if not owner:
            raise exc.ObjectLostError(oid.hex(), "no owner known",
                                      cause={"kind": "NO_OWNER"})
        try:
            conn = await self._get_owner_conn(owner)
            header, frames = await conn.call(
                "GetObject", {"object_id": oid.binary(),
                              "timeout": timeout if timeout is not None else 3600.0},
                timeout=timeout)
        except ConnectionError:
            raise exc.ObjectLostError(
                oid.hex(), f"owner {owner} unreachable",
                cause={"kind": "OWNER_UNREACHABLE"}) from None
        except asyncio.TimeoutError:
            raise exc.GetTimeoutError(
                f"get() timed out waiting for {oid.hex()}") from None
        if not header.get("found"):
            raise exc.ObjectLostError(oid.hex(), "owner no longer has object",
                                      cause={"kind": "OWNER_RELEASED"})
        if header.get("in_plasma"):
            return await self._get_from_plasma(oid, owner)
        obj = SerializedObject(header["metadata"], frames)
        # Cache small borrowed values locally for repeat gets.
        self.memory_store.put(oid, obj)
        return self._deserialize_obj(obj)

    async def _get_from_plasma(self, oid: ObjectID, owner_address: str):
        with self._attached_lock:
            att = self._attached.get(oid)
        if att is None:
            reply, _ = await self.raylet_conn.call(
                "EnsureObjectLocal",
                {"object_id": oid.binary(), "owner_address": owner_address})
            if not reply.get("ok") and not reply.get("segment"):
                recovered = await self._try_recover(oid)
                if not recovered:
                    raise exc.ObjectLostError(
                        oid.hex(), reply.get("reason", "pull failed"),
                        cause={"kind": "PULL_FAILED"})
                # The re-executed task may have returned the value (or an
                # error object) inline this time — prefer the memory store
                # over another plasma round trip.
                obj = self.memory_store.get_if_exists(oid)
                if obj is not None and obj is not IN_PLASMA:
                    return self._deserialize_obj(obj)
                reply, _ = await self.raylet_conn.call(
                    "EnsureObjectLocal",
                    {"object_id": oid.binary(), "owner_address": owner_address})
                if not reply.get("segment"):
                    raise exc.ObjectLostError(oid.hex(), "recovery failed",
                                              cause={"kind":
                                                     "RECOVERY_FAILED"})
            att = await asyncio.get_running_loop().run_in_executor(
                None, AttachedObject, reply["segment"])
            with self._attached_lock:
                self._attached[oid] = att
        obj = SerializedObject(att.metadata, att.frames)
        return self._deserialize_obj(obj)

    def _deserialize_obj(self, obj: SerializedObject):
        return self.serialization_context.deserialize(obj.metadata, obj.frames)

    async def _try_recover(self, oid: ObjectID) -> bool:
        """Lineage reconstruction: resubmit the creating task (reference:
        ObjectRecoveryManager, src/ray/core_worker/object_recovery_manager.h)."""
        if not self.config.lineage_reconstruction_enabled:
            return False
        entry = self.pending_tasks.get(oid.task_id().binary())
        if entry is None:
            return False
        logger.info("reconstructing %s by resubmitting task %s",
                    oid.hex()[:16], entry.spec.name)
        # The memory store still holds the stale IN_PLASMA marker, so
        # polling it would return immediately — wait for the actual task
        # completion instead. One shared waiter per entry: concurrent
        # recoveries of sibling returns resubmit the task ONCE and all
        # await the same future (shield: one caller timing out must not
        # cancel it for the rest).
        if entry.recovery_waiter is None:
            entry.recovery_waiter = self.loop.create_future()
            self.stats["tasks_retried"] += 1
            if self.task_events.enabled:
                self.task_events.record(entry.spec.task_id, RETRY,
                                        {"reason": "lineage reconstruction"})
            self._queue_spec(entry.spec)
        waiter = entry.recovery_waiter
        try:
            await asyncio.wait_for(asyncio.shield(waiter), timeout=30.0)
        except asyncio.TimeoutError:
            return False
        # raylint: disable=async-blocking — awaited above: a done future's result() is a non-blocking read
        return bool(waiter.result())

    # ---------------------------------------------------------------- wait

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: float | None = None, fetch_local: bool = True):
        return self._run(self._wait_async(refs, num_returns, timeout))

    async def _wait_async(self, refs, num_returns, timeout):
        pending = list(refs)
        ready: List[ObjectRef] = []

        async def _await_ready(ref):
            try:
                await self._object_available(ref)
            # raylint: disable=exception-hygiene — errored objects count as ready (get will raise)
            except Exception:
                pass
            return ref

        tasks = {asyncio.ensure_future(_await_ready(r)): r for r in pending}
        deadline = None if timeout is None else time.monotonic() + timeout
        try:
            while len(ready) < num_returns and tasks:
                remaining = None if deadline is None else \
                    max(0.0, deadline - time.monotonic())
                done, _ = await asyncio.wait(
                    tasks.keys(), timeout=remaining,
                    return_when=asyncio.FIRST_COMPLETED)
                if not done:
                    break
                for d in done:
                    ready.append(tasks.pop(d))
        finally:
            for t in tasks:
                t.cancel()
        ready_set = set(ready)
        ready_in_order = [r for r in refs if r in ready_set][:num_returns]
        in_order_set = set(ready_in_order)
        not_ready = [r for r in refs if r not in in_order_set]
        return ready_in_order, not_ready

    async def _object_available(self, ref: ObjectRef):
        oid = ref.object_id
        if self.reference_counter.is_owned(oid) or self.memory_store.contains(oid):
            await self.memory_store.get(oid)
            return
        owner = ref.owner_address
        conn = await self._get_owner_conn(owner)
        await conn.call("GetObject", {"object_id": oid.binary(),
                                      "timeout": 3600.0})

    # ------------------------------------------------------- SPMD gangs

    def create_gang(self, world_size: int,
                    resources: Optional[Dict[str, float]] = None,
                    runtime_env: Optional[Dict] = None) -> SpmdGang:
        """Book an SPMD gang: ``world_size`` workers across the cluster
        in ONE all-or-nothing lease round. See :class:`SpmdGang`."""
        if world_size < 1:
            raise ValueError("world_size must be >= 1")
        gang = SpmdGang(self, world_size, resources or {"CPU": 1.0},
                        self._resolve_runtime_env(runtime_env))
        return self._run(gang._form())

    def _submit_gang_step(self, gang: SpmdGang, fn_key: str, name: str,
                          per_rank_args: List[list]) -> List[ObjectRef]:
        """Loop thread: register + push one rank-pinned spec per gang
        member. Bypasses the scheduling pump entirely — placement was
        decided at gang formation, so each spec goes straight to its
        rank's worker conn with max_retries=0 (a dead member is a step
        failure, never a silent re-placement)."""
        if self.mode == "driver":
            prefix = self._task_lineage_prefix
        else:
            prefix = (self._current_task_id or
                      self._driver_task_id.binary())[:ACTOR_ID_SIZE]
        refs: List[ObjectRef] = []
        ev = self.task_events
        for rank, (lw, args) in enumerate(
                zip(gang.members, per_rank_args)):
            prepared_args, arg_holds = self._prepare_args(args) \
                if args else ((), None)
            spec = TaskSpec(
                task_id=make_task_id_bytes(prefix), job_id=self.job_id,
                task_type=TASK_NORMAL, name=f"{name}:{rank}",
                fn_key=fn_key, args=prepared_args, num_returns=1,
                resources=dict(gang.resources), max_retries=0,
                retry_exceptions=False, owner_address=self.address,
                owner_worker_id=self.worker_id,
                runtime_env=gang.runtime_env, trace_ctx=_trace_ctx())
            refs.extend(self._register_task(spec, arg_holds))
            if ev.enabled:
                ev.record(spec.task_id, SUBMITTED,
                          {"name": spec.name,
                           "gang": gang.gang_id.hex()[:12],
                           "rank": rank, "epoch": gang.epoch})
            lw.inflight += 1
            self._push_task_batch_nowait(
                spec.scheduling_class, gang._state, lw, [spec])
        return refs

    # ------------------------------------------------ distributed arrays

    def put_sharded(self, array, mesh, spec):
        """Shard ``array`` over ``mesh`` with ``spec`` and put every
        shard as a first-class shm object carrying placement metadata.
        Returns a :class:`~ray_tpu._private.distributed_array
        .DistributedArray`; the shard set is registered as ONE lineage
        unit (ReferenceCounter.add_shard_group) — dropping the handle
        frees every shard segment together or not at all."""
        return self._run(self._put_sharded_async(array, mesh, spec))

    async def _put_sharded_async(self, array, mesh, spec):
        import numpy as np

        from ray_tpu._private import distributed_array as da

        arr = np.ascontiguousarray(array)
        if arr.dtype == object:
            raise TypeError("object-dtype arrays cannot be sharded")
        da._validate(arr.shape, mesh, spec)
        shards: List[da.ShardInfo] = []
        for rank in range(mesh.nranks):
            box = da._rank_box(arr.shape, mesh, spec, rank)
            shard = np.ascontiguousarray(
                arr[tuple(slice(a, b) for a, b in box)])
            serialized = self.serialization_context.serialize(shard)
            _hdr, raw_frames, offsets, _total = plan_segment(serialized)
            if len(raw_frames) != 2:
                raise TypeError(
                    "sharded put requires the 2-frame ndarray wire "
                    f"shape, got {len(raw_frames)} frames")
            oid = self._next_put_id()
            attrs = {"rank": rank, "coords": list(mesh.coords(rank)),
                     "mesh": list(mesh.shape),
                     "array_shape": list(arr.shape)}
            node_id = await self._put_shard_async(oid, serialized, attrs)
            shards.append(da.ShardInfo(
                ref=ObjectRef(oid, owner_address=self.address,
                              worker=self, call_site="put_sharded"),
                rank=rank, node_id=node_id, data_offset=offsets[1],
                nbytes=raw_frames[1].nbytes, shape=shard.shape))
        self.reference_counter.add_shard_group(
            [s.ref.object_id for s in shards])
        return da.DistributedArray(mesh, spec, arr.shape, str(arr.dtype),
                                   shards)

    async def _put_shard_async(self, oid: ObjectID,
                               serialized: SerializedObject,
                               shard_attrs: dict) -> bytes:
        """Always-plasma put for one shard: shard-group lineage and the
        GatherShards collectives need a real segment even when the
        shard is small enough for the in-process store. ``shard_attrs``
        ride the SealObject frame into the SEALED object-plane record
        (state.list_objects() placement surface)."""
        self.reference_counter.add_owned_object(oid)
        segment, size = await self._write_segment_async(serialized)
        reply, _ = await self.raylet_conn.call(
            "SealObject", protocol.SealObjectRequest(
                object_id=oid.binary(), segment=segment, size=size,
                pin=True, owner_address=self.address,
                shard=shard_attrs).to_header())
        if not reply.get("ok"):
            raise exc.ObjectStoreFullError(
                f"shard {oid.hex()} ({size} bytes) does not fit in the "
                f"store")
        self.reference_counter.add_location(oid, reply["node_id"], size)
        self.memory_store.put(oid, IN_PLASMA)
        return reply["node_id"]

    def get_shard(self, darr, rank: int):
        """Fetch one shard's value (zero-copy attach when local)."""
        return self.get([darr.shards[rank].ref])[0]

    def assemble(self, darr):
        """Materialize the full array driver-side by pasting every
        shard into place (pulls remote shards through the normal
        striped pull path)."""
        return self._run(self._assemble_async(darr))

    async def _assemble_async(self, darr):
        import numpy as np

        from ray_tpu._private import distributed_array as da

        out = np.empty(darr.shape, dtype=np.dtype(darr.dtype_str))
        slices = da.shard_slices(darr.shape, darr.mesh, darr.spec)
        for shard in darr.shards:
            val = await self._get_one(shard.ref, None)
            out[slices[shard.rank]] = val
        return out

    def reshard(self, darr, mesh_dst, spec_dst):
        """Re-partition a DistributedArray onto a new mesh/spec. Every
        destination shard is built by ONE GatherShards collective whose
        bulk bytes ride the striped data plane straight into the
        destination segment (zero intermediate copies); on any typed
        collective failure the slice falls back to the naive
        get+assemble+put path (fallback matrix in the README)."""
        return self._run(self._reshard_async(darr, mesh_dst, spec_dst))

    async def _reshard_async(self, darr, mesh_dst, spec_dst):
        import numpy as np

        from ray_tpu._private import distributed_array as da

        dtype = np.dtype(darr.dtype_str)
        da._validate(darr.shape, mesh_dst, spec_dst)
        plan = da.gather_plan(darr.shape, dtype.itemsize, darr.mesh,
                              darr.spec, mesh_dst, spec_dst)
        shards: List[da.ShardInfo] = []
        for dst_rank in range(mesh_dst.nranks):
            shape = da.shard_shape(darr.shape, mesh_dst, spec_dst,
                                   dst_rank)
            attrs = {"rank": dst_rank,
                     "coords": list(mesh_dst.coords(dst_rank)),
                     "mesh": list(mesh_dst.shape),
                     "array_shape": list(darr.shape)}
            sources = [{
                "oid": darr.shards[src_rank].ref.object_id.binary(),
                "node_id": darr.shards[src_rank].node_id,
                "data_offset": darr.shards[src_rank].data_offset,
                "runs": runs,
            } for src_rank, runs in plan[dst_rank]]
            info = await self._gather_shard(shape, dtype, attrs, sources)
            if info is None:
                # fallback matrix: any dest slice the collective can't
                # build routes the WHOLE reshard through the naive path
                # (the already-built slices' refs drop with this list —
                # no group was registered yet, so they free normally)
                del shards
                arr = await self._assemble_async(darr)
                return await self._put_sharded_async(arr, mesh_dst,
                                                     spec_dst)
            shards.append(da.ShardInfo(
                ref=info[0], rank=dst_rank, node_id=info[1],
                data_offset=info[2], nbytes=info[3], shape=shape))
        self.reference_counter.add_shard_group(
            [s.ref.object_id for s in shards])
        return da.DistributedArray(mesh_dst, spec_dst, darr.shape,
                                   darr.dtype_str, shards)

    async def _gather_shard(self, shape, dtype, attrs: dict,
                            sources: List[dict], reduce_spec=None):
        """Ask the local raylet to build one destination shard via
        GatherShards. Returns (ref, node_id, data_offset, nbytes) or
        None on a typed collective failure (caller falls back)."""
        import numpy as np

        from ray_tpu._private import distributed_array as da

        # zeros template: np.zeros never touches the calloc'd pages, so
        # this payload is byte-identical to the real shard's regardless
        # of content — the destination raylet lays the segment out from
        # (meta, payload, data_nbytes) alone
        template = np.zeros(shape, dtype=dtype)
        serialized = self.serialization_context.serialize(template)
        _hdr, raw_frames, offsets, total = plan_segment(serialized)
        if len(raw_frames) != 2:
            return None
        oid = self._next_put_id()
        try:
            reply, _ = await self.raylet_conn.call(
                "GatherShards",
                protocol.GatherShardsRequest(
                    object_id=oid.binary(),
                    meta=serialized.metadata,
                    payload=bytes(raw_frames[0]),
                    data_nbytes=raw_frames[1].nbytes,
                    owner_address=self.address,
                    shard=attrs, sources=sources,
                    reduce=reduce_spec).to_header())
        except ConnectionError:
            reply = {"ok": False, "reason": "raylet unreachable"}
        if not reply.get("ok"):
            # nothing sealed, nothing registered: the minted id simply
            # goes unused and the caller takes the fallback path
            logger.warning("GatherShards for %s failed (%s); falling "
                           "back to naive path", oid.hex()[:16],
                           reply.get("reason"))
            return None
        self.reference_counter.add_owned_object(oid)
        self.reference_counter.add_location(oid, reply["node_id"], total)
        self.memory_store.put(oid, IN_PLASMA)
        ref = ObjectRef(oid, owner_address=self.address, worker=self,
                        call_site="reshard")
        return ref, reply["node_id"], offsets[1], raw_frames[1].nbytes

    # ------------------------------------------------------ ring collectives
    #
    # Driver-orchestrated ring engine. The driver never moves array
    # bytes: it mints one member identity per rank, asks each shard's
    # raylet to stage a full-size accumulator (RingInit), then issues
    # one RingStep RPC per (rank, step) — P concurrent calls per round
    # with a barrier between rounds, so a rank only ever pulls a
    # segment its upstream peer finished in the previous round. Bulk
    # bytes move peer-to-peer over the striped data plane; per-rank
    # wire traffic is 2*(P-1)/P * N for all_reduce (the bandwidth
    # optimum) vs (P-1)*N for the fold path's single sink.

    def _ring_applicable(self, darr) -> bool:
        """Ring engages only when configured, with enough ranks for
        the ring to beat the fold sink (P >= 3), and with a data plane
        to carry the peer-to-peer segment traffic."""
        return (self.config.collective_algorithm == "ring"
                and darr.mesh.nranks >= 3
                and self.config.data_plane_stripes > 0)

    async def _collective_raylet_conn(self, node_id: bytes):
        """Control-plane connection to the raylet hosting one ring
        member (the local raylet for local shards; cached dials for
        remote peers)."""
        if not node_id or node_id == self.node_id:
            return self.raylet_conn
        addr = await self._node_address_of(node_id)
        if not addr:
            raise exc.CollectiveError(
                f"no raylet address for node {node_id.hex()[:12]}")
        if addr == self.raylet_address:
            return self.raylet_conn
        conn = self._ring_conns.get(addr)
        if conn is None or conn.closed:
            conn = await rpc.connect(
                addr, peer_name=f"ring-raylet@{addr}",
                timeout=self.config.rpc_connect_timeout_s)
            self._ring_conns[addr] = conn
        return conn

    async def _ring_abort(self, members, reason: str):
        """Best-effort RingAbort fan-out: every member's raylet drops
        its accumulator segment and serve entry. Idempotent on the
        raylet side, so members that never finished RingInit are fine."""
        async def _one(m):
            try:
                await m["conn"].call(
                    "RingAbort",
                    protocol.RingAbortRequest(
                        member_id=m["mid"],
                        reason=reason[:200]).to_header(),
                    timeout=5)
            except (ConnectionError, OSError, asyncio.TimeoutError):
                pass
        await asyncio.gather(*(_one(m) for m in members),
                             return_exceptions=True)

    async def _ring_collective(self, darr, segments, schedules, sources,
                               op, attrs: dict, call_site: str):
        """Run one ring collective to completion and return the result
        ObjectRef. ``segments`` is the [(seg_off, seg_len)] tiling of
        the result's data frame, ``schedules[rank]`` the per-rank step
        list from distributed_array.ring_*_schedule, ``sources[rank]``
        the GatherShards-style source dict each member seeds its
        accumulator from. Raises CollectiveError after aborting every
        member on any round failure."""
        import numpy as np

        from ray_tpu._private import faultpoints

        nranks = darr.mesh.nranks
        dtype = np.dtype(darr.dtype_str)
        # identical zeros template on every rank: all members share one
        # frame layout, so a peer's absolute segment offset equals our
        # own data_off + seg_off (the pull model depends on this)
        template = np.zeros(darr.shape, dtype=dtype)
        serialized = self.serialization_context.serialize(template)
        _hdr, raw_frames, offsets, total = plan_segment(serialized)
        if len(raw_frames) != 2:
            raise exc.CollectiveError(
                "template does not serialize to the 2-frame ndarray "
                "wire shape")
        data_nbytes = raw_frames[1].nbytes
        oid = self._next_put_id()
        members = []
        try:
            for rank in range(nranks):
                conn = await self._collective_raylet_conn(
                    darr.shards[rank].node_id)
                # member ids ride the put-id minter: 28 bytes, globally
                # unique, disjoint from any sealed object's id
                members.append({"mid": self._next_put_id().binary(),
                                "conn": conn, "data_address": ""})
        except ConnectionError as e:
            raise exc.CollectiveError(
                f"ring peer raylet unreachable: {e}") from e
        meta = serialized.metadata
        payload = bytes(raw_frames[0])
        try:
            inits = await asyncio.gather(*(
                m["conn"].call(
                    "RingInit",
                    protocol.RingInitRequest(
                        collective_id=oid.binary(),
                        member_id=m["mid"], rank=rank, nranks=nranks,
                        object_id=oid.binary(), meta=meta,
                        payload=payload, data_nbytes=data_nbytes,
                        source=sources[rank], dtype=darr.dtype_str,
                        op=op, owner_address=self.address,
                        shard=attrs).to_header())
                for rank, m in enumerate(members)),
                return_exceptions=True)
            for m, rep in zip(members, inits):
                if isinstance(rep, BaseException):
                    raise rep
                reply, _ = rep
                if not reply.get("ok"):
                    raise exc.CollectiveError(
                        f"RingInit failed: {reply.get('reason')}")
                m["data_address"] = reply.get("data_address") or ""
                if not m["data_address"]:
                    raise exc.CollectiveError(
                        "ring peer runs without a data plane")
            nsteps = len(schedules[0])
            for step in range(nsteps):
                if faultpoints.armed:
                    await faultpoints.async_fire(
                        "collective.ring_step", step=step,
                        nsteps=nsteps, collective=oid.hex())
                calls = []
                for rank, m in enumerate(members):
                    st = schedules[rank][step]
                    seg_off, seg_len = segments[st["seg"]]
                    peer = members[st["recv_peer"]]
                    calls.append(m["conn"].call(
                        "RingStep",
                        protocol.RingStepRequest(
                            member_id=m["mid"],
                            peer_member_id=peer["mid"],
                            peer_data_address=peer["data_address"],
                            seg_off=seg_off, seg_len=seg_len,
                            reduce=bool(st["reduce"]),
                            step=step).to_header()))
                replies = await asyncio.gather(*calls,
                                               return_exceptions=True)
                for rep in replies:
                    if isinstance(rep, BaseException):
                        raise rep
                    reply, _ = rep
                    if not reply.get("ok"):
                        raise exc.CollectiveError(
                            f"ring step {step} failed: "
                            f"{reply.get('reason')}")
            fins = await asyncio.gather(*(
                m["conn"].call(
                    "RingFinish",
                    protocol.RingFinishRequest(
                        member_id=m["mid"]).to_header())
                for m in members), return_exceptions=True)
            node_ids = []
            for rep in fins:
                if isinstance(rep, BaseException):
                    raise rep
                reply, _ = rep
                if not reply.get("ok"):
                    raise exc.CollectiveError(
                        f"RingFinish failed: {reply.get('reason')}")
                node_ids.append(reply["node_id"])
        except BaseException as e:
            # abort EVERY member (not just survivors): RingAbort is
            # idempotent and this is the only thing standing between a
            # failed round and P leaked full-size segments
            await self._ring_abort(members, str(e) or type(e).__name__)
            if isinstance(e, (exc.CollectiveError,
                              asyncio.CancelledError)):
                raise
            raise exc.CollectiveError(
                f"ring collective {oid.hex()[:16]} failed: {e!r}") from e
        self.reference_counter.add_owned_object(oid)
        for nid in set(node_ids):
            self.reference_counter.add_location(oid, nid, total)
        self.memory_store.put(oid, IN_PLASMA)
        return ObjectRef(oid, owner_address=self.address, worker=self,
                         call_site=call_site)

    def _ring_gather_layout(self, darr, contribs, data_nbytes: int):
        """(segments, sources) for a ring all-gather, or None when the
        source layout is not a rank-ordered contiguous tiling of the
        destination (rank r's ring segment must be exactly its own
        shard's bytes, laid out in rank order — true for every 1-D
        sharding and for row-major leading-axis shardings; anything
        else takes the fold path)."""
        if len(contribs) != darr.mesh.nranks:
            return None
        segments, sources = [], []
        expect = 0
        for idx, (src_rank, runs) in enumerate(contribs):
            if src_rank != idx or len(runs) != 1:
                return None
            s_off, d_off, length = runs[0]
            if (s_off != 0 or d_off != expect
                    or length != darr.shards[src_rank].nbytes):
                return None
            segments.append((d_off, length))
            sources.append({
                "oid": darr.shards[src_rank].ref.object_id.binary(),
                "node_id": darr.shards[src_rank].node_id,
                "data_offset": darr.shards[src_rank].data_offset,
                "runs": [[0, d_off, length]],
            })
            expect += length
        if expect != data_nbytes:
            return None
        return segments, sources

    def all_gather(self, darr) -> ObjectRef:
        """Materialize the FULL array as one new object via a single
        GatherShards collective (striped data plane); returns its ref.
        Falls back to assemble+put when the collective fails."""
        return self._run(self._all_gather_async(darr))

    async def _all_gather_async(self, darr):
        import numpy as np

        from ray_tpu._private import distributed_array as da

        dtype = np.dtype(darr.dtype_str)
        mesh1 = da.Mesh((1,), ("gather",))
        plan = da.gather_plan(darr.shape, dtype.itemsize, darr.mesh,
                              darr.spec, mesh1, da.PartitionSpec())
        if self._ring_applicable(darr):
            nbytes = (int(np.prod(darr.shape, dtype=np.int64))
                      * dtype.itemsize)
            layout = self._ring_gather_layout(darr, plan[0], nbytes)
            if layout is not None:
                segments, ring_sources = layout
                schedules = [
                    da.ring_gather_schedule(r, darr.mesh.nranks)
                    for r in range(darr.mesh.nranks)]
                try:
                    return await self._ring_collective(
                        darr, segments, schedules, ring_sources, None,
                        {"gather": True, "ring": True}, "all_gather")
                except exc.CollectiveError as e:
                    logger.warning(
                        "ring all_gather failed (%s); falling back to "
                        "the fold path", e)
        sources = [{
            "oid": darr.shards[src_rank].ref.object_id.binary(),
            "node_id": darr.shards[src_rank].node_id,
            "data_offset": darr.shards[src_rank].data_offset,
            "runs": runs,
        } for src_rank, runs in plan[0]]
        info = await self._gather_shard(
            darr.shape, dtype, {"gather": True}, sources)
        if info is None:
            arr = await self._assemble_async(darr)
            oid = self._next_put_id()
            await self._put_serialized(
                oid, self.serialization_context.serialize(arr))
            return ObjectRef(oid, owner_address=self.address,
                             worker=self, call_site="all_gather")
        return info[0]

    def all_reduce(self, darr, op: str = "sum") -> ObjectRef:
        """Fold every rank's full-shape partial into one summed array
        (each shard must be the full global shape — a replicated spec).
        The destination raylet streams the first partial straight into
        the result segment and folds the rest through one reused
        scratch buffer; returns the result's ref. Falls back to
        get-all + np.sum when the collective fails."""
        return self._run(self._all_reduce_async(darr, op))

    async def _all_reduce_async(self, darr, op: str):
        import numpy as np

        from ray_tpu._private import distributed_array as da

        # typed rejection BEFORE any bytes move: both fold tiers and the
        # native kernel only know these ops, and reducing non-numeric
        # dtypes (strings, objects) is meaningless on raw frames
        if op not in ("sum", "min", "max"):
            raise ValueError(
                f"all_reduce op must be 'sum', 'min' or 'max', got "
                f"{op!r}")
        dtype = np.dtype(darr.dtype_str)
        if dtype.kind not in "fiu":
            raise TypeError(
                "all_reduce supports float/int/uint dtypes only, got "
                f"{darr.dtype_str}")
        nbytes = int(np.prod(darr.shape, dtype=np.int64)) * dtype.itemsize
        for s in darr.shards:
            if tuple(s.shape) != tuple(darr.shape):
                raise ValueError(
                    "all_reduce needs full-shape partials on every rank "
                    f"(rank {s.rank} holds {s.shape}, global is "
                    f"{darr.shape})")
        sources = [{
            "oid": s.ref.object_id.binary(), "node_id": s.node_id,
            "data_offset": s.data_offset,
            "runs": [[0, 0, nbytes]],
        } for s in darr.shards]
        if self._ring_applicable(darr):
            segments = da.ring_segments(nbytes, dtype.itemsize,
                                        darr.mesh.nranks)
            schedules = [da.ring_reduce_schedule(r, darr.mesh.nranks)
                         for r in range(darr.mesh.nranks)]
            try:
                return await self._ring_collective(
                    darr, segments, schedules, sources, op,
                    {"reduce": op, "ring": True}, "all_reduce")
            except exc.CollectiveError as e:
                logger.warning(
                    "ring all_reduce failed (%s); falling back to the "
                    "fold path", e)
        info = await self._gather_shard(
            darr.shape, dtype, {"reduce": op}, sources,
            reduce_spec={"op": op, "dtype": darr.dtype_str})
        if info is not None:
            return info[0]
        vals = [await self._get_one(s.ref, None) for s in darr.shards]
        out = vals[0].copy()
        ufunc = {"sum": np.add, "min": np.minimum,
                 "max": np.maximum}[op]
        for v in vals[1:]:
            ufunc(out, v, out)
        oid = self._next_put_id()
        await self._put_serialized(
            oid, self.serialization_context.serialize(out))
        return ObjectRef(oid, owner_address=self.address, worker=self,
                         call_site="all_reduce")

    # -------------------------------------------------------- runtime envs

    def set_job_runtime_env(self, runtime_env: Optional[Dict]) -> None:
        """Job-level default env (ray.init(runtime_env=...)): uploaded
        once, merged under every task/actor env (reference: JobConfig
        runtime_env, python/ray/job_config.py). Persisted in the GCS KV
        so WORKERS of this job merge it into their nested submissions
        too (the reference ships JobConfig inside the job table)."""
        from ray_tpu._private import runtime_env as runtime_env_mod

        self.job_runtime_env = self._prepare_runtime_env(runtime_env)
        if self.job_runtime_env and self.job_id:
            import json as _json
            self._kv_put_sync(
                runtime_env_mod.JOB_ENV_KEY_PREFIX + self.job_id,
                _json.dumps(self.job_runtime_env).encode())

    def adopt_job_runtime_env(self, job_id: bytes) -> None:
        """Worker side: when adopting a job, pick up its job-level
        runtime env so nested task/actor submissions inherit it."""
        from ray_tpu._private import runtime_env as runtime_env_mod

        if self.job_runtime_env is not None or not job_id:
            return
        try:
            raw = self._kv_get_sync(
                runtime_env_mod.JOB_ENV_KEY_PREFIX + job_id)
        except Exception:  # noqa: BLE001 — GCS restarting; best effort
            return
        import json as _json
        # {} on miss: caches "no job env" so this is one KV read per
        # worker, not one per task.
        self.job_runtime_env = _json.loads(raw) if raw else {}

    def _prepare_runtime_env(self, runtime_env: Optional[Dict]):
        if not runtime_env:
            return runtime_env
        from ray_tpu._private import runtime_env as runtime_env_mod
        return runtime_env_mod.prepare_runtime_env(
            runtime_env, self._kv_get_sync, self._kv_put_sync,
            self._uploaded_working_dirs)

    def _resolve_runtime_env(self, runtime_env: Optional[Dict]):
        """Prepare (validate/upload) a per-task env and merge the job
        default under it. Task env_vars overlay the job's; a task-level
        working_dir wins over the job's."""
        prepared = self._prepare_runtime_env(runtime_env)
        job = self.job_runtime_env
        if not job:
            return prepared
        if not prepared:
            return dict(job)
        merged = dict(job)
        merged.update({k: v for k, v in prepared.items()
                       if k != "env_vars"})
        env_vars = dict(job.get("env_vars") or {})
        env_vars.update(prepared.get("env_vars") or {})
        if env_vars:
            merged["env_vars"] = env_vars
        return merged

    # ------------------------------------------------------- task submission

    def submit_task(self, fn_key: str, name: str, args: List[Any],
                    num_returns: int = 1, resources: Dict[str, float] | None = None,
                    max_retries: int | None = None,
                    retry_exceptions: bool = False,
                    placement_group_id: bytes = b"",
                    placement_group_bundle_index: int = -1,
                    scheduling_strategy: str = "DEFAULT",
                    runtime_env: Dict | None = None) -> List[ObjectRef]:
        # Hot path: raw-bytes task id (lineage prefix + random suffix)
        # instead of TaskID/ActorID wrapper churn — ~4 object
        # constructions per submit otherwise.
        if self.mode == "driver":
            prefix = self._task_lineage_prefix
        else:
            prefix = (self._current_task_id or
                      self._driver_task_id.binary())[:ACTOR_ID_SIZE]
        task_id_b = make_task_id_bytes(prefix)
        prepared_args, arg_holds = self._prepare_args(args) \
            if args else ((), None)
        spec = TaskSpec(
            task_id=task_id_b, job_id=self.job_id,
            task_type=TASK_NORMAL, name=name, fn_key=fn_key,
            args=prepared_args,
            num_returns=num_returns,
            resources=resources or {"CPU": 1.0},
            max_retries=self.config.task_max_retries_default
            if max_retries is None else max_retries,
            retry_exceptions=retry_exceptions,
            owner_address=self.address, owner_worker_id=self.worker_id,
            placement_group_id=placement_group_id,
            placement_group_bundle_index=placement_group_bundle_index,
            scheduling_strategy=scheduling_strategy,
            runtime_env=self._resolve_runtime_env(runtime_env),
            trace_ctx=_trace_ctx())
        return self._register_and_submit(spec, arg_holds)

    def make_task_template(self, fn_key: str, name: str,
                           num_returns: int = 1,
                           resources: Dict[str, float] | None = None,
                           max_retries: int | None = None,
                           retry_exceptions: bool = False,
                           placement_group_id: bytes = b"",
                           placement_group_bundle_index: int = -1,
                           scheduling_strategy: str = "DEFAULT",
                           runtime_env: Dict | None = None) -> TaskSpec:
        """Prototype TaskSpec for repeated submissions of the same
        remote function: runtime env resolved and scheduling class
        interned ONCE, per-call work reduced to id generation + arg
        prep + a slot-copy clone (see TaskSpec.clone_for)."""
        proto = TaskSpec(
            task_id=b"", job_id=self.job_id,
            task_type=TASK_NORMAL, name=name, fn_key=fn_key, args=[],
            num_returns=num_returns,
            resources=resources or {"CPU": 1.0},
            max_retries=self.config.task_max_retries_default
            if max_retries is None else max_retries,
            retry_exceptions=retry_exceptions,
            owner_address=self.address, owner_worker_id=self.worker_id,
            placement_group_id=placement_group_id,
            placement_group_bundle_index=placement_group_bundle_index,
            scheduling_strategy=scheduling_strategy,
            runtime_env=self._resolve_runtime_env(runtime_env))
        proto.scheduling_class  # intern now, off the per-call path
        return proto

    def submit_task_from_template(self, proto: TaskSpec,
                                  args: List[Any]) -> List[ObjectRef]:
        if self.mode == "driver":
            prefix = self._task_lineage_prefix
        else:
            prefix = (self._current_task_id or
                      self._driver_task_id.binary())[:ACTOR_ID_SIZE]
        if not args and proto.num_returns == 1:
            # The dominant microbenchmark shape (arg-less, one return):
            # one C call fuses mint + clone + refcount + ObjectRef +
            # pending entry + queue append (cpp/fastpath.c).
            ctx = self._fast_ctx
            if ctx is None and not self._fast_ctx_failed:
                ctx = self._make_fast_ctx()
            if ctx is not None:
                # SUBMITTED is recorded loop-side by
                # _drain_submit_buffer (the C path enqueues the cloned
                # spec there like every other submission): the caller
                # thread pays nothing for recording.
                return ctx.submit(proto, prefix, _trace_ctx())
            prepared_args, arg_holds = (), None
        elif args:
            prepared_args, arg_holds = self._prepare_args(args)
        else:
            prepared_args, arg_holds = (), None
        spec = proto.clone_for(make_task_id_bytes(prefix), prepared_args,
                               trace_ctx=_trace_ctx())
        return self._register_and_submit(spec, arg_holds)

    def _make_fast_ctx(self):
        """Bind a native fused-submit context to this worker (or mark
        the attempt failed and stay on the pure-Python path forever)."""
        try:
            from ray_tpu._private.native import load_fastpath

            mod = load_fastpath()
            if mod is None or not self.address:
                raise RuntimeError("native module or address unavailable")
            self._fast_ctx = mod.Ctx(
                worker=self,
                refs_dict=self.reference_counter._refs,
                pending_dict=self.pending_tasks,
                submit_buffer=self._submit_buffer,
                stats_dict=self.stats,
                own_address=self.address,
                call_soon_threadsafe=self.loop.call_soon_threadsafe,
                drain_fn=self._drain_submit_buffer,
                taskspec_cls=TaskSpec,
                objectid_cls=ObjectID,
                objectref_cls=ObjectRef,
                reference_cls=Reference,
                entry_cls=PendingTaskEntry,
                serialized_cls=SerializedObject,
                seed=os.urandom(16),
            )
            return self._fast_ctx
        except Exception as e:  # noqa: BLE001 — perf tier, never correctness
            logger.debug("fast submit path unavailable: %s", e)
            self._fast_ctx_failed = True
            return None

    def _register_and_submit(self, spec: TaskSpec,
                             arg_holds: Optional[List[ObjectRef]] = None
                             ) -> List[ObjectRef]:
        refs = self._register_task(spec, arg_holds)
        # SUBMITTED recorded loop-side by _drain_submit_buffer
        self._enqueue_submit("task", spec)
        return refs

    def _register_task(self, spec: TaskSpec,
                       arg_holds: Optional[List[ObjectRef]] = None
                       ) -> List[ObjectRef]:
        tid_b = spec.task_id
        if spec.num_returns == 1:
            # Hot path (the reference's microbenchmarks are all
            # single-return): no list comprehension frames.
            oid = ObjectID(return_object_id_bytes(tid_b, 1))
            self.reference_counter.add_owned_with_local_ref(
                oid, pin_lineage=True)
            refs = [ObjectRef(oid, owner_address=self.address, worker=self,
                              call_site=spec.name,
                              skip_adding_local_ref=True)]
            return_ids = [oid]
        else:
            return_ids = [
                ObjectID(return_object_id_bytes(tid_b, i + 1))
                for i in range(spec.num_returns)]
            refs = []
            for oid in return_ids:
                self.reference_counter.add_owned_with_local_ref(
                    oid, pin_lineage=True)
                refs.append(ObjectRef(oid, owner_address=self.address,
                                      worker=self, call_site=spec.name,
                                      skip_adding_local_ref=True))
        entry = PendingTaskEntry(spec, return_ids)
        self.pending_tasks[tid_b] = entry
        if entry.dep_ids:
            self.reference_counter.update_submitted_task_references(
                entry.dep_ids)
        del arg_holds  # promoted args now pinned by submitted-ref counts
        self.stats["tasks_submitted"] += 1
        return refs

    def queue_local_decref(self, object_id: ObjectID):
        """Deferred remove_local_reference (called from ObjectRef.__del__,
        any thread): batch the lock + release side effects onto the loop."""
        self._decref_buffer.append(object_id)
        if not self._decref_scheduled:
            self._decref_scheduled = True
            try:
                self.loop.call_soon_threadsafe(self._drain_decrefs)
            except RuntimeError:  # loop closed: shutting down
                self._decref_scheduled = False

    def _drain_decrefs(self):
        self._decref_scheduled = False
        buf = self._decref_buffer
        remove = self.reference_counter.remove_local_reference
        # Chunked: dropping a 1M-ref list must not freeze the IO loop
        # for the whole backlog — yield after a slice and reschedule.
        for _ in range(20000):
            try:
                oid = buf.popleft()
            except IndexError:
                return
            remove(oid)
        if buf and not self._decref_scheduled:
            self._decref_scheduled = True
            self.loop.call_soon(self._drain_decrefs)

    def _enqueue_submit(self, kind: str, spec: TaskSpec):
        """Queue a spec for submission and wake the IO loop at most once
        per burst (reference analog: the submitter queue pump in
        direct_task_transport.cc, but batched for the caller thread).
        Lock-free: deque.append is GIL-atomic, and the drain clears the
        scheduled flag BEFORE popping, so the worst interleaving is one
        spurious extra wakeup — never a stranded spec."""
        self._submit_buffer.append((kind, spec))
        if not self._submit_scheduled:
            self._submit_scheduled = True
            self.loop.call_soon_threadsafe(self._drain_submit_buffer)

    def _drain_submit_buffer(self):
        """Loop thread: move buffered submissions into per-key / per-actor
        queues, then pump each touched queue once."""
        self._submit_scheduled = False
        items = []
        buf = self._submit_buffer
        while True:
            try:
                items.append(buf.popleft())
            except IndexError:
                break
        ev = self.task_events
        # SUBMITTED stamps for the whole burst, grouped by task name
        # (one record_many per distinct template): the caller thread
        # pays nothing, and the grouping is FUSED into the routing loop
        # below — one pass over the burst, not a separate stamping pass
        # (bench.py task_events_overhead pins the submit-path cost).
        recording = bool(ev.enabled and items)
        # Stamp ts taken BEFORE the loop: PENDING_ARGS records fired
        # mid-loop must sort after their task's SUBMITTED event.
        now = time.time() if recording else 0.0
        by_name: Dict[str, list] = {}
        touched_keys: Dict[int, SchedulingKeyState] = {}
        touched_actors: Dict[bytes, ActorQueueState] = {}
        for kind, spec in items:
            if recording:
                tids = by_name.get(spec.name)
                if tids is None:
                    tids = by_name[spec.name] = []
                tids.append(spec.task_id)
            if kind == "task":
                # args check first: the dominant argless submit skips
                # the dependency_ids() call entirely
                if spec.args and spec.dependency_ids():
                    # Owned args may be pending: resolve asynchronously.
                    if self.task_events.enabled:
                        self.task_events.record(spec.task_id, PENDING_ARGS)
                    rpc.spawn_logged(self._submit_when_ready(spec),
                                     "worker-submit-when-ready",
                                     loop=self.loop)
                    continue
                sc = spec._sched  # interned at template creation
                if sc < 0:
                    sc = spec.scheduling_class
                state = self.scheduling_keys.get(sc)
                if state is None:
                    state = self.scheduling_keys[sc] = \
                        SchedulingKeyState(spec.resources)
                state.queue.append(spec)
                touched_keys[sc] = state
            else:
                q = self.actor_queues.get(spec.actor_id)
                if q is None:
                    q = self.actor_queues[spec.actor_id] = \
                        ActorQueueState(spec.actor_id)
                if q.state == "DEAD":
                    self._store_error_for_task(
                        spec, exc.ActorDiedError(
                            q.death_cause or "actor is dead",
                            cause=q.death_info))
                    continue
                # Seqnos assigned in buffer order == submission order (the
                # receiver executes strictly by seqno per caller).
                seqno = q.seqno
                q.seqno += 1
                q.buffer.append((spec, seqno))
                touched_actors[spec.actor_id] = q
        if by_name:
            for tname, tids in by_name.items():
                ev.record_many(tids, SUBMITTED, tname, ts=now)
        for sc, state in touched_keys.items():
            self._pump_scheduling_key(sc, state)
        for q in touched_actors.values():
            self._pump_actor_queue(q)

    def _prepare_args(self, args: List[Any]):
        """Inline small values; pass ObjectRefs and big values by reference
        (reference: prepare_args in _raylet.pyx — the
        max_direct_call_object_size threshold). Returns (task_args, holds):
        ``holds`` keeps promoted-arg ObjectRefs alive until the caller has
        registered submitted-task references for them."""
        holds: List[ObjectRef] = []
        out: List[TaskArg] = []
        for a in args:
            if isinstance(a, ObjectRef):
                owner = a.owner_address or \
                    self.reference_counter.owner_address_of(a.object_id) or \
                    self.address
                out.append(TaskArg(ARG_REF, object_id=a.object_id.binary(),
                                   owner_address=owner))
                continue
            serialized = self.serialization_context.serialize(a)
            if serialized.total_bytes() <= self.config.max_direct_call_object_size \
                    and not serialized.contained_refs:
                meta, frames = serialized.to_wire()
                out.append(TaskArg(ARG_VALUE, metadata=meta, frames=frames))
            else:
                # Too big (or carries refs needing ownership tracking):
                # promote to a put + by-reference arg.
                ref = self.put(a)
                out.append(TaskArg(ARG_REF, object_id=ref.object_id.binary(),
                                   owner_address=self.address))
                holds.append(ref)
        return out, holds

    async def _submit_when_ready(self, spec: TaskSpec):
        """Local dependency resolution (reference: LocalDependencyResolver):
        wait until every owned arg is available before asking for a lease;
        borrowed args resolve at the executing worker."""
        for dep in spec.dependency_ids():
            oid = ObjectID(dep)
            if self.reference_counter.is_owned(oid):
                try:
                    await self.memory_store.get(oid)
                # raylint: disable=exception-hygiene — errored deps surface at the executing worker
                except Exception:
                    pass
        self._queue_spec(spec)

    def _queue_spec(self, spec: TaskSpec):
        """Loop thread: queue a dependency-free spec and pump."""
        sc = spec.scheduling_class
        state = self.scheduling_keys.get(sc)
        if state is None:
            state = self.scheduling_keys[sc] = SchedulingKeyState(spec.resources)
        state.queue.append(spec)
        self._pump_scheduling_key(sc, state)

    def _pump_scheduling_key(self, sc: int, state: SchedulingKeyState):
        """Breadth-first lease acquisition, depth only when breadth is
        exhausted: leases are requested in proportion to the queue (one
        per ~8 queued tasks, bounded), and each worker's batch is sized
        to an even split across the workers we have or expect — NOT to
        the full pipeline cap. The cap (deep, for wire batching) only
        bites when the cluster can't give us more workers, so a 100-task
        job on an 8-CPU node parallelizes instead of serializing into
        one 512-deep pipeline (reference: per-scheduling-key lease
        requests bounded by backlog, direct_task_transport.h)."""
        cap = self.config.max_tasks_in_flight_per_worker
        max_pending = self.config.max_pending_leases_per_scheduling_class
        credits_on = self.config.lease_credits_enabled
        stale_s = self.config.lease_credit_stale_s
        while state.queue:
            qlen = len(state.queue)
            # target worker count for this backlog (breadth first)
            want = min(max(1, qlen // 8), max_pending)
            floor = 0
            if credits_on:
                # Streaming leases. Until the raylet announces a window
                # (credit_target < 0), probe with ONE legacy request —
                # it carries the backlog that opens the window and
                # keeps locality-aware targeting intact. After that:
                #   * breadth is clamped to the raylet's cluster-wide
                #     slot bound — parking legacy requests beyond real
                #     capacity WAS the 200-700ms grant_wait tail;
                #   * the first min(want, window_target) slots are
                #     RESERVED for the credit stream while it is live
                #     (credits activating, workers held, or a grant
                #     within the stale period) — the stream fills them
                #     with zero request/grant round-trips;
                #   * legacy requests fire only for the remainder
                #     (remote capacity, reached through the existing
                #     park-and-spill machinery) or when the stream has
                #     gone silent (raylet restarted, pressure zeroed
                #     the window, grant push lost) — the fallback lane.
                tgt = state.credit_target
                if tgt < 0:
                    want = min(want, 1)
                else:
                    want = min(want, max(1, state.cluster_slots))
                    stream_live = state.activating > 0 or \
                        bool(state.workers) or \
                        time.monotonic() - state.last_grant_ts < stale_s
                    if stream_live:
                        floor = min(want, tgt)
                now = time.monotonic()
                expected0 = len(state.workers) + state.activating
                if tgt >= 0 and \
                        self.raylet_conn is not None and \
                        not self.raylet_conn.closed and \
                        (now - state.last_demand_ts > stale_s / 2 or
                         (expected0 == 0 and
                          now - state.last_demand_ts > 0.01)):
                    # paced backlog refresh (kept off the per-task
                    # path): renews the window mid-drain, and a
                    # zero-worker burst start kicks it immediately so
                    # the stream restarts without waiting out the pace
                    state.last_demand_ts = now
                    head = state.queue[0]
                    from ray_tpu._private import runtime_env as _re
                    try:
                        self.raylet_conn.push_nowait(
                            "ReportLeaseDemand",
                            protocol.ReportLeaseDemandRequest(
                                sched_class=sc, backlog=qlen,
                                resources=state.resources,
                                # same env key the legacy summary
                                # carries: a window (re)created from
                                # this push must keep the warm-pool
                                # runtime-env affinity
                                env_hash=_re.hash_runtime_env(
                                    head.runtime_env),
                                retriable=head.max_retries != 0,
                            ).to_header())
                    except ConnectionError:
                        pass  # raylet gone; lease path handles retries
            while True:
                expected = len(state.workers) + state.pending_lease + \
                    state.activating
                if expected >= want or \
                        state.pending_lease >= want - floor:
                    # enough breadth, or the legacy band is full: only
                    # (want - floor) legacy requests may be in flight —
                    # the stream owns the floor, and a partially-filled
                    # stream must not block the remote-spill band
                    break
                state.pending_lease += 1
                rpc.spawn_logged(
                    self._request_lease(sc, state, self.raylet_address),
                    "worker-request-lease", loop=self.loop)
            worker = min((w for w in state.workers if w.inflight < cap),
                         key=lambda w: w.inflight, default=None)
            if worker is None:
                if state.pending_lease == 0 and state.activating == 0:
                    if floor:
                        # deferred to the stream with nothing in
                        # flight: guard against a silent stream (lost
                        # demand push / raylet restart) — re-pump after
                        # the stale period, by when stream_live has
                        # expired and the legacy fallback fires
                        if not self._shutdown:
                            self.loop.call_later(
                                stale_s, self._pump_scheduling_key,
                                sc, state)
                    else:
                        state.pending_lease += 1
                        rpc.spawn_logged(
                            self._request_lease(sc, state,
                                                self.raylet_address),
                            "worker-request-lease", loop=self.loop)
                return
            # Batch sizing: fair share over current+expected workers
            # while grants are ARRIVING (breadth phase); once they stop
            # — saturated node, or a single-worker box whose extra
            # lease requests just sit pending — deepen to the cap so
            # wire batches stay large (tail batches shrinking with the
            # fair share measured a ~20% throughput loss).
            growing = (state.pending_lease > 0 or state.activating > 0) \
                and time.monotonic() - state.last_grant_ts < 0.05
            if growing:
                share = qlen // max(
                    1, len(state.workers) + state.pending_lease +
                    state.activating)
                target = min(cap, max(8, share))
            else:
                target = cap
            if worker.inflight >= target:
                # growing: breadth pending, wait for grants;
                # otherwise: every worker at the cap, wait for replies
                return
            n = min(qlen, target - worker.inflight)
            batch = [state.queue.popleft() for _ in range(n)]
            worker.inflight += n
            if worker.idle_timer is not None:
                worker.idle_timer.cancel()
                worker.idle_timer = None
            self._push_task_batch_nowait(sc, state, worker, batch)

    def _dep_info(self, spec: TaskSpec) -> List[dict]:
        """Owner-side locality data per by-ref arg: size + known replica
        locations from the reference counter (reference: LocalityData fed
        into lease_policy.h)."""
        out = []
        for a in spec.args:
            if a.kind != ARG_REF:
                continue
            size, locations = self.reference_counter.location_info(
                ObjectID(a.object_id))
            out.append({"oid": a.object_id,
                        "owner": a.owner_address or self.address,
                        "size": size, "locations": locations})
        return out

    async def _node_address_of(self, node_id: bytes) -> str:
        """node_id -> raylet address via a cached GCS node table."""
        now = time.monotonic()
        if now - self._node_table_ts > 5.0:
            try:
                reply, _ = await self._gcs_call("GetAllNodeInfo", {})
            except (ConnectionError, asyncio.TimeoutError):
                return ""
            # Re-sample after the await: a concurrent refresher may
            # have landed a NEWER table during our RPC — overwriting it
            # with this (older) reply would roll the cache backwards.
            if self._node_table_ts <= now:
                self._node_table = {n["node_id"]: n["address"]
                                    for n in reply["nodes"] if n["alive"]}
                self._node_table_ts = now
        return self._node_table.get(node_id, "")

    async def _best_locality_raylet(self, dep_info: List[dict]) -> str:
        """Locality-aware lease targeting (reference: lease_policy.h
        LocalityAwareLeasePolicy::GetBestNodeForTask): request the lease
        from the node already holding the most argument bytes."""
        per_node: Dict[bytes, int] = {}
        for d in dep_info:
            for nid in d["locations"]:
                per_node[nid] = per_node.get(nid, 0) + d["size"]
        if not per_node:
            return ""
        best_node = max(per_node, key=per_node.get)
        if per_node[best_node] <= 0:
            return ""
        addr = await self._node_address_of(best_node)
        return addr if addr and addr != self.raylet_address else ""

    async def _request_lease(self, sc: int, state: SchedulingKeyState,
                             raylet_address: str, depth: int = 0):
        try:
            def _build_summary():
                sample = state.queue[0] if state.queue else None
                if sample is None:
                    return {
                        "task_id": b"", "scheduling_class": sc,
                        "resources": state.resources, "deps": [],
                        "strategy": "DEFAULT", "pg_id": b"",
                        "pg_bundle": -1, "runtime_env": None,
                        "depth": 0, "name": "", "retriable": False,
                        "backlog": 0}
                s = sample.lease_summary()
                s["dep_info"] = self._dep_info(sample)
                # streaming leases: the backlog opens/refreshes this
                # owner's credit window at the serving raylet
                s["backlog"] = len(state.queue)
                return s

            summary = _build_summary()
            dep_info = summary.get("dep_info")
            if dep_info and depth == 0 and \
                    raylet_address == self.raylet_address:
                target = await self._best_locality_raylet(dep_info)
                if target:
                    raylet_address = target
            if raylet_address == self.raylet_address:
                conn = self.raylet_conn
            else:
                conn = await self._get_owner_conn(raylet_address)
            bo = None
            while True:
                reply, _ = await conn.call(
                    "RequestWorkerLease",
                    protocol.RequestWorkerLeaseRequest(
                        summary=summary).to_header())
                if not reply.get("retry_later"):
                    break
                # Typed lease backpressure: the raylet is above its
                # memory threshold and admits no new work. Back off
                # with jitter and re-request while this scheduling
                # class still has backlog (pressure clears when the
                # watchdog frees memory or the work drains elsewhere);
                # once the queue empties, stop asking.
                if self._shutdown or not state.queue:
                    state.pending_lease -= 1
                    return
                if bo is None:
                    from ray_tpu._private import backoff as backoff_mod
                    bo = backoff_mod.from_config(self.config)
                await bo.sleep()
                # re-sample the CURRENT queue head: the task sampled
                # before the backoff may have completed (stolen,
                # cancelled) — its task-events and retriable flag must
                # not be stamped onto whatever runs next
                summary = _build_summary()
        except asyncio.CancelledError:
            # settle the ledger, but stay cancelled: swallowing here
            # made `task.cancel(); await task` report success with the
            # lease request half-done
            state.pending_lease -= 1
            raise
        except ConnectionError:
            state.pending_lease -= 1
            return
        if reply.get("granted"):
            try:
                wconn = await rpc.connect(reply["worker_address"],
                                          peer_name="leased-worker")
            except ConnectionError:
                state.pending_lease -= 1
                return
            lw = LeasedWorker(reply["worker_address"], reply["lease_id"],
                              reply["node_id"], wconn, raylet_address,
                              reply["worker_id"])
            state.workers.append(lw)
            state.pending_lease -= 1
            state.last_grant_ts = time.monotonic()
            lw.on_drop = \
                lambda c: self._on_leased_worker_died(sc, state, lw)
            wconn.on_disconnect.append(lw.on_drop)
            if state.queue:
                self._pump_scheduling_key(sc, state)
            elif not self._try_steal(sc, state):
                # Stale grant: the queue drained while this request was
                # pending at the raylet and no sibling has stealable
                # backlog. Hand the worker straight back — keeping it
                # starves other scheduling classes.
                state.workers.remove(lw)
                await self._return_lease(lw)
        elif reply.get("spill") and depth < 4:
            await self._request_lease(sc, state, reply["spill"], depth + 1)
        elif reply.get("spill"):
            # Spill chain exhausted — e.g. mutually memory-pressured
            # nodes bouncing the request between each other (each zeroes
            # only its OWN availability in the backpressure view). The
            # old silent drop left the queue stranded with
            # pending_lease=0 and nothing to re-pump it. Back off, then
            # start over from the HOME raylet: pressure clears and the
            # home node re-admits (or re-spills somewhere healthy).
            state.pending_lease -= 1
            if state.queue and not self._shutdown:
                from ray_tpu._private import backoff as backoff_mod
                await backoff_mod.from_config(self.config).sleep()
                if state.queue and not self._shutdown:
                    self._pump_scheduling_key(sc, state)
        elif reply.get("infeasible"):
            state.pending_lease -= 1
            self._fail_queued_tasks(state, exc.RaySystemError(
                f"task requires infeasible resources {state.resources}"))
        else:
            state.pending_lease -= 1

    def _schedule_idle_return(self, sc: int, state: SchedulingKeyState,
                              lw: "LeasedWorker") -> None:
        """Keep an idle leased worker warm for a grace period before
        returning it — a sync-loop caller (submit, get, repeat) reuses
        the lease instead of paying a raylet round trip per task. One
        cancellable timer per worker: re-arming replaces the old timer,
        and the pump cancels it when work lands, so a stale timer can
        never return a lease that went back into use."""
        if lw.gang is not None:
            # gang-pinned lease: rank identity must survive between
            # steps — only the gang's release/teardown path (or the
            # raylet's owner-liveness watch) ends it
            return

        def _maybe_return():
            lw.idle_timer = None
            if lw not in state.workers or lw.inflight > 0 or state.queue:
                return  # back in use
            state.workers.remove(lw)
            rpc.spawn_logged(self._return_lease(lw),
                             "worker-return-lease", loop=self.loop)

        if lw.idle_timer is not None:
            lw.idle_timer.cancel()
        lw.idle_timer = self.loop.call_later(
            self.config.idle_lease_keepalive_s, _maybe_return)

    def _try_steal(self, sc: int, state: SchedulingKeyState) -> bool:
        """Initiate work stealing when a worker sits idle while a
        sibling has a deep pipeline (reference:
        direct_task_transport.h:57 StealTasks). Returns True if a steal
        was started (the idle worker should be kept leased)."""
        if state.steal_pending or state.queue:
            return False
        victim = max((w for w in state.workers if w.inflight >= 2),
                     key=lambda w: w.inflight, default=None)
        if victim is None or not any(
                w is not victim and w.inflight == 0 for w in state.workers):
            return False
        state.steal_pending = True
        rpc.spawn_logged(self._steal_tasks(sc, state, victim),
                         "worker-steal-tasks", loop=self.loop)
        return True

    async def _steal_tasks(self, sc: int, state: SchedulingKeyState,
                           victim: LeasedWorker):
        try:
            reply, rbufs = await victim.conn.call(
                "StealTasks", {"max_n": victim.inflight - 1})
        except ConnectionError:
            reply, rbufs = {"tasks": []}, []
        finally:
            state.steal_pending = False
        protos = [TaskSpec.from_tail_wire(t) for t in reply.get("protos", ())]
        for pidx, task_id, args_wire, fstart, nframes, trace_ctx in \
                reply["tasks"]:
            spec = protos[pidx].clone_for(
                task_id,
                TaskSpec._args_from_wire(
                    args_wire, list(rbufs[fstart:fstart + nframes])),
                trace_ctx=tuple(trace_ctx) if trace_ctx else None)
            state.reassigned.setdefault(spec.task_id, []).append(
                victim.worker_id)
            state.queue.append(spec)
            self.stats["tasks_stolen"] += 1
        if state.queue:
            self._pump_scheduling_key(sc, state)
        # thieves the steal couldn't feed idle out through the normal
        # keepalive (an immediate return would defeat the warm lease)
        for w in [w for w in state.workers if w.inflight == 0]:
            if state.queue:
                break
            self._schedule_idle_return(sc, state, w)

    def _fail_queued_tasks(self, state: SchedulingKeyState, error: BaseException):
        for spec in state.queue:
            self._store_error_for_task(spec, error)
        state.queue.clear()

    def _on_leased_worker_died(self, sc, state, lw: LeasedWorker):
        if lw in state.workers:
            state.workers.remove(lw)
        self._fire_and_forget(self._return_lease(lw, worker_died=True))

    async def _return_lease(self, lw: LeasedWorker, worker_died: bool = False):
        try:
            if lw.raylet_address == self.raylet_address:
                conn = self.raylet_conn
            else:
                conn = await self._get_owner_conn(lw.raylet_address)
            await conn.call(
                "ReturnWorker",
                protocol.ReturnWorkerRequest(
                    lease_id=lw.lease_id,
                    worker_died=worker_died).to_header())
        except ConnectionError:
            pass
        if not lw.conn.closed:
            # deliberate return: unhook the death watch first so the
            # close doesn't fire a spurious worker-died ReturnWorker
            if lw.on_drop is not None and \
                    lw.on_drop in lw.conn.on_disconnect:
                lw.conn.on_disconnect.remove(lw.on_drop)
            await lw.conn.close()

    def _push_task_batch_nowait(self, sc: int, state: SchedulingKeyState,
                                lw: LeasedWorker, batch: List[TaskSpec]):
        """Loop thread: write ONE PushTasks frame carrying the whole batch
        and attach completion handling to the reply future — no per-task
        coroutine, no per-task syscall. Static spec fields ride once per
        distinct prototype (TaskSpec.tail_wire), not once per task."""
        ctx = self._fast_ctx
        if ctx is not None:
            # C wire assembly also hands back the task-id list so the
            # dispatch stamp below needs no Python per-spec loop
            tails, theaders, frames, tids = ctx.build_push(batch)
        else:
            tails_l: List[list] = []
            tail_idx: Dict[int, int] = {}
            theaders_l: List[list] = []
            frames_l: List[bytes] = []
            tids = []
            for spec in batch:
                proto = spec._proto or spec
                tids.append(spec.task_id)
                pidx = tail_idx.get(id(proto))
                if pidx is None:
                    pidx = tail_idx[id(proto)] = len(tails_l)
                    tails_l.append(proto.tail_wire())
                if not spec.args and spec.trace_ctx is None:
                    theaders_l.append([pidx, spec.task_id])  # compact
                    continue
                args_wire, afr = spec._args_wire()
                theaders_l.append([pidx, spec.task_id, args_wire,
                                   len(frames_l), len(afr), spec.trace_ctx])
                frames_l.extend(afr)
            tails, theaders, frames = tails_l, theaders_l, frames_l
        # owner-side credit hit-rate: per-task dispatch split between
        # streamed credits and legacy request/grant leases
        self.stats["credit_dispatches" if lw.via_credit
                   else "legacy_dispatches"] += len(batch)
        ev = self.task_events
        if ev.enabled:
            # CREDIT_DISPATCHED marks the hop that replaced the lease
            # round-trip — grant_wait stays honestly measured (a credit
            # hit is visible as such, never passed off as a zero-wait
            # legacy grant)
            ev.record_many(tids,
                           CREDIT_DISPATCHED if lw.via_credit
                           else DISPATCHED,
                           {"worker": lw.worker_id.hex()[:12]})
        try:
            fut = lw.conn.call_nowait("PushTasks",
                                      {"protos": tails, "tasks": theaders},
                                      bufs=frames)
        except ConnectionError:
            lw.inflight -= len(batch)
            for spec in batch:
                self._retry_or_fail_after_worker_death(spec, lw.worker_id)
            return
        fut.add_done_callback(
            lambda f: self._on_push_batch_done(f, sc, state, lw, batch))

    def _retry_or_fail_after_worker_death(self, spec: TaskSpec,
                                          via_worker_id: bytes = b""):
        state = self.scheduling_keys.get(spec.scheduling_class)
        if state is not None and \
                via_worker_id in state.reassigned.get(spec.task_id, ()):
            # the VICTIM of a steal died before its batch reply; the
            # task already runs elsewhere — only this worker's copy is
            # skipped (a thief's death still retries below)
            victims = state.reassigned[spec.task_id]
            victims.remove(via_worker_id)
            if not victims:
                del state.reassigned[spec.task_id]
            return
        entry = self.pending_tasks.get(spec.task_id)
        # OOM classification is only trusted close to the notify: the
        # SIGKILL follows the owner's ack within ~1s, so a much older
        # entry means the kill was aborted (re-grant guard) and THIS
        # death has some other cause.
        rec = self._oom_worker_kills.get(via_worker_id) \
            if via_worker_id else None
        oom_cause = rec[1] if rec is not None and \
            time.monotonic() - rec[0] < 5.0 else None
        if oom_cause is not None:
            self._retry_or_fail_after_oom_kill(spec, entry, oom_cause)
            return
        if entry is not None and entry.num_retries_left != 0:
            if entry.num_retries_left > 0:
                entry.num_retries_left -= 1
            self.stats["tasks_retried"] += 1
            if self.task_events.enabled:
                self.task_events.record(spec.task_id, RETRY,
                                        {"reason": "worker died"})
            logger.info("retrying task %s after worker death", spec.name)
            self._queue_spec(spec)
        else:
            self._store_error_for_task(
                spec, exc.WorkerCrashedError(
                    f"worker died executing {spec.name}"))

    def _retry_or_fail_after_oom_kill(self, spec: TaskSpec, entry,
                                      cause: dict):
        """Worker was killed by a node's memory watchdog: retry under
        the DEDICATED ``task_oom_retries`` budget (an OOM kill is the
        node's pressure, not the task's bug — the generic worker-crash
        budget survives), paced by the shared exponential-jitter
        backoff so a genuinely ballooning task can't hot-loop
        kill/retry against a node that is still at the threshold.
        Exhausted budget — or a non-retriable task — surfaces a typed
        :class:`~ray_tpu.exceptions.OutOfMemoryError` carrying the
        watchdog's cause (node/worker ids + per-worker RSS snapshot)."""
        left = getattr(entry, "oom_retries_left", None) \
            if entry is not None else None
        if left is None:
            # first OOM for this task (or a C-fastpath entry whose
            # slots were never initialized): budget comes from config
            left = self.config.task_oom_retries
        if entry is not None and spec.max_retries != 0 and left != 0:
            entry.oom_retries_left = left - 1 if left > 0 else left
            self.stats["tasks_retried"] += 1
            if self.task_events.enabled:
                self.task_events.record(spec.task_id, RETRY,
                                        {"reason": "worker OOM-killed"})
            bo = getattr(entry, "oom_backoff", None)
            if bo is None:
                from ray_tpu._private import backoff as backoff_mod
                bo = entry.oom_backoff = backoff_mod.from_config(
                    self.config)
            delay = bo.next_delay()
            logger.info("retrying task %s in %.2fs after watchdog OOM "
                        "kill", spec.name, delay)
            self.loop.call_later(delay, self._queue_spec, spec)
        else:
            self._store_error_for_task(
                spec, exc.OutOfMemoryError(
                    f"worker running {spec.name} was killed by the "
                    f"node memory watchdog", cause=cause))

    def _on_push_batch_done(self, fut: asyncio.Future, sc: int,
                            state: SchedulingKeyState, lw: LeasedWorker,
                            batch: List[TaskSpec]):
        lw.inflight -= len(batch)
        err = fut.exception() if not fut.cancelled() else None
        if fut.cancelled() or err is not None:
            for spec in batch:
                self._retry_or_fail_after_worker_death(spec, lw.worker_id)
            return
        reply, rbufs = fut.result()
        # Fast path for the dominant reply shape (ok, one inline
        # return, no deps/contained refs): batch every memory-store
        # landing under ONE lock via put_many.  The shape split runs in
        # C when the native ctx exists (cpp/fastpath.c complete_fast);
        # the Python fallback implements the identical
        # (pairs, finished, slow-indices) contract, so the stolen-reply
        # handling and the lease tail exist exactly once.
        replies = reply["replies"]
        keep_lineage = self.config.lineage_reconstruction_enabled
        ctx = self._fast_ctx
        if ctx is not None:
            put_pairs, finished, slow = ctx.complete_fast(
                batch, replies, rbufs, keep_lineage)
        else:
            put_pairs, finished, slow = self._complete_batch_py(
                batch, replies, rbufs, keep_lineage)
        for i in slow:
            spec = batch[i]
            rheader, fstart, nframes = replies[i]
            if rheader[0] == REPLY_STOLEN:
                # relinquished by THIS worker via StealTasks; the steal
                # reply already requeued it elsewhere. Consume only this
                # victim's entry — a second steal's victim keeps its own.
                victims = state.reassigned.get(spec.task_id)
                if victims is not None and lw.worker_id in victims:
                    victims.remove(lw.worker_id)
                    if not victims:
                        del state.reassigned[spec.task_id]
                continue
            self._complete_task(spec, rheader, rbufs[fstart:fstart + nframes])
        if put_pairs:
            self.memory_store.put_many(put_pairs)
        if finished:  # lineage-skip completions carry no put pair
            self.stats["tasks_finished"] += finished
        # Reuse the lease, steal for it, or (after a grace) return it.
        if state.queue:
            self._pump_scheduling_key(sc, state)
        elif lw.inflight == 0:
            if not self._try_steal(sc, state):
                self._schedule_idle_return(sc, state, lw)

    def _complete_batch_py(self, batch, replies, rbufs, keep_lineage):
        """Pure-Python twin of the native complete_fast: split a reply
        batch into memory-store pairs for the dominant shape plus slow
        indices for everything else."""
        pending = self.pending_tasks
        put_pairs: List[tuple] = []
        slow: List[int] = []
        finished = 0
        for i, (spec, (rheader, fstart, _nframes)) in enumerate(
                zip(batch, replies)):
            rets = rheader[1]
            if rheader[0] == 0 and not spec.args and len(rets) == 1:
                ret0 = rets[0]
                compact = len(ret0) == 2
                if not compact and (ret0[1] or ret0[5]):
                    slow.append(i)  # plasma / contained refs
                    continue
                entry = pending.get(spec.task_id)
                if entry is None:
                    continue
                if entry.recovery_waiter is not None:
                    slow.append(i)
                    continue
                if entry.lineage_pinned is None:
                    # returns all released in flight: skip the store
                    # put (it would orphan — the release-path delete
                    # already ran, and put_many lands AFTER the
                    # _finish_pending_entry cleanup) and drop the
                    # record, same contract as the C path's skip
                    # branch. Applies with lineage on OR off.
                    pending.pop(spec.task_id, None)
                    finished += 1
                    continue
                if compact:
                    # [meta, frames], oid derived from the task id
                    oid_b = return_object_id_bytes(spec.task_id, 1)
                    meta, frames = ret0
                else:
                    oid_b, _ip, meta, start, n, _cont = ret0[:6]
                    if len(ret0) > 6:
                        # inline return: payload frames decoded with
                        # the reply header (INLINE_RETURN_MAX)
                        frames = ret0[6]
                    else:
                        # `start` is task-relative; `fstart` locates
                        # this task's frames in the batch buffer
                        base = fstart + start
                        frames = rbufs[base:base + n]
                put_pairs.append((ObjectID(oid_b), SerializedObject(
                    meta, frames)))
                finished += 1
                self._finish_pending_entry(spec, entry, keep_lineage)
                continue
            slow.append(i)
        return put_pairs, finished, slow

    def _complete_task(self, spec: TaskSpec, reply: list, rbufs: List[bytes]):
        """Handle a task reply: land return values in the memory store /
        record plasma locations (reference: TaskManager::CompletePendingTask).
        ``reply`` is the compact [status, returns] list (task_spec.py)."""
        entry = self.pending_tasks.get(spec.task_id)
        if entry is None:
            return
        if reply[0] == REPLY_ERROR and spec.retry_exceptions and \
                entry.num_retries_left != 0:
            if entry.num_retries_left > 0:
                entry.num_retries_left -= 1
            self.stats["tasks_retried"] += 1
            if self.task_events.enabled:
                self.task_events.record(spec.task_id, RETRY,
                                        {"reason": "application error"})
            self._queue_spec(spec)
            return
        for ret in reply[1]:
            if len(ret) == 2:
                # compact single-return row [meta, frames]: the return
                # oid is derived (task id + index 1)
                self.memory_store.put(
                    return_object_id_bytes(spec.task_id, 1),
                    SerializedObject(ret[0], ret[1]))
                continue
            oid_b, in_plasma, meta, start, n, contained_b = ret[:6]
            oid = ObjectID(oid_b)
            if in_plasma:
                # plasma entry: meta=node_id, start=size. if_tracked:
                # refs released while the task ran must not be
                # resurrected by the location report — free the
                # replica instead (it has zero owners)
                if self.reference_counter.add_location_if_tracked(
                        oid, meta, start):
                    self.memory_store.put(oid, IN_PLASMA)
                else:
                    self._fire_and_forget(self._free_remote(oid, [meta]))
            else:
                frames = ret[6] if len(ret) > 6 \
                    else rbufs[start:start + n]
                obj = SerializedObject(meta, frames)
                if contained_b:
                    contained = [ObjectID(b) for b in contained_b]
                    self.reference_counter.add_contained_refs(oid, contained)
                    obj.contained_refs = contained
                self.memory_store.put(oid, obj)
        self.stats["tasks_finished"] += 1
        if spec.args and not spec.is_actor_task():
            self.reference_counter.update_finished_task_references(
                spec.dependency_ids())
        self._finish_pending_entry(
            spec, entry, self.config.lineage_reconstruction_enabled)

    def _finish_pending_entry(self, spec: TaskSpec, entry,
                              keep_lineage: bool) -> None:
        """Completion tail shared by _complete_task and the batched
        fast path: wake any recovery waiter, and drop the pending entry
        unless lineage reconstruction needs it."""
        waiter = entry.recovery_waiter
        if waiter is not None:
            entry.recovery_waiter = None
            if not waiter.done():
                waiter.set_result(True)
        if not keep_lineage or entry.lineage_pinned is None:
            # lineage off, or every return was already released while
            # the task ran (_release_lineage) — nobody can reconstruct
            self.pending_tasks.pop(spec.task_id, None)
            if entry.lineage_pinned is None:
                # the refs died before the values landed, so the
                # release path's memory_store.delete already ran —
                # drop the just-stored orphans (fire-and-forget tasks)
                for rid in entry.return_ids:
                    self.memory_store.delete(rid)
        else:
            # completed: the entry now lives only for lineage; the last
            # return's release pops it (_release_lineage)
            entry.lineage_pinned = True

    def _store_error_for_task(self, spec: TaskSpec, error: BaseException):
        if self.task_events.enabled:
            # owner-observed failures (worker death, cancellation,
            # infeasibility, dead actor): the worker never ran the task,
            # so the terminal FAILED is stamped here
            attrs = {"reason": type(error).__name__,
                     "message": str(error)[:200]}
            cause = getattr(error, "cause_info", None)
            if cause:
                # structured death cause (ActorDiedError /
                # ObjectLostError): state.list_tasks() shows node death
                # vs worker crash vs restarts-exhausted, with ids
                attrs["cause"] = {k: cause[k] for k in
                                  ("kind", "node_id", "worker_id",
                                   "last_failure")
                                  if cause.get(k)}
            self.task_events.record(spec.task_id, FAILED, attrs)
        serialized = self.serialization_context.serialize_error(error)
        task_id = TaskID(spec.task_id)
        for i in range(spec.num_returns):
            self.memory_store.put(task_id.object_id(i + 1), serialized)
        # A recovery waiting on this task must learn the outcome NOW (the
        # error value landed in the memory store) rather than time out;
        # the entry then follows the normal completion lifecycle so
        # errored tasks don't pin their records forever.
        entry = self.pending_tasks.get(spec.task_id)
        if entry is not None:
            self._finish_pending_entry(
                spec, entry, self.config.lineage_reconstruction_enabled)
        self.reference_counter.update_finished_task_references(
            spec.dependency_ids())

    # ------------------------------------------------------------- actors

    def register_actor_handle_factory(self, factory):
        self._actor_handle_factory = factory

    def create_actor(self, fn_key: str, name: str, args: List[Any],
                     actor_name: str = "", namespace: str = "",
                     max_restarts: int = 0, max_concurrency: int = 1,
                     resources: Dict[str, float] | None = None,
                     lifetime_resources: Dict[str, float] | None = None,
                     is_asyncio: bool = False,
                     placement_group_id: bytes = b"",
                     placement_group_bundle_index: int = -1,
                     max_pending_calls: int = -1,
                     runtime_env: Dict | None = None) -> bytes:
        actor_id = ActorID.of(JobID(self.job_id)).binary()
        prepared_args, arg_holds = self._prepare_args(args)
        spec = TaskSpec(
            task_id=TaskID.of(ActorID(actor_id)).binary(), job_id=self.job_id,
            task_type=TASK_ACTOR_CREATION, name=name, fn_key=fn_key,
            args=prepared_args, num_returns=0,
            resources=resources or {"CPU": 1.0},
            owner_address=self.address, owner_worker_id=self.worker_id,
            actor_id=actor_id,
            runtime_env=self._resolve_runtime_env(runtime_env),
            actor_creation={"max_restarts": max_restarts,
                            "max_concurrency": max_concurrency,
                            "is_asyncio": is_asyncio,
                            "name": actor_name, "namespace": namespace},
            placement_group_id=placement_group_id,
            placement_group_bundle_index=placement_group_bundle_index)
        header, frames = spec.to_wire_dict()
        header["lifetime_resources"] = lifetime_resources
        header["pg_id"] = placement_group_id
        header["pg_bundle"] = placement_group_bundle_index
        self._run(self._gcs_call("RegisterActor", {
            "actor_id": actor_id, "spec": header,
            "name": actor_name, "namespace": namespace,
            "max_restarts": max_restarts, "job_id": self.job_id,
        }, bufs=frames))
        q = ActorQueueState(actor_id)
        q.max_pending = max_pending_calls
        self.actor_queues[actor_id] = q
        # Actor-creation args stay pinned for the actor's restarts: keep the
        # holds on the queue state (freed when the queue is dropped).
        q.creation_arg_holds = arg_holds  # type: ignore[attr-defined]
        return actor_id

    def submit_actor_task(self, actor_id: bytes, fn_key: str, name: str,
                          args: List[Any], num_returns: int = 1,
                          max_task_retries: int = 0) -> List[ObjectRef]:
        # (4) backpressure: enforce max_pending_calls before queueing.
        self._check_actor_backpressure(actor_id)
        task_id = TaskID.of(ActorID(actor_id))
        prepared_args, arg_holds = self._prepare_args(args)
        spec = TaskSpec(
            task_id=task_id.binary(), job_id=self.job_id,
            task_type=TASK_ACTOR, name=name, fn_key=fn_key,
            args=prepared_args, num_returns=num_returns,
            resources={}, max_retries=max_task_retries,
            owner_address=self.address, owner_worker_id=self.worker_id,
            actor_id=actor_id, trace_ctx=_trace_ctx())
        return self._register_and_submit_actor(spec, arg_holds)

    def make_actor_template(self, actor_id: bytes, fn_key: str, name: str,
                            num_returns: int = 1,
                            max_task_retries: int = 0) -> TaskSpec:
        """Prototype spec for repeated calls of one actor method (the
        actor-side twin of make_task_template): per-call work drops to
        id mint + clone — or the native fused submit."""
        return TaskSpec(
            task_id=b"", job_id=self.job_id,
            task_type=TASK_ACTOR, name=name, fn_key=fn_key,
            args=[], num_returns=num_returns,
            resources={}, max_retries=max_task_retries,
            owner_address=self.address, owner_worker_id=self.worker_id,
            actor_id=actor_id)

    def _check_actor_backpressure(self, actor_id: bytes) -> None:
        q = self.actor_queues.get(actor_id)
        if q is not None and q.max_pending >= 0 and \
                len(q.buffer) + len(q.inflight) >= q.max_pending:
            raise exc.PendingCallsLimitExceeded(
                f"actor has {len(q.buffer) + len(q.inflight)} pending calls "
                f"(max_pending_calls={q.max_pending})")

    def submit_actor_from_template(self, proto: TaskSpec
                                   ) -> List[ObjectRef]:
        """Arg-less actor call on a cached template (backpressure
        checked, then the fused native path when built — single-return
        only, same gate as submit_task_from_template)."""
        actor_id = proto.actor_id
        self._check_actor_backpressure(actor_id)
        if proto.num_returns == 1:
            ctx = self._fast_ctx
            if ctx is None and not self._fast_ctx_failed:
                ctx = self._make_fast_ctx()
            if ctx is not None:
                # SUBMITTED recorded loop-side (_drain_submit_buffer)
                return ctx.submit(proto, actor_id, _trace_ctx(), True)
        spec = proto.clone_for(make_task_id_bytes(actor_id), (),
                               trace_ctx=_trace_ctx())
        return self._register_and_submit_actor(spec, None)

    def _register_and_submit_actor(self, spec: TaskSpec, arg_holds
                                   ) -> List[ObjectRef]:
        task_id = TaskID(spec.task_id)
        num_returns = spec.num_returns
        return_ids = [task_id.object_id(i + 1) for i in range(num_returns)]
        refs = []
        for oid in return_ids:
            self.reference_counter.add_owned_with_local_ref(oid)
            refs.append(ObjectRef(oid, owner_address=self.address, worker=self,
                                  call_site=spec.name,
                                  skip_adding_local_ref=True))
        entry = PendingTaskEntry(spec, return_ids)
        self.pending_tasks[spec.task_id] = entry
        if entry.dep_ids:
            self.reference_counter.update_submitted_task_references(
                entry.dep_ids)
        del arg_holds
        self.stats["actor_tasks_submitted"] += 1
        # SUBMITTED recorded loop-side by _drain_submit_buffer
        # Seqno assignment happens at drain time in buffer order, which is
        # submission order (the receiver executes strictly by seqno). By-ref
        # args resolve at the executing worker — the owner's GetObject blocks
        # until the value exists — so no client-side dependency wait is
        # needed, and ordering can't be inverted by slow dependencies.
        self._enqueue_submit("actor", spec)
        return refs

    def _pump_actor_queue(self, q: ActorQueueState):
        if q.state == "DEAD":
            for spec, _ in q.buffer:
                self._store_error_for_task(
                    spec, exc.ActorDiedError(q.death_cause or "actor is dead",
                                             cause=q.death_info))
            q.buffer.clear()
            return
        if q.conn is None or q.conn.closed:
            if not q.resolving:
                q.resolving = True
                rpc.spawn_logged(self._resolve_actor(q),
                                 "worker-resolve-actor", loop=self.loop)
            return
        if not q.buffer:
            return
        # Drain the whole buffer into ONE wire message (same batching as
        # the normal-task path); seqnos stay per-task for the receiver's
        # reorder buffer.
        theaders: List[list] = []
        frames: List[bytes] = []
        batch: List[Tuple[TaskSpec, int]] = []
        ev = self.task_events
        ev_attrs = {"actor": q.actor_id.hex()[:12]} if ev.enabled else None
        while q.buffer:
            spec, seqno = q.buffer.popleft()
            q.inflight[seqno] = (spec, 0)
            if ev_attrs is not None:
                ev.record(spec.task_id, DISPATCHED, ev_attrs)
            tw, tfr = spec.to_wire()
            theaders.append([tw, seqno, len(frames), len(tfr)])
            frames.extend(tfr)
            batch.append((spec, seqno))
        try:
            fut = q.conn.call_nowait(
                "PushActorTasks",
                {"tasks": theaders, "incarnation": q.incarnation},
                bufs=frames)
        except ConnectionError:
            # Conn-lost handler requeues the inflight entries.
            return
        fut.add_done_callback(
            lambda f, batch=batch: self._on_actor_batch_done(f, q, batch))

    async def _resolve_actor(self, q: ActorQueueState):
        from ray_tpu._private import backoff as backoff_mod

        try:
            deadline = time.monotonic() + 120.0
            # exponential-jitter retry pacing (backoff.py): starts at
            # the old 0.05 s fast path, backs off toward the cap while
            # the actor is restarting / the GCS is down — no more
            # fixed-interval polling storms from every holder of a
            # handle to a restarting actor
            bo = backoff_mod.from_config(self.config)
            while time.monotonic() < deadline:
                if q.conn is not None and not q.conn.closed and \
                        q.state == "ALIVE":
                    return  # a concurrent resolve already connected
                if self._shutdown:
                    return
                # _gcs_call redials a restarting GCS — do NOT bail on a
                # closed gcs_conn here, or buffered actor calls would
                # hang with no retry timer.
                try:
                    reply, _ = await self._gcs_call(
                        "GetActorInfo", {"actor_id": q.actor_id})
                except ConnectionError:
                    await bo.sleep()  # GCS still down; keep trying
                    continue
                if not reply.get("found"):
                    await bo.sleep()
                    continue
                if reply["state"] == "ALIVE" and \
                        reply["incarnation"] != q.incarnation:
                    try:
                        q.conn = await rpc.connect(
                            reply["address"], peer_name="actor",
                            handlers={"ActorTaskResult":
                                      self._actor_result_handler(q)})
                    except ConnectionError:
                        await bo.sleep()
                        continue
                    q.address = reply["address"]
                    q.state = "ALIVE"
                    restarted = q.incarnation != -1
                    q.incarnation = reply["incarnation"]
                    if restarted:
                        # Fresh worker expects seqno 0: renumber the stream
                        # (reference: the submitter resets sequence state on
                        # actor restart, direct_actor_transport.h).
                        q.buffer = deque(
                            (spec, i)
                            for i, (spec, _) in enumerate(q.buffer))
                        q.seqno = len(q.buffer)
                    q.conn.on_disconnect.append(
                        lambda c, q=q: self._on_actor_conn_lost(q, c))
                    self._pump_actor_queue(q)
                    return
                if reply["state"] == "DEAD":
                    q.state = "DEAD"
                    q.death_cause = reply.get("death_cause", "actor died")
                    q.death_info = reply.get("death_info") or {}
                    self._pump_actor_queue(q)
                    return
                await bo.sleep()
            q.state = "DEAD"
            q.death_cause = "timed out resolving actor location"
            q.death_info = {"kind": "RESOLVE_TIMEOUT"}
            self._pump_actor_queue(q)
        finally:
            q.resolving = False

    def _on_actor_conn_lost(self, q: ActorQueueState,
                            conn: Optional[rpc.Connection] = None):
        """Actor worker connection dropped: requeue retryable inflight tasks
        and re-resolve (the actor may be restarting). Tasks without retries
        fail with ActorDiedError (reference: max_task_retries semantics in
        direct_actor_transport.h)."""
        if conn is not None and q.conn is not conn:
            return  # stale disconnect from a pre-restart connection
        q.conn = None
        q.state = "RESOLVING"
        inflight = sorted(q.inflight.items())
        q.inflight.clear()
        requeue = []
        for seqno, (spec, _) in inflight:
            entry = self.pending_tasks.get(spec.task_id)
            retries_left = entry.num_retries_left if entry else 0
            if retries_left != 0:
                if entry and entry.num_retries_left > 0:
                    entry.num_retries_left -= 1
                self.stats["tasks_retried"] += 1
                if self.task_events.enabled:
                    self.task_events.record(
                        spec.task_id, RETRY,
                        {"reason": "actor connection lost"})
                requeue.append((spec, seqno))
            else:
                self._store_error_for_task(spec, exc.ActorDiedError(
                    "actor worker died before the call completed",
                    cause=q.death_info or {"kind": "WORKER_DIED"}))
        q.buffer.extendleft(reversed(requeue))
        self._pump_actor_queue(q)

    def _on_actor_batch_done(self, fut: asyncio.Future, q: ActorQueueState,
                             batch: List[Tuple[TaskSpec, int]]):
        if fut.cancelled() or fut.exception() is not None:
            # Connection lost: the conn-lost handler requeues inflight.
            return
        reply, rbufs = fut.result()
        if reply.get("streamed"):
            # Concurrent actor: per-task results arrive as
            # ActorTaskResult pushes (see _actor_result_handler);
            # entries stay inflight until theirs lands.
            return
        requeue: List[Tuple[TaskSpec, int]] = []
        for (spec, seqno), (rheader, fstart, nframes) in zip(
                batch, reply["replies"]):
            q.inflight.pop(seqno, None)
            if rheader[0] == REPLY_ACTOR_RESTARTING:
                requeue.append((spec, seqno))
                continue
            self._complete_task(spec, rheader, rbufs[fstart:fstart + nframes])
            if spec.args:
                self.reference_counter.update_finished_task_references(
                    spec.dependency_ids())
        if requeue:
            q.buffer.extendleft(reversed(requeue))

    def _actor_result_handler(self, q: ActorQueueState):
        """Push handler resolving one streamed actor-task result
        (concurrent actors reply per task, not per batch)."""
        async def handler(conn, header, bufs):
            if q.conn is not conn:
                return  # stale pre-restart connection
            seqno = header["seqno"]
            entry = q.inflight.get(seqno)
            if entry is None:
                return  # already requeued by a conn-loss race
            spec, _ = entry
            rheader = header["reply"]
            q.inflight.pop(seqno, None)
            if rheader[0] == REPLY_ACTOR_RESTARTING:
                q.buffer.append((spec, seqno))
                self._pump_actor_queue(q)
                return
            self._complete_task(spec, rheader, list(bufs))
            if spec.args:
                self.reference_counter.update_finished_task_references(
                    spec.dependency_ids())
        return handler

    def cancel(self, ref: ObjectRef, force: bool = False):
        """Best-effort task cancel (reference: CoreWorker::CancelTask):
        drop it from the local queue if not yet pushed, else ask every
        leased worker of the scheduling class to cancel."""
        self._run(self._cancel_async(ref))

    async def _cancel_async(self, ref: ObjectRef):
        entry = self.pending_tasks.get(ref.object_id.task_id().binary())
        if entry is None:
            return
        state = self.scheduling_keys.get(entry.spec.scheduling_class)
        if state is None:
            return
        if entry.spec in state.queue:
            state.queue.remove(entry.spec)
            self._store_error_for_task(
                entry.spec, exc.TaskCancelledError(entry.spec.name))
            return
        for lw in state.workers:
            try:
                await lw.conn.call("CancelTask",
                                   {"task_id": entry.spec.task_id})
            except ConnectionError:
                pass

    def kill_actor(self, actor_id: bytes, no_restart: bool = True):
        self._run(self._gcs_call("KillActor", {
            "actor_id": actor_id, "no_restart": no_restart}))

    async def _metrics_report_loop(self):
        """Ship this process's user-metric registry AND buffered
        task-lifecycle events to the GCS on a timer (reference:
        per-process OpenCensus exporter → metrics agent,
        stats/metric.h + metrics_agent.py; TaskEventBuffer's periodic
        GCS flush, task_event_buffer.h). Task events ride this existing
        cadence — never a per-transition RPC."""
        from ray_tpu._private import metrics as metrics_mod

        period = self.config.metrics_report_period_ms / 1000.0
        reporter = f"{self.mode}-{WorkerID(self.worker_id).hex()[:12]}"
        # This CoreWorker ships the process-global registry; an
        # in-process raylet (head node) must not ship it again.
        metrics_mod.mark_core_reporter()
        while not self._shutdown:
            await asyncio.sleep(period)
            # loop-lag probe rides this existing cadence (the
            # instrumented_io_context tick for worker/driver loops)
            rpc.telemetry.loop_probe("core").tick()
            snap = metrics_mod.global_registry().snapshot()
            if rpc.telemetry.enabled:
                # per-method RPC latency histograms merge into the same
                # registry shipment (real Prometheus histograms on the
                # GCS endpoint, no new transport)
                snap.update(rpc.telemetry.prom_snapshot())
            if snap:
                try:
                    await self._gcs_call("ReportMetrics", {
                        "reporter_id": reporter, "snapshot": snap})
                except (ConnectionError, asyncio.TimeoutError):
                    pass  # GCS restarting; next period retries
            await self._flush_rpc_telemetry(reporter)
            await self._flush_task_events()
            await self._flush_object_events()
            await self._flush_cluster_events()

    async def _flush_rpc_telemetry(self, reporter: str):
        """Ship this process's flight-recorder snapshot + drained slow
        calls (claiming the process's reporter role — an in-process
        raylet skips its heartbeat copy via metrics.core_reporter, the
        same single-shipper rule the metric registry uses)."""
        if not rpc.telemetry.enabled:
            return
        slow, dropped = rpc.telemetry.drain_slow_calls()
        try:
            await self._gcs_call(
                "ReportRpcTelemetry",
                protocol.ReportRpcTelemetryRequest(
                    reporter_id=reporter,
                    snapshot=rpc.telemetry.wire(probe="core"),
                    slow_calls=slow,
                    slow_calls_dropped=dropped).to_header())
        except (ConnectionError, asyncio.TimeoutError):
            pass  # GCS restarting; gauges re-ship next period
        except Exception:  # noqa: BLE001
            # a not-yet-upgraded GCS without the handler (rolling
            # upgrade): the wire error must not kill the metrics loop
            logger.debug("ReportRpcTelemetry flush failed", exc_info=True)

    async def _flush_cluster_events(self):
        """Drain the cluster-event buffer to the GCS event table (same
        contract as _flush_task_events: bounded batch, a flush lost to
        a restarting GCS is bounded loss by design)."""
        events, dropped = self.cluster_events.drain()
        if not events and not dropped:
            return
        try:
            await self._gcs_call(
                "AddClusterEvents",
                protocol.AddClusterEventsRequest(
                    events=events, dropped=dropped).to_header())
        except (ConnectionError, asyncio.TimeoutError):
            pass  # GCS restarting; bounded loss
        except Exception:  # noqa: BLE001
            # a not-yet-upgraded GCS without the AddClusterEvents
            # handler must not kill the metrics-report loop
            logger.debug("AddClusterEvents flush failed", exc_info=True)

    async def _flush_object_events(self):
        """Drain the object-event buffer to the GCS object table (same
        contract as _flush_task_events: bounded batch, a flush lost to
        a restarting GCS is bounded loss by design)."""
        events, dropped = self.object_events.drain_wire()
        if not events and not dropped:
            return
        try:
            await self._gcs_call(
                "AddObjectEvents",
                protocol.AddObjectEventsRequest(
                    events=events, dropped=dropped).to_header())
        except (ConnectionError, asyncio.TimeoutError):
            pass  # GCS restarting; bounded loss
        except Exception:  # noqa: BLE001
            # e.g. a not-yet-upgraded GCS without the AddObjectEvents
            # handler (rolling upgrade): the error re-raised off the
            # wire must not escape the metrics-report loop and kill
            # metrics + task-event shipping for the worker's lifetime
            logger.debug("AddObjectEvents flush failed", exc_info=True)

    async def _flush_task_events(self):
        """Drain the task-event buffer to the GCS task table (the
        batch is bounded by the buffer capacity; a flush lost to a
        restarting GCS is bounded event loss, by design —
        observability never blocks or retries forever)."""
        events, dropped = self.task_events.drain_wire()
        if not events and not dropped:
            return
        try:
            await self._gcs_call(
                "AddTaskEvents",
                protocol.AddTaskEventsRequest(
                    events=events, dropped=dropped,
                    job_id=self.job_id).to_header())
        except (ConnectionError, asyncio.TimeoutError):
            pass  # GCS restarting; bounded loss

    async def _handle_published(self, conn, header, bufs):
        if header["channel"] == "LOGS":
            msg = header["msg"]
            prefix = f"(pid={msg['pid']}, {msg['ip']})"
            for line in msg["lines"]:
                print(f"{prefix} {line}", flush=True)
            return {}
        if header["channel"] == "ACTOR":
            msg = header["msg"]
            q = self.actor_queues.get(msg["actor_id"])
            if q is None:
                return {}
            if msg["state"] == "ALIVE" and msg["incarnation"] != q.incarnation:
                if not q.resolving:
                    q.resolving = True
                    rpc.spawn_logged(self._resolve_actor(q),
                                     "worker-resolve-actor")
            elif msg["state"] == "DEAD":
                q.state = "DEAD"
                q.death_cause = msg.get("reason", "actor died")
                q.death_info = msg.get("death_info") or {}
                self._pump_actor_queue(q)
            elif msg["state"] == "RESTARTING":
                q.state = "RESOLVING"
        return {}

    # ------------------------------------------------------------ profiling

    def add_task_event(self, event: dict):
        if self.config.profiling_enabled:
            self._task_events.append(event)

    def add_exec_event(self, name: str, task_id: bytes,
                       start: float, end: float):
        """Hot-path execution event: append a TUPLE; the dict form (with
        hex ids) is built lazily at flush time, off the per-task path."""
        self._task_events.append(("task:execute", name, task_id, start, end))

    async def _profile_flush_loop(self):
        period = self.config.metrics_report_period_ms / 1000.0
        while not self._shutdown:
            await asyncio.sleep(period)
            # reap shm mappings whose last zero-copy consumer view has
            # been garbage-collected since the store detached (the
            # park-and-sweep half of the view-release discipline —
            # shm_store._QuietSharedMemory)
            try:
                from ray_tpu._private import shm_store
                shm_store.sweep_zombies()
            # raylint: disable=exception-hygiene — maintenance loop must not die
            except Exception:
                pass
            if self._task_events and self.gcs_conn and not self.gcs_conn.closed:
                events, self._task_events = self._task_events, []
                wid = self.worker_id.hex()
                events = [
                    {"event": e[0], "name": e[1], "task_id": e[2].hex(),
                     "start": e[3], "end": e[4], "worker_id": wid}
                    if type(e) is tuple else e
                    for e in events]
                try:
                    await self.gcs_conn.call("AddProfileEvents",
                                             {"events": events})
                except ConnectionError:
                    return
