"""Deterministic fault-injection plane: named fault points + arming API.

Every recovery path in this repo — task retries, actor restarts, GCS
journal replay, stripe failover, heartbeat-driven node death — exists
because some component can fail. This module is the process-wide
registry that lets tests (and the seeded chaos scheduler in
tests/chaos.py) MAKE those components fail, deterministically, at the
exact seam that owns each failure domain.

Design contract:

* **Zero cost disarmed.** Wired sites guard with
  ``if faultpoints.armed:`` — one module-attribute load and a falsy
  check on the hot path; the registry itself is only consulted once a
  test armed something. ``armed`` is False in production by default
  and is pinned by bench.py's ``faultpoints_overhead`` row.
* **Deterministic.** Probabilistic points draw from a per-point
  ``random.Random(seed)``; hit counters are exact; the same arming +
  the same workload fires the same faults in the same order.
* **Cross-process.** Worker/raylet/GCS subprocesses arm themselves at
  boot from the ``RAY_TPU_FAULTPOINTS`` env var (a JSON list of arm()
  kwargs), so "kill the worker at its 3rd task" is a deterministic
  schedule, not a SIGKILL race.

Action vocabulary (``action=``):

=============  ==============================================================
``raise``      raise ``exc`` (default :class:`FaultInjected`) at the site
``delay``      sleep ``delay_s`` (async sites await, sync sites block)
``kill``       ``os._exit(kill_code)`` — hard process death at the site
``hook``       call ``hook(**ctx)`` (may itself raise) — arbitrary injection
``drop``       site-interpreted: the message/beat/reply is silently dropped
``sever``      site-interpreted: the owning connection is torn down
``duplicate``  site-interpreted: the message is sent twice
``corrupt``    site-interpreted: the payload/frame is scribbled with garbage
``short``      site-interpreted: fewer payload bytes than promised are sent
``miss``       site-interpreted: the allocation/lookup reports not-found
``refuse``     site-interpreted: the operation reports failure (e.g. seal)
=============  ==============================================================

Site-interpreted actions are returned from :func:`fire` as strings; the
wired layer applies the ones it understands (unknown actions at a site
are ignored — arming ``corrupt`` on a point that cannot corrupt is a
no-op, never an error).

Wired point catalogue (name — owning layer — ctx keys):

* ``rpc.call.send``        — rpc.py client     — method, peer
* ``rpc.reply.send``       — rpc.py server     — method, peer
* ``data.serve_chunk``     — data_channel.py   — oid, offset, length
* ``data.stripe_dial``     — data_channel.py   — address
* ``data.fetch_chunk``     — data_channel.py   — offset, length
* ``shm.alloc``            — shm_store.py      — size
* ``shm.seal``             — shm_store.py      — oid, size
* ``raylet.heartbeat``     — raylet.py         — node
* ``raylet.lease.grant``   — raylet.py         — lease_id, node
* ``gcs.journal.append``   — gcs.py            — op
* ``gcs.journal.replay``   — gcs.py            — op, n
* ``task.execute``         — task_executor.py  — name, task_id
* ``memory.poll``          — memory_monitor.py — node, sim, pids
* ``memory.kill``          — memory_monitor.py — node, worker, pid
* ``lease.backpressure``   — raylet.py         — node
* ``lease.credit.grant``   — raylet.py         — node, sched_class, n
* ``lease.credit.revoke``  — raylet.py         — node, sched_class,
  reason, n (``drop`` loses the grant push / revoke call — the ledger
  must reconcile on a later heartbeat beat)

Match predicates (all optional, AND-combined):

* ``nth=N``    fire only on the Nth matching hit (1-based)
* ``every=K``  fire on every Kth matching hit
* ``after=N``  fire on every matching hit past the first N
* ``p=F``      fire with probability F per hit (seeded RNG)
* ``times=N``  fire at most N times, then go dormant (still counted)
* ``match={}`` ctx filter: key -> expected value, or key -> callable(v)
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

# THE hot-path guard: wired sites check ``faultpoints.armed`` before
# touching anything else in this module. False = production default.
armed = False

ENV_VAR = "RAY_TPU_FAULTPOINTS"

# Actions fully handled inside fire(); everything else is returned to
# the wired site to interpret.
_GENERIC_ACTIONS = ("raise", "delay", "kill", "hook")
SITE_ACTIONS = ("drop", "sever", "duplicate", "corrupt", "short",
                "miss", "refuse")


class FaultInjected(RuntimeError):
    """Default exception raised by an armed ``raise`` action."""


class FaultPoint:
    """One armed fault spec at a named point (a point may hold several,
    e.g. a delay on Heartbeat and a raise on KVPut at the same site)."""

    def __init__(self, name: str, action: str, *,
                 exc: Optional[BaseException] = None,
                 delay_s: float = 0.0,
                 nth: int = 0, every: int = 0, after: int = 0,
                 p: float = 0.0, seed: int = 0, times: int = 0,
                 match: Optional[Dict[str, Any]] = None,
                 hook: Optional[Callable[..., Any]] = None,
                 kill_code: int = 1):
        if action not in _GENERIC_ACTIONS and action not in SITE_ACTIONS:
            raise ValueError(f"unknown fault action {action!r}")
        if action == "hook" and hook is None:
            raise ValueError("action='hook' requires hook=")
        self.name = name
        self.action = action
        self.exc = exc
        self.delay_s = delay_s
        self.nth = nth
        self.every = every
        self.after = after
        self.p = p
        self.times = times
        self.match = match or {}
        self.hook = hook
        self.kill_code = kill_code
        self.hits = 0    # matching-context evaluations
        self.fires = 0   # times the action actually triggered
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def _evaluate(self, ctx: Dict[str, Any]) -> Optional[str]:
        """Count a hit and decide whether this spec fires for ``ctx``.
        Returns the action name, or None."""
        for key, want in self.match.items():
            got = ctx.get(key)
            if callable(want):
                if not want(got):
                    return None
            elif got != want:
                return None
        with self._lock:
            self.hits += 1
            if self.times and self.fires >= self.times:
                return None
            if self.nth and self.hits != self.nth:
                return None
            if self.after and self.hits <= self.after:
                return None
            if self.every and self.hits % self.every != 0:
                return None
            if self.p and self._rng.random() >= self.p:
                return None
            self.fires += 1
        return self.action


_registry_lock = threading.Lock()
_points: Dict[str, List[FaultPoint]] = {}


def arm(name: str, action: str = "raise", **kwargs) -> FaultPoint:
    """Arm a fault spec at point ``name``; returns it (tests read
    ``.hits``/``.fires``). Arming the same name again STACKS a second
    spec — use :func:`disarm`/:func:`reset` between scenarios."""
    global armed
    spec = FaultPoint(name, action, **kwargs)
    with _registry_lock:
        _points.setdefault(name, []).append(spec)
        armed = True
    logger.info("faultpoint armed: %s action=%s", name, action)
    return spec


def disarm(name: str) -> None:
    """Remove every spec armed at ``name``."""
    global armed
    with _registry_lock:
        _points.pop(name, None)
        if not _points:
            armed = False


def reset() -> None:
    """Disarm everything (test teardown)."""
    global armed
    with _registry_lock:
        _points.clear()
        armed = False


def specs(name: str) -> List[FaultPoint]:
    return list(_points.get(name, ()))


def hits(name: str) -> int:
    return sum(s.hits for s in _points.get(name, ()))


def fires(name: str) -> int:
    return sum(s.fires for s in _points.get(name, ()))


def _apply(spec: FaultPoint, ctx: Dict[str, Any]) -> Optional[str]:
    """Execute a generic action inline; pass site actions back."""
    if spec.action == "raise":
        e = spec.exc if spec.exc is not None else FaultInjected(
            f"fault injected at {spec.name}")
        logger.info("faultpoint %s: raising %r", spec.name, e)
        raise e
    if spec.action == "kill":
        logger.warning("faultpoint %s: killing process %d", spec.name,
                       os.getpid())
        try:
            import sys
            sys.stdout.flush()
            sys.stderr.flush()
        except Exception:  # raylint: disable=exception-hygiene — flush is best-effort on the way out of a deliberate kill
            pass
        os._exit(spec.kill_code)
    if spec.action == "hook":
        spec.hook(**ctx)
        return None
    if spec.action == "delay":
        return "delay"  # caller decides blocking vs awaited sleep
    return spec.action


def _fired(point: str, ctx: Dict[str, Any]):
    """Shared firing pass for :func:`fire`/:func:`async_fire`:
    evaluate every spec at ``point`` against ``ctx`` and execute the
    generic actions (raise/kill/hook inside :func:`_apply`). Yields
    ``(spec, applied)`` for the actions the CALLER must finish —
    ``"delay"`` (blocking vs awaited sleep is the only difference
    between the two entry points) and the site-interpreted names."""
    point_specs = _points.get(point)
    if not point_specs:
        return
    for spec in list(point_specs):
        if spec._evaluate(ctx) is None:
            continue
        applied = _apply(spec, ctx)
        if applied is not None:
            yield spec, applied


def fire(point: str, **ctx) -> Optional[str]:
    """Evaluate fault point ``point`` (sync sites). Generic actions
    execute inline (``delay`` blocks the calling thread — wire async
    sites through :func:`async_fire` instead); the last matching
    site-interpreted action is returned, else None. The positional
    parameter is named ``point`` so ctx keys like ``name=`` never
    collide."""
    out = None
    for spec, applied in _fired(point, ctx):
        if applied == "delay":
            # raylint: disable=async-blocking — injected delay IS the fault: a sync site sleeps here by design; loop-hosted sites must wire async_fire instead
            time.sleep(spec.delay_s)
        else:
            out = applied
    return out


async def async_fire(point: str, **ctx) -> Optional[str]:
    """:func:`fire` for event-loop sites: ``delay`` awaits instead of
    blocking the loop."""
    import asyncio

    out = None
    for spec, applied in _fired(point, ctx):
        if applied == "delay":
            await asyncio.sleep(spec.delay_s)
        else:
            out = applied
    return out


def arm_from_env(environ=None) -> int:
    """Arm points from the ``RAY_TPU_FAULTPOINTS`` env var — a JSON
    list of ``arm()`` kwarg dicts, e.g.::

        [{"name": "task.execute", "action": "kill", "nth": 3}]

    Called at worker/raylet/GCS subprocess boot so chaos schedules
    reach processes the test did not construct directly. Unknown or
    malformed specs are logged and skipped (a typo in a chaos schedule
    must not take down the process it was meant to test). Returns the
    number of points armed."""
    raw = (environ or os.environ).get(ENV_VAR, "")
    if not raw:
        return 0
    try:
        entries = json.loads(raw)
    except ValueError:
        logger.error("malformed %s (not JSON): %r", ENV_VAR, raw[:200])
        return 0
    n = 0
    for entry in entries if isinstance(entries, list) else []:
        try:
            kwargs = dict(entry)
            name = kwargs.pop("name")
            action = kwargs.pop("action", "raise")
            arm(name, action, **kwargs)
            n += 1
        except Exception:  # noqa: BLE001 — a bad spec is skipped (and logged), never fatal
            logger.exception("bad faultpoint spec in %s: %r", ENV_VAR,
                             entry)
    return n
