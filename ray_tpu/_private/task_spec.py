"""Wire task representation + scheduling class interning.

Role parity: reference TaskSpecification (src/ray/common/task/task_spec.h)
— a self-contained, serializable description of one task invocation,
including inline small args, references for large args, resource demands,
retry policy and the owner's address. ``scheduling_class`` interns the
(resources, function) pair to a small int so scheduler queues can be
per-class arrays (reference: TaskSpecification::GetSchedulingClass).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import msgpack

TASK_NORMAL = 0
TASK_ACTOR_CREATION = 1
TASK_ACTOR = 2

# Arg encodings on the wire.
ARG_VALUE = 0  # inline serialized value: (ARG_VALUE, metadata, nframes) + frames
ARG_REF = 1    # by-reference: (ARG_REF, object_id_bytes, owner_address)

# Per-task reply headers are positional lists (hot path — one per task):
#   [status, returns]
# with each returns entry either
#   [object_id, 0, metadata, frame_start, num_frames, contained]  (inline)
#   [object_id, 1, node_id, size, 0, contained]                   (plasma)
REPLY_OK = 0
REPLY_ERROR = 1
REPLY_STOLEN = 2
REPLY_ACTOR_RESTARTING = 3


class TaskArg:
    __slots__ = ("kind", "metadata", "frames", "object_id", "owner_address",
                 "contained_refs")

    def __init__(self, kind, metadata=b"", frames=(), object_id=b"",
                 owner_address="", contained_refs=()):
        self.kind = kind
        self.metadata = metadata
        self.frames = list(frames)
        self.object_id = object_id
        self.owner_address = owner_address
        self.contained_refs = list(contained_refs)


_sched_class_lock = threading.Lock()
_sched_class_table: Dict[Tuple, int] = {}
_sched_class_rev: List[Tuple] = []


def scheduling_class_of(resources: Dict[str, float], fn_key: str) -> int:
    key = (tuple(sorted(resources.items())), fn_key)
    with _sched_class_lock:
        sc = _sched_class_table.get(key)
        if sc is None:
            sc = len(_sched_class_rev)
            _sched_class_table[key] = sc
            _sched_class_rev.append(key)
        return sc


class TaskSpec:
    __slots__ = (
        "task_id", "job_id", "task_type", "name", "fn_key", "args",
        "num_returns", "resources", "max_retries", "retry_exceptions",
        "owner_address", "owner_worker_id", "actor_id", "actor_counter",
        "actor_creation", "runtime_env", "placement_group_id",
        "placement_group_bundle_index", "scheduling_strategy", "depth",
        "trace_ctx", "_sched", "_proto",
    )

    def __init__(self, task_id: bytes, job_id: bytes, task_type: int,
                 name: str, fn_key: str, args: List[TaskArg],
                 num_returns: int = 1, resources: Optional[Dict[str, float]] = None,
                 max_retries: int = 0, retry_exceptions: bool = False,
                 owner_address: str = "", owner_worker_id: bytes = b"",
                 actor_id: bytes = b"", actor_counter: int = 0,
                 actor_creation: Optional[Dict[str, Any]] = None,
                 runtime_env: Optional[Dict[str, Any]] = None,
                 placement_group_id: bytes = b"",
                 placement_group_bundle_index: int = -1,
                 scheduling_strategy: str = "DEFAULT",
                 depth: int = 0,
                 trace_ctx=None):
        self.task_id = task_id
        self.job_id = job_id
        self.task_type = task_type
        self.name = name
        self.fn_key = fn_key
        self.args = args
        self.num_returns = num_returns
        self.resources = resources or {"CPU": 1.0}
        self.max_retries = max_retries
        self.retry_exceptions = retry_exceptions
        self.owner_address = owner_address
        self.owner_worker_id = owner_worker_id
        self.actor_id = actor_id
        self.actor_counter = actor_counter
        self.actor_creation = actor_creation
        self.runtime_env = runtime_env
        self.placement_group_id = placement_group_id
        self.placement_group_bundle_index = placement_group_bundle_index
        self.scheduling_strategy = scheduling_strategy
        self.depth = depth
        # (trace_id_hex, parent_span_id_hex) span context propagated
        # through submission (reference: util/tracing/tracing_helper.py
        # _inject_tracing_into_function metadata propagation)
        self.trace_ctx = trace_ctx
        self._sched = -1
        # Prototype this spec was cloned from (clone_for): identity key
        # for per-batch static-header dedup on the push wire.
        self._proto = None

    def clone_for(self, task_id: bytes, args: List[TaskArg],
                  trace_ctx=None) -> "TaskSpec":
        """Cheap per-call clone of a prototype spec (the submit hot
        path): every static field is copied by reference, only the
        per-call fields change. ~3x cheaper than __init__ with 17
        keyword arguments."""
        s = TaskSpec.__new__(TaskSpec)
        s.task_id = task_id
        s.job_id = self.job_id
        s.task_type = self.task_type
        s.name = self.name
        s.fn_key = self.fn_key
        s.args = args
        s.num_returns = self.num_returns
        s.resources = self.resources
        s.max_retries = self.max_retries
        s.retry_exceptions = self.retry_exceptions
        s.owner_address = self.owner_address
        s.owner_worker_id = self.owner_worker_id
        s.actor_id = self.actor_id
        s.actor_counter = 0
        s.actor_creation = None
        s.runtime_env = self.runtime_env
        s.placement_group_id = self.placement_group_id
        s.placement_group_bundle_index = self.placement_group_bundle_index
        s.scheduling_strategy = self.scheduling_strategy
        s.depth = self.depth
        s.trace_ctx = trace_ctx
        s._sched = self._sched
        s._proto = self
        return s

    @property
    def scheduling_class(self) -> int:
        # Cached: interning sorts the resource dict and takes a lock, and
        # the hot submit path reads this once per task.
        if self._sched < 0:
            self._sched = scheduling_class_of(self.resources, self.fn_key)
        return self._sched

    def is_actor_task(self) -> bool:
        return self.task_type == TASK_ACTOR

    def is_actor_creation(self) -> bool:
        return self.task_type == TASK_ACTOR_CREATION

    def dependency_ids(self) -> List[bytes]:
        return [a.object_id for a in self.args if a.kind == ARG_REF]

    # -- wire ---------------------------------------------------------------

    def _args_wire(self) -> Tuple[list, List[bytes]]:
        frames: List[bytes] = []
        args_wire = []
        for a in self.args:
            if a.kind == ARG_VALUE:
                start = len(frames)
                frames.extend(a.frames)
                args_wire.append([ARG_VALUE, a.metadata, start, len(a.frames),
                                  a.contained_refs])
            else:
                args_wire.append([ARG_REF, a.object_id, a.owner_address])
        return args_wire, frames

    # Positional field order of the compact wire form (hot path).
    # [task_id, job_id, task_type, name, fn_key, args, num_returns,
    #  resources, max_retries, retry_exceptions, owner_address,
    #  owner_worker_id, actor_id, actor_counter, actor_creation,
    #  runtime_env, pg_id, pg_bundle, strategy, depth]
    WIRE_OWNER_WORKER_ID = 11  # index used by the actor reorder buffer
    WIRE_TASK_ID = 0
    WIRE_NUM_RETURNS = 6

    def to_wire(self) -> Tuple[list, List[bytes]]:
        """(header, frames): the header is a positional msgpack list (cheaper
        to pack/unpack than a keyed dict on the per-task hot path); arg value
        frames are hoisted into the RPC raw frame list so msgpack never
        copies object payloads."""
        args_wire, frames = self._args_wire()
        header = [
            self.task_id, self.job_id, self.task_type, self.name,
            self.fn_key, args_wire, self.num_returns, self.resources,
            self.max_retries, self.retry_exceptions, self.owner_address,
            self.owner_worker_id, self.actor_id, self.actor_counter,
            self.actor_creation, self.runtime_env, self.placement_group_id,
            self.placement_group_bundle_index, self.scheduling_strategy,
            self.depth, self.trace_ctx,
        ]
        return header, frames

    @staticmethod
    def _args_from_wire(args_wire, frames: List[bytes]) -> List[TaskArg]:
        args: List[TaskArg] = []
        for aw in args_wire:
            if aw[0] == ARG_VALUE:
                _, metadata, start, n, contained = aw
                args.append(TaskArg(ARG_VALUE, metadata=metadata,
                                    frames=frames[start:start + n],
                                    contained_refs=contained))
            else:
                args.append(TaskArg(ARG_REF, object_id=aw[1], owner_address=aw[2]))
        return args

    @classmethod
    def from_wire(cls, header: list, frames: List[bytes]) -> "TaskSpec":
        (task_id, job_id, task_type, name, fn_key, args_wire, num_returns,
         resources, max_retries, retry_exceptions, owner_address,
         owner_worker_id, actor_id, actor_counter, actor_creation,
         runtime_env, pg_id, pg_bundle, strategy, depth, trace_ctx) = header
        return cls(
            task_id=task_id, job_id=job_id, task_type=task_type, name=name,
            fn_key=fn_key, args=cls._args_from_wire(args_wire, frames),
            num_returns=num_returns, resources=resources,
            max_retries=max_retries, retry_exceptions=retry_exceptions,
            owner_address=owner_address, owner_worker_id=owner_worker_id,
            actor_id=actor_id, actor_counter=actor_counter,
            actor_creation=actor_creation, runtime_env=runtime_env,
            placement_group_id=pg_id, placement_group_bundle_index=pg_bundle,
            scheduling_strategy=strategy, depth=depth,
            trace_ctx=tuple(trace_ctx) if trace_ctx else None,
        )

    # -- batched push wire form ---------------------------------------------
    # A PushTasks batch sends each distinct static "tail" ONCE and
    # per-task entries as [proto_idx, task_id, args_wire, frame_start,
    # num_frames, trace_ctx] — the drain workload repeats the same
    # remote function millions of times, so per-task wire shrinks from
    # the full 21-field header to ~50 bytes (reference analog: the
    # SchedulingKey already guarantees batch homogeneity in
    # direct_task_transport.h; here we exploit it on the wire too).

    def tail_wire(self) -> list:
        return [self.job_id, self.task_type, self.name, self.fn_key,
                self.num_returns, self.resources, self.max_retries,
                self.retry_exceptions, self.owner_address,
                self.owner_worker_id, self.runtime_env,
                self.placement_group_id, self.placement_group_bundle_index,
                self.scheduling_strategy, self.depth]

    @classmethod
    def from_tail_wire(cls, tail: list) -> "TaskSpec":
        proto = cls.__new__(cls)
        (proto.job_id, proto.task_type, proto.name, proto.fn_key,
         proto.num_returns, proto.resources, proto.max_retries,
         proto.retry_exceptions, proto.owner_address,
         proto.owner_worker_id, proto.runtime_env,
         proto.placement_group_id, proto.placement_group_bundle_index,
         proto.scheduling_strategy, proto.depth) = tail
        proto.task_id = b""
        proto.args = []
        proto.actor_id = b""
        proto.actor_counter = 0
        proto.actor_creation = None
        proto.trace_ctx = None
        proto._sched = -1
        proto._proto = None
        return proto

    def to_wire_dict(self) -> Tuple[dict, List[bytes]]:
        """Keyed wire form for cold paths whose header is stored/augmented
        by other services (actor-creation specs pass through the GCS and
        raylet, which read fields by name)."""
        args_wire, frames = self._args_wire()
        header = {
            "task_id": self.task_id,
            "job_id": self.job_id,
            "task_type": self.task_type,
            "name": self.name,
            "fn_key": self.fn_key,
            "args": args_wire,
            "num_returns": self.num_returns,
            "resources": self.resources,
            "max_retries": self.max_retries,
            "retry_exceptions": self.retry_exceptions,
            "owner_address": self.owner_address,
            "owner_worker_id": self.owner_worker_id,
            "actor_id": self.actor_id,
            "actor_counter": self.actor_counter,
            "actor_creation": self.actor_creation,
            "runtime_env": self.runtime_env,
            "pg_id": self.placement_group_id,
            "pg_bundle": self.placement_group_bundle_index,
            "strategy": self.scheduling_strategy,
            "depth": self.depth,
            "trace_ctx": self.trace_ctx,
        }
        return header, frames

    @classmethod
    def from_wire_dict(cls, header: dict, frames: List[bytes]) -> "TaskSpec":
        return cls(
            task_id=header["task_id"], job_id=header["job_id"],
            task_type=header["task_type"], name=header["name"],
            fn_key=header["fn_key"],
            args=cls._args_from_wire(header["args"], frames),
            num_returns=header["num_returns"], resources=header["resources"],
            max_retries=header["max_retries"],
            retry_exceptions=header["retry_exceptions"],
            owner_address=header["owner_address"],
            owner_worker_id=header["owner_worker_id"],
            actor_id=header["actor_id"], actor_counter=header["actor_counter"],
            actor_creation=header["actor_creation"],
            runtime_env=header["runtime_env"],
            placement_group_id=header.get("pg_id", b""),
            placement_group_bundle_index=header.get("pg_bundle", -1),
            scheduling_strategy=header.get("strategy", "DEFAULT"),
            depth=header.get("depth", 0),
            trace_ctx=tuple(header["trace_ctx"])
            if header.get("trace_ctx") else None,
        )

    def lease_summary(self) -> dict:
        """The light subset the raylet needs for a lease decision (no arg
        payloads — the raylet never sees task data, matching the reference's
        lease-based dispatch)."""
        return {
            "task_id": self.task_id,
            "scheduling_class": self.scheduling_class,
            "resources": self.resources,
            "deps": self.dependency_ids(),
            "strategy": self.scheduling_strategy,
            "pg_id": self.placement_group_id,
            "pg_bundle": self.placement_group_bundle_index,
            "runtime_env": self.runtime_env,
            "depth": self.depth,
            "name": self.name,
            # memory-watchdog victim eligibility: only workers running
            # retriable work may be OOM-killed (memory_monitor.py).
            # Sample-task approximation, like every summary field.
            "retriable": self.max_retries != 0,
        }
