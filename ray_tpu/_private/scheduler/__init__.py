"""Scheduling backends behind one seam.

The raylet delegates every scheduling tick to a backend implementing
``SchedulingBackend``. Role parity: reference ClusterTaskManager +
ClusterResourceScheduler + HybridPolicy behind the ISchedulingPolicy /
ClusterTaskManagerInterface seams (src/ray/raylet/scheduling/
cluster_task_manager_interface.h, scheduling_policy.h). Two backends:

  * host        — dict/heap reference implementation (correctness oracle)
  * tpu_batched — JAX batched kernel: pending lease requests and the
                  cluster resource table become arrays; (task × node)
                  feasibility+scoring runs as one vmapped step (the
                  north-star backend; see BASELINE.json)

Both see the same inputs and must produce identical placements for
identical state (differentially tested in tests/test_scheduler_diff.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

GRANT = "grant"
SPILL = "spill"
WAIT = "wait"
INFEASIBLE = "infeasible"


@dataclass
class PendingRequest:
    """One queued lease request, in arrival order."""
    req_id: int
    scheduling_class: int
    resources: Dict[str, float]
    strategy: str = "DEFAULT"
    pg_id: bytes = b""
    pg_bundle: int = -1
    # Runtime-env identity for worker-pool affinity (reference:
    # worker_pool.h:135 runtime_env_hash).
    env_hash: str = ""
    # Bytes of task args already local per candidate node (locality term).
    locality: Dict[bytes, int] = field(default_factory=dict)
    # Frontier gate: False while the local dependency manager is still
    # prefetching this task's plasma args (reference: DependencyManager
    # RequestTaskDependencies -> dispatch gating). A request with pending
    # deps may still SPILL to a node that already holds them, but a local
    # GRANT waits for the pull.
    deps_ready: bool = True
    # monotonic arrival time (schedule-latency accounting)
    arrival_ts: float = 0.0
    # Sample task this lease request carries (TaskSpec.lease_summary's
    # head-of-queue task): the anchor for the raylet's task-lifecycle
    # events (PENDING_LEASE / LEASE_GRANTED / SPILLBACK).
    task_id: bytes = b""
    # monotonic time of the FIRST scheduler tick that evaluated this
    # request: arrival->first_decision is pure decision latency;
    # first_decision->grant is resource wait (the two must be reported
    # separately — on a saturated node the latter measures queue depth,
    # not the kernel).
    first_decision_ts: float = 0.0
    # Whether the sample task is retriable (max_retries != 0): the
    # granted worker inherits this as its memory-watchdog victim
    # eligibility (memory_monitor.py kills only retriable work).
    # Defaults False so a summary without the field never enables kills.
    retriable: bool = False


@dataclass
class NodeView:
    node_id: bytes
    address: str
    total: Dict[str, float]
    available: Dict[str, float]
    is_local: bool = False


@dataclass
class Decision:
    req_id: int
    action: str                     # GRANT | SPILL | WAIT | INFEASIBLE
    spill_address: str = ""


class SchedulingBackend:
    def schedule(self, pending: List[PendingRequest],
                 nodes: List[NodeView],
                 spread_threshold: float) -> List[Decision]:
        raise NotImplementedError


def make_backend(name: str) -> SchedulingBackend:
    if name == "tpu_batched":
        from ray_tpu._private.scheduler.tpu_batched import TpuBatchedBackend
        return TpuBatchedBackend()
    from ray_tpu._private.scheduler.host_backend import HostBackend
    return HostBackend()
