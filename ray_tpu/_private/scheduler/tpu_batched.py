"""tpu_batched scheduling backend: the decision path as one JAX kernel
over STATE-RESIDENT arrays.

The north-star design (BASELINE.json): instead of per-task callback chains
(reference: ClusterTaskManager::DispatchScheduledTasksToWorkers,
src/ray/raylet/scheduling/cluster_task_manager.cc), the whole tick is a
single jit-compiled program over arrays:

  * demands  [T, R]  — resource demand per pending lease request
  * totals   [N, R]  / avail [N, R] — cluster resource table
  * locality [T, N]  — bytes of each task's args already on each node
  * is_local [N]

The request-side arrays are **resident**: they live on the kernel device
across ticks, keyed by slot. A tick uploads only the DELTA — rows for
newly arrived / changed requests, cleared validity bits for departed
ones — so tick cost is O(changes) + one kernel launch, not O(T × N)
Python work (the round-2 shape). Requests keep their slot for life; a
per-tick permutation restores arrival order inside the kernel (grants
must see earlier grants' resource consumption, so the scan is ordered).

One ``lax.scan`` over tasks with fully vectorized per-node feasibility +
fixed-point scoring inside each step; XLA fuses gather + scan into one
program, so a tick over thousands of pending tasks is one device launch
instead of thousands of callback invocations. Capacities are bucketed
(powers of two) to keep retraces rare; growth copies into a bigger
bucket. Ticks are submit-triggered and coalesced by the raylet
(_schedule_tick schedules at most one tick per loop turn).

Placements are bit-identical to the host backend (shared fixed-point
score, scheduler/scoring.py); tests/test_scheduler_diff.py enforces it.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional

from ray_tpu._private.scheduler import (
    GRANT, INFEASIBLE, SPILL, WAIT, Decision, NodeView, PendingRequest,
    SchedulingBackend,
)
from ray_tpu._private.scheduler.scoring import (
    HI_LOC_SHIFT, LO_LOC_MASK, LOC_MAX, UTIL_MAX, UTIL_SCALE,
    spread_threshold_fp,
)

ACTION_WAIT = -1
ACTION_INFEASIBLE = -2


def _bucket(n: int) -> int:
    """Pad to power-of-two-ish buckets so jit retraces stay rare."""
    b = 8
    while b < n:
        b *= 2
    return b


@functools.lru_cache(maxsize=None)
def _kernel_device():
    """Which device runs the scheduling kernel (a ``jax.Device`` the
    inputs are placed on, or None for the default backend).

    Default "cpu": a lease tick is a tiny (T x N) problem where DISPATCH
    LATENCY dominates — on hardware reached through a remote tunnel a
    device round trip costs more than the whole tick. Set
    RAY_TPU_SCHEDULER_KERNEL_DEVICE=default to run on the default
    platform (the TPU) for very large clusters, where the batched
    (task x node) scoring actually amortizes the launch. Falls back to
    "cpu" when the requested platform cannot run a trivial op (e.g. a
    worker node without TPU access) — the scheduler must keep making
    decisions either way."""
    import os

    import jax
    import jax.numpy as jnp

    choice = os.environ.get("RAY_TPU_SCHEDULER_KERNEL_DEVICE", "cpu")
    if choice != "cpu":
        try:
            jax.jit(lambda: jnp.zeros(()))().block_until_ready()
            return None
        # raylint: disable=exception-hygiene — any backend-init failure falls back to CPU
        except Exception:
            pass
    return jax.local_devices(backend="cpu")[0]


@functools.lru_cache(maxsize=1)
def _preflight_backend_init(attempts: int = 2, timeout_s: float = 60.0,
                            retry_sleep_s: float = 10.0) -> bool:
    """True if jax backend init completes in a throwaway subprocess.

    Runs the same ``jax.local_devices(backend="cpu")`` call that
    ``_kernel_device`` will make, but in a child process under a hard
    timeout, with the same environment (so a backend-resolution-
    wrapping device plugin is exercised too)."""
    import os
    import subprocess
    import sys
    import time

    for i in range(attempts):
        if i:
            # raylint: disable=async-blocking — one-time backend preflight in a raylet subprocess, before any loop runs
            time.sleep(retry_sleep_s)
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; jax.local_devices(backend='cpu')"],
                env=dict(os.environ), timeout=timeout_s,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            if r.returncode == 0:
                return True
        except subprocess.TimeoutExpired:
            pass
        except Exception:  # noqa: BLE001 — treat as not responsive
            return False
    return False


@functools.lru_cache(maxsize=None)
def _compiled_kernel(t_bucket: int, n_bucket: int, r_bucket: int):
    """Gather (slot → arrival order) + feasibility/scoring scan, fused
    into one jitted program."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def kernel(demands_s, locality_s, dep_ready_s, perm, totals, avail0,
               is_local, valid_task, valid_node, spread_fp):
        # *_s are SLOT-ordered resident arrays; perm maps scan position
        # (arrival order) → slot. valid_task is per scan POSITION.
        demands = demands_s[perm]
        locality = locality_s[perm]
        dep_ready = dep_ready_s[perm]
        inv_totals = jnp.where(totals > 0, 1.0 / jnp.maximum(totals, 1e-9), 0.0)
        local_idx = jnp.argmax(is_local)

        def step(avail, inp):
            d, loc, tvalid, t_ready = inp
            feasible = jnp.all(totals + 1e-9 >= d[None, :], axis=1) & valid_node
            ready = jnp.all(avail + 1e-9 >= d[None, :], axis=1) & feasible
            used = (totals - avail) + d[None, :]
            # Fixed-point critical-resource utilization, ceil semantics.
            frac = used * inv_totals
            fp = jnp.ceil(frac * UTIL_SCALE).astype(jnp.int32)
            fp = jnp.clip(jnp.where(totals > 0, fp, 0), 0, UTIL_MAX)
            util_fp = jnp.max(fp, axis=1)                       # [N] i32
            anti_loc = (1 << 20) - jnp.minimum(
                loc.astype(jnp.int32) >> 10, LOC_MAX)
            node_idx = jnp.arange(n_bucket, dtype=jnp.int32)
            remote = jnp.where(is_local, 0, 1).astype(jnp.int32)
            # 58-bit key carried as (hi, lo) int32 pair (see scoring.py).
            hi = (util_fp << 10) | (anti_loc >> HI_LOC_SHIFT)
            lo = ((anti_loc & LO_LOC_MASK) << 16) | (remote << 15) | node_idx
            imax = jnp.int32(2**31 - 1)
            hi = jnp.where(ready, hi, imax)
            min_hi = jnp.min(hi)
            lo = jnp.where(ready & (hi == min_hi), lo, imax)
            best = jnp.argmin(lo)
            # Hybrid rule: local node wins while under the spread threshold.
            local_ready = ready[local_idx] & (util_fp[local_idx] <= spread_fp)
            chosen = jnp.where(local_ready, local_idx, best)
            any_ready = jnp.any(ready)
            any_feasible = jnp.any(feasible)
            # Frontier gate: a local grant waits for dep prefetch; a spill
            # to a node already holding the data proceeds (scoring.py).
            blocked = (chosen == local_idx) & ~t_ready
            action = jnp.where(
                ~tvalid, ACTION_WAIT,
                jnp.where(~any_feasible, ACTION_INFEASIBLE,
                          jnp.where(any_ready & ~blocked, chosen,
                                    ACTION_WAIT)))
            take = (action >= 0)
            delta = jnp.where(
                (jnp.arange(n_bucket) == action)[:, None] & take, d[None, :], 0.0)
            return avail - delta, action.astype(jnp.int32)

        _, actions = lax.scan(
            step, avail0, (demands, locality, valid_task, dep_ready))
        return actions

    jitted = jax.jit(kernel)
    device = _kernel_device()
    if device is None:
        return jitted

    def run_on_device(*args):
        import jax

        return jitted(*(a if hasattr(a, "devices") else
                        jax.device_put(a, device) for a in args))

    return run_on_device


@functools.lru_cache(maxsize=1)
def _row_scatter():
    """Jitted row scatter (jit caches per shape/dtype on its own)."""
    import jax

    return jax.jit(lambda arr, idx, rows: arr.at[idx].set(rows))


class _ResidentState:
    """Slot-addressed request arrays living on the kernel device."""

    def __init__(self, cap_t: int, cap_n: int, cap_r: int, device):
        import jax
        import jax.numpy as jnp

        import contextlib

        self.cap_t, self.cap_n, self.cap_r = cap_t, cap_n, cap_r
        with jax.default_device(device) if device is not None else \
                contextlib.nullcontext():
            self.demands = jnp.zeros((cap_t, cap_r), jnp.float32)
            self.locality = jnp.zeros((cap_t, cap_n), jnp.int32)
            self.dep_ready = jnp.ones((cap_t,), bool)
        self.slots: Dict[int, int] = {}       # req_id -> slot
        self.free: List[int] = list(range(cap_t - 1, -1, -1))
        # per-request fingerprint of the mutable inputs (deps_ready +
        # locality dict) so changed rows re-upload
        self.finger: Dict[int, tuple] = {}


class TpuBatchedBackend(SchedulingBackend):
    """Drop-in for HostBackend behind the scheduler seam.

    XLA backend bring-up happens in a SIDE thread; until it completes,
    ticks are served by the host backend (identical placements, only
    the decision path differs). A wedged bring-up (e.g. a dead device
    tunnel) therefore degrades the scheduler instead of blocking the
    raylet's IO loop — leases are the cluster's heartbeat, and a
    blocked loop also stalls heartbeats into false node deaths."""

    def __init__(self):
        import jax.numpy as jnp  # noqa: F401 — fail fast if jax is missing
        import threading

        from ray_tpu._private.scheduler.host_backend import HostBackend

        self._resource_names: List[str] = []
        self._fallback = HostBackend()
        self._kernel_ready = False
        self._probe_done = threading.Event()
        self._state: Optional[_ResidentState] = None
        self._node_order: List[bytes] = []
        self.num_row_uploads = 0   # introspection: delta-upload counter
        self.num_rebuilds = 0

        def probe():
            try:
                # Pre-flight in a DISPOSABLE SUBPROCESS first: a wedged
                # device plugin (e.g. a dead TPU tunnel) blocks inside
                # backend init while holding the GIL, which would freeze
                # the whole driver process — not just this thread. A
                # subprocess can be timed out and killed; only when it
                # proves the plugin responsive do we init in-process.
                # Exception: a process already pinned to CPU-only jax
                # (jax.config or env) resolves backends without the
                # plugin — direct init is safe and the subprocess would
                # wrongly probe the plugin-wrapped path.
                import jax

                pinned_cpu = "cpu" in str(
                    getattr(jax.config, "jax_platforms", None) or "")
                if pinned_cpu or _preflight_backend_init():
                    _kernel_device()
                    self._kernel_ready = True
            # raylint: disable=exception-hygiene — any init failure leaves the kernel disabled (host backend serves)
            except Exception:
                pass
            finally:
                self._probe_done.set()
                if not self._kernel_ready:
                    import logging

                    logging.getLogger(__name__).error(
                        "tpu_batched kernel backend failed to "
                        "initialize; staying on the host decision path")

        threading.Thread(target=probe, daemon=True,
                         name="rtpu-sched-probe").start()

    def wait_ready(self, timeout_s: float = 60.0) -> bool:
        """Block until the kernel backend is up (or declared bad).
        Tests that differentially compare THIS backend's decisions
        against the host oracle must call this first — otherwise they
        compare the fallback against itself and prove nothing."""
        self._probe_done.wait(timeout_s)
        return self._kernel_ready

    # ---------------------------------------------------------- resident

    def _intern_kinds(self, pending, nodes) -> List[str]:
        kinds = list(self._resource_names)
        known = set(kinds)
        for req in pending:
            for k in req.resources:
                if k not in known:
                    kinds.append(k)
                    known.add(k)
        for n in nodes:
            for k in n.total:
                if k not in known:
                    kinds.append(k)
                    known.add(k)
        self._resource_names = kinds
        return kinds

    @staticmethod
    def _fingerprint(req: PendingRequest) -> tuple:
        # exact: the host oracle reads locality dicts directly, so a
        # missed change would diverge the differential tests
        return (req.deps_ready, tuple(sorted(req.locality.items())))

    def _ensure_state(self, n_pending: int, nodes: List[NodeView],
                      kinds: List[str]) -> _ResidentState:
        """(Re)build the resident arrays when capacities or the node
        column order change; otherwise return the live state."""
        node_order = [n.node_id for n in nodes]
        st = self._state
        # Sized from n_pending alone: each tick reconciles slots to
        # exactly the pending set before allocating, so n_pending live
        # requests always fit an n_pending-bucket capacity.
        need_t = _bucket(n_pending)
        need_n = _bucket(len(nodes))
        need_r = _bucket(max(len(kinds), 1))
        if (st is None or need_t > st.cap_t or need_n != st.cap_n
                or need_r != st.cap_r or node_order != self._node_order):
            self._state = _ResidentState(
                max(need_t, st.cap_t if st else 0), need_n, need_r,
                _kernel_device())
            self._node_order = node_order
            self.num_rebuilds += 1
            # existing requests re-upload on this tick (their
            # fingerprints are dropped)
        return self._state

    def schedule(self, pending: List[PendingRequest],
                 nodes: List[NodeView],
                 spread_threshold: float) -> List[Decision]:
        import numpy as np

        if not pending:
            return []
        if not self._kernel_ready:
            return self._fallback.schedule(pending, nodes,
                                           spread_threshold)
        # Stable resource-kind interning across ticks (reference:
        # scheduling_ids.h string->int interning).
        kinds = self._intern_kinds(pending, nodes)
        kidx = {k: i for i, k in enumerate(kinds)}
        nidx = {n.node_id: i for i, n in enumerate(nodes)}
        st = self._ensure_state(len(pending), nodes, kinds)
        T, N = len(pending), len(nodes)
        tb, nb, rb = st.cap_t, st.cap_n, st.cap_r

        # ---- delta detection: new / changed / departed requests ----
        current = set()
        dirty: List[PendingRequest] = []
        for req in pending:
            current.add(req.req_id)
            fp = self._fingerprint(req)
            if st.finger.get(req.req_id) != fp:
                st.finger[req.req_id] = fp
                dirty.append(req)
        for req_id in [r for r in st.slots if r not in current]:
            st.free.append(st.slots.pop(req_id))
            st.finger.pop(req_id, None)

        if dirty:
            idx = np.empty((len(dirty),), np.int32)
            drows = np.zeros((len(dirty), rb), np.float32)
            lrows = np.zeros((len(dirty), nb), np.int32)
            deps = np.ones((len(dirty),), bool)
            for i, req in enumerate(dirty):
                slot = st.slots.get(req.req_id)
                if slot is None:
                    slot = st.free.pop()
                    st.slots[req.req_id] = slot
                idx[i] = slot
                for k, v in req.resources.items():
                    if v > 0:
                        drows[i, kidx[k]] = v
                for node_id, nbytes in req.locality.items():
                    ni = nidx.get(node_id)
                    if ni is not None:
                        lrows[i, ni] = min(nbytes, 2**31 - 1)
                deps[i] = req.deps_ready
            scatter = _row_scatter()
            st.demands = scatter(st.demands, idx, drows)
            st.locality = scatter(st.locality, idx, lrows)
            st.dep_ready = scatter(st.dep_ready, idx, deps)
            self.num_row_uploads += len(dirty)

        # ---- per-tick small inputs (arrival order + node table) ----
        perm = np.zeros((tb,), np.int32)
        valid_task = np.zeros((tb,), bool)
        for pos, req in enumerate(pending):
            perm[pos] = st.slots[req.req_id]
            valid_task[pos] = True
        totals = np.zeros((nb, rb), np.float32)
        avail = np.zeros((nb, rb), np.float32)
        is_local = np.zeros((nb,), bool)
        valid_node = np.zeros((nb,), bool)
        for ni, n in enumerate(nodes):
            valid_node[ni] = True
            is_local[ni] = n.is_local
            for k, v in n.total.items():
                totals[ni, kidx[k]] = v
            for k, v in n.available.items():
                avail[ni, kidx[k]] = v

        kernel = _compiled_kernel(tb, nb, rb)
        actions = np.asarray(kernel(
            st.demands, st.locality, st.dep_ready, perm, totals, avail,
            is_local, valid_task, valid_node,
            np.int32(min(spread_threshold_fp(spread_threshold), 2**31 - 1))))

        decisions: List[Decision] = []
        local = next((n for n in nodes if n.is_local), None)
        for ti, req in enumerate(pending):
            a = int(actions[ti])
            if a == ACTION_INFEASIBLE:
                decisions.append(Decision(req.req_id, INFEASIBLE))
            elif a == ACTION_WAIT or a >= N:
                decisions.append(Decision(req.req_id, WAIT))
            elif local is not None and nodes[a].node_id == local.node_id:
                decisions.append(Decision(req.req_id, GRANT))
            else:
                decisions.append(Decision(req.req_id, SPILL,
                                          spill_address=nodes[a].address))
        return decisions
