"""tpu_batched scheduling backend: the decision path as one JAX kernel.

The north-star design (BASELINE.json): instead of per-task callback chains
(reference: ClusterTaskManager::DispatchScheduledTasksToWorkers,
src/ray/raylet/scheduling/cluster_task_manager.cc), the whole tick is a
single jit-compiled program over arrays:

  * demands  [T, R]  — resource demand per pending lease request
  * totals   [N, R]  / avail [N, R] — cluster resource table
  * locality [T, N]  — bytes of each task's args already on each node
  * is_local [N]

One ``lax.scan`` over tasks (grants must see earlier grants' resource
consumption — inherently sequential) with fully vectorized per-node
feasibility + fixed-point scoring inside each step; XLA fuses the scan body
into one kernel, so a tick over thousands of pending tasks is one device
launch instead of thousands of callback invocations. Sizes are bucketed to
keep retraces rare.

Placements are bit-identical to the host backend (shared fixed-point score,
scheduler/scoring.py); tests/test_scheduler_diff.py enforces it.
"""

from __future__ import annotations

import functools
from typing import Dict, List

from ray_tpu._private.scheduler import (
    GRANT, INFEASIBLE, SPILL, WAIT, Decision, NodeView, PendingRequest,
    SchedulingBackend,
)
from ray_tpu._private.scheduler.scoring import (
    HI_LOC_SHIFT, LO_LOC_MASK, LOC_MAX, UTIL_MAX, UTIL_SCALE,
    spread_threshold_fp,
)

ACTION_WAIT = -1
ACTION_INFEASIBLE = -2


def _bucket(n: int) -> int:
    """Pad to power-of-two-ish buckets so jit retraces stay rare."""
    b = 8
    while b < n:
        b *= 2
    return b


@functools.lru_cache(maxsize=None)
def _kernel_device():
    """Which device runs the scheduling kernel (a ``jax.Device`` the
    inputs are placed on, or None for the default backend).

    Default "cpu": a lease tick is a tiny (T x N) problem where DISPATCH
    LATENCY dominates — on hardware reached through a remote tunnel a
    device round trip costs more than the whole tick. Set
    RAY_TPU_SCHEDULER_KERNEL_DEVICE=default to run on the default
    platform (the TPU) for very large clusters, where the batched
    (task x node) scoring actually amortizes the launch. Falls back to
    "cpu" when the requested platform cannot run a trivial op (e.g. a
    worker node without TPU access) — the scheduler must keep making
    decisions either way."""
    import os

    import jax
    import jax.numpy as jnp

    choice = os.environ.get("RAY_TPU_SCHEDULER_KERNEL_DEVICE", "cpu")
    if choice != "cpu":
        try:
            jax.jit(lambda: jnp.zeros(()))().block_until_ready()
            return None
        except Exception:  # noqa: BLE001 — any backend-init failure
            pass
    return jax.local_devices(backend="cpu")[0]


@functools.lru_cache(maxsize=None)
def _compiled_kernel(t_bucket: int, n_bucket: int, r_bucket: int):
    import jax
    import jax.numpy as jnp
    from jax import lax

    def kernel(demands, totals, avail0, locality, is_local, valid_task,
               valid_node, dep_ready, spread_fp):
        # demands [T,R] f32, totals/avail0 [N,R] f32, locality [T,N] i32,
        # is_local [N] bool, valid_* masks, dep_ready [T] bool (frontier:
        # the local dependency manager finished prefetching this task's
        # args), spread_fp scalar i64.
        inv_totals = jnp.where(totals > 0, 1.0 / jnp.maximum(totals, 1e-9), 0.0)
        local_idx = jnp.argmax(is_local)

        def step(avail, inp):
            d, loc, tvalid, t_ready = inp
            feasible = jnp.all(totals + 1e-9 >= d[None, :], axis=1) & valid_node
            ready = jnp.all(avail + 1e-9 >= d[None, :], axis=1) & feasible
            used = (totals - avail) + d[None, :]
            # Fixed-point critical-resource utilization, ceil semantics.
            frac = used * inv_totals
            fp = jnp.ceil(frac * UTIL_SCALE).astype(jnp.int32)
            fp = jnp.clip(jnp.where(totals > 0, fp, 0), 0, UTIL_MAX)
            util_fp = jnp.max(fp, axis=1)                       # [N] i32
            anti_loc = (1 << 20) - jnp.minimum(
                loc.astype(jnp.int32) >> 10, LOC_MAX)
            node_idx = jnp.arange(n_bucket, dtype=jnp.int32)
            remote = jnp.where(is_local, 0, 1).astype(jnp.int32)
            # 58-bit key carried as (hi, lo) int32 pair (see scoring.py).
            hi = (util_fp << 10) | (anti_loc >> HI_LOC_SHIFT)
            lo = ((anti_loc & LO_LOC_MASK) << 16) | (remote << 15) | node_idx
            imax = jnp.int32(2**31 - 1)
            hi = jnp.where(ready, hi, imax)
            min_hi = jnp.min(hi)
            lo = jnp.where(ready & (hi == min_hi), lo, imax)
            best = jnp.argmin(lo)
            # Hybrid rule: local node wins while under the spread threshold.
            local_ready = ready[local_idx] & (util_fp[local_idx] <= spread_fp)
            chosen = jnp.where(local_ready, local_idx, best)
            any_ready = jnp.any(ready)
            any_feasible = jnp.any(feasible)
            # Frontier gate: a local grant waits for dep prefetch; a spill
            # to a node already holding the data proceeds (scoring.py).
            blocked = (chosen == local_idx) & ~t_ready
            action = jnp.where(
                ~tvalid, ACTION_WAIT,
                jnp.where(~any_feasible, ACTION_INFEASIBLE,
                          jnp.where(any_ready & ~blocked, chosen,
                                    ACTION_WAIT)))
            take = (action >= 0)
            delta = jnp.where(
                (jnp.arange(n_bucket) == action)[:, None] & take, d[None, :], 0.0)
            return avail - delta, action.astype(jnp.int32)

        _, actions = lax.scan(
            step, avail0, (demands, locality, valid_task, dep_ready))
        return actions

    jitted = jax.jit(kernel)
    device = _kernel_device()
    if device is None:
        return jitted

    def run_on_device(*args):
        return jitted(*(jax.device_put(a, device) for a in args))

    return run_on_device


class TpuBatchedBackend(SchedulingBackend):
    """Drop-in for HostBackend behind the scheduler seam.

    XLA backend bring-up happens in a SIDE thread; until it completes,
    ticks are served by the host backend (identical placements, only
    the decision path differs). A wedged bring-up (e.g. a dead device
    tunnel) therefore degrades the scheduler instead of blocking the
    raylet's IO loop — leases are the cluster's heartbeat, and a
    blocked loop also stalls heartbeats into false node deaths."""

    def __init__(self):
        import jax.numpy as jnp  # noqa: F401 — fail fast if jax is missing
        import threading

        from ray_tpu._private.scheduler.host_backend import HostBackend

        self._resource_names: List[str] = []
        self._fallback = HostBackend()
        self._kernel_ready = False
        self._probe_done = threading.Event()

        def probe():
            try:
                _kernel_device()
                self._kernel_ready = True
            except Exception:  # noqa: BLE001 — any init failure
                pass
            finally:
                self._probe_done.set()
                if not self._kernel_ready:
                    import logging

                    logging.getLogger(__name__).error(
                        "tpu_batched kernel backend failed to "
                        "initialize; staying on the host decision path")

        threading.Thread(target=probe, daemon=True,
                         name="rtpu-sched-probe").start()

    def wait_ready(self, timeout_s: float = 60.0) -> bool:
        """Block until the kernel backend is up (or declared bad).
        Tests that differentially compare THIS backend's decisions
        against the host oracle must call this first — otherwise they
        compare the fallback against itself and prove nothing."""
        self._probe_done.wait(timeout_s)
        return self._kernel_ready

    def schedule(self, pending: List[PendingRequest],
                 nodes: List[NodeView],
                 spread_threshold: float) -> List[Decision]:
        import numpy as np

        if not pending:
            return []
        if not self._kernel_ready:
            return self._fallback.schedule(pending, nodes,
                                           spread_threshold)
        # Stable resource-kind interning across ticks (reference:
        # scheduling_ids.h string->int interning).
        kinds = list(self._resource_names)
        known = set(kinds)
        for req in pending:
            for k in req.resources:
                if k not in known:
                    kinds.append(k)
                    known.add(k)
        for n in nodes:
            for k in n.total:
                if k not in known:
                    kinds.append(k)
                    known.add(k)
        self._resource_names = kinds

        T, N, R = len(pending), len(nodes), max(len(kinds), 1)
        tb, nb, rb = _bucket(T), _bucket(N), _bucket(R)
        demands = np.zeros((tb, rb), dtype=np.float32)
        locality = np.zeros((tb, nb), dtype=np.int32)
        totals = np.zeros((nb, rb), dtype=np.float32)
        avail = np.zeros((nb, rb), dtype=np.float32)
        is_local = np.zeros((nb,), dtype=bool)
        valid_task = np.zeros((tb,), dtype=bool)
        valid_node = np.zeros((nb,), dtype=bool)
        dep_ready = np.ones((tb,), dtype=bool)
        kidx = {k: i for i, k in enumerate(kinds)}
        for ti, req in enumerate(pending):
            valid_task[ti] = True
            dep_ready[ti] = req.deps_ready
            for k, v in req.resources.items():
                if v > 0:
                    demands[ti, kidx[k]] = v
            for ni, n in enumerate(nodes):
                locality[ti, ni] = min(req.locality.get(n.node_id, 0), 2**31 - 1)
        for ni, n in enumerate(nodes):
            valid_node[ni] = True
            is_local[ni] = n.is_local
            for k, v in n.total.items():
                totals[ni, kidx[k]] = v
            for k, v in n.available.items():
                avail[ni, kidx[k]] = v

        kernel = _compiled_kernel(tb, nb, rb)
        actions = np.asarray(kernel(
            demands, totals, avail, locality, is_local, valid_task, valid_node,
            dep_ready,
            np.int32(min(spread_threshold_fp(spread_threshold), 2**31 - 1))))

        decisions: List[Decision] = []
        local = next((n for n in nodes if n.is_local), None)
        for ti, req in enumerate(pending):
            a = int(actions[ti])
            if a == ACTION_INFEASIBLE:
                decisions.append(Decision(req.req_id, INFEASIBLE))
            elif a == ACTION_WAIT or a >= N:
                decisions.append(Decision(req.req_id, WAIT))
            elif local is not None and nodes[a].node_id == local.node_id:
                decisions.append(Decision(req.req_id, GRANT))
            else:
                decisions.append(Decision(req.req_id, SPILL,
                                          spill_address=nodes[a].address))
        return decisions
