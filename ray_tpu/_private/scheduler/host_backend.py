"""Host (dict/heap) scheduling backend — the correctness oracle.

Implements the reference's hybrid policy semantics (reference:
src/ray/raylet/scheduling/scheduling_policy.h HybridPolicy) in plain
Python, using the shared fixed-point score (scheduler/scoring.py) so the
tpu_batched backend can be differentially tested against it: FIFO order per
arrival; prefer the local node while its post-placement critical-resource
utilization stays under the spread threshold; otherwise the globally
lowest-key node (key = utilization, then locality, then local-first, then
stable node index). INFEASIBLE if no node's totals fit; WAIT if totals fit
but nothing is currently available.
"""

from __future__ import annotations

from typing import Dict, List

from ray_tpu._private.scheduler import (
    GRANT, INFEASIBLE, SPILL, WAIT, Decision, NodeView, PendingRequest,
    SchedulingBackend,
)
from ray_tpu._private.scheduler.scoring import (
    anti_locality, pack_key, spread_threshold_fp, util_fixed_point,
)


def _feasible(total: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(total.get(k, 0.0) + 1e-9 >= v for k, v in demand.items() if v > 0)


def _available(avail: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in demand.items() if v > 0)


def _util_fp_after(node: NodeView, avail: Dict[str, float],
                   demand: Dict[str, float]) -> int:
    fp = 0
    for k, cap in node.total.items():
        if cap <= 0:
            continue
        used = cap - avail.get(k, 0.0) + demand.get(k, 0.0)
        fp = max(fp, util_fixed_point(used, cap))
    return fp


class HostBackend(SchedulingBackend):
    def schedule(self, pending: List[PendingRequest],
                 nodes: List[NodeView],
                 spread_threshold: float) -> List[Decision]:
        avail = {n.node_id: dict(n.available) for n in nodes}
        local = next((n for n in nodes if n.is_local), None)
        spread_fp = spread_threshold_fp(spread_threshold)
        decisions: List[Decision] = []
        for req in pending:
            demand = req.resources
            feasible_idx = [i for i, n in enumerate(nodes)
                            if _feasible(n.total, demand)]
            if not feasible_idx:
                decisions.append(Decision(req.req_id, INFEASIBLE))
                continue
            ready_idx = [i for i in feasible_idx
                         if _available(avail[nodes[i].node_id], demand)]
            if not ready_idx:
                decisions.append(Decision(req.req_id, WAIT))
                continue
            best_i = None
            # Hybrid rule: local node wins outright under the threshold.
            if local is not None:
                li = nodes.index(local)
                if li in ready_idx and _util_fp_after(
                        local, avail[local.node_id], demand) <= spread_fp:
                    best_i = li
            if best_i is None:
                best_key = None
                for i in ready_idx:
                    n = nodes[i]
                    key = pack_key(
                        _util_fp_after(n, avail[n.node_id], demand),
                        anti_locality(req.locality.get(n.node_id, 0)),
                        n.is_local, i)
                    if best_key is None or key < best_key:
                        best_key = key
                        best_i = i
            best = nodes[best_i]
            if local is not None and best.node_id == local.node_id \
                    and not req.deps_ready:
                # Frontier gate: the chosen node is THIS node but its args
                # are still being prefetched — hold the grant (no resource
                # consumption) until the dependency manager reports ready.
                decisions.append(Decision(req.req_id, WAIT))
                continue
            a = avail[best.node_id]
            for k, v in demand.items():
                a[k] = a.get(k, 0.0) - v
            if local is not None and best.node_id == local.node_id:
                decisions.append(Decision(req.req_id, GRANT))
            else:
                decisions.append(Decision(req.req_id, SPILL,
                                          spill_address=best.address))
        return decisions
