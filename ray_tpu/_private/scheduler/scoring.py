"""Canonical fixed-point scheduling score, shared by both backends.

The reference does its resource arithmetic in fixed point
(src/ray/raylet/scheduling/fixed_point.h) precisely so scheduling decisions
are deterministic; we follow suit so the host backend and the JAX batched
backend produce bit-identical placements (differentially tested in
tests/test_scheduler_diff.py).

Key (lower wins), conceptually one 58-bit integer per (task, node):

    [ util_fp : 21 ][ anti_locality : 21 ][ remote : 1 ][ node_idx : 15 ]

  util_fp       = ceil(max_r (used_r + demand_r) / total_r * 2^20), clamped
  anti_locality = 2^20 - min(locality_bytes >> 10, 2^20 - 1)  (more local
                  arg bytes -> smaller)
  remote        = 0 for the local node, 1 otherwise
  node_idx      = stable index in the tick's node list (final tiebreak)

JAX runs without x64 by default, so the kernel carries the key as an
(hi, lo) int32 pair compared lexicographically:

    hi = util_fp * 2^10 + (anti_locality >> 11)            (31 bits)
    lo = (anti_locality & 2^11-1) * 2^16 + remote * 2^15 + node_idx  (27 bits)

The hybrid rule sits above the key: if the local node is ready and its
util_fp <= spread_threshold_fp, it wins outright (reference HybridPolicy's
prefer-local-under-threshold behavior, scheduling_policy.h).
"""

from __future__ import annotations

UTIL_SCALE = 1 << 20
UTIL_MAX = (1 << 21) - 1
LOC_MAX = (1 << 20) - 1       # anti-locality values live in [1, 2^20]
NODE_MAX = (1 << 15) - 1

HI_LOC_SHIFT = 11             # low bits of anti_loc carried in `lo`
LO_LOC_MASK = (1 << 11) - 1


def util_fixed_point(used_plus_demand: float, total: float) -> int:
    """ceil((used+demand)/total * 2^20) in int, clamped to 21 bits."""
    if total <= 0:
        return 0
    v = used_plus_demand / total
    fp = int(v * UTIL_SCALE)
    if fp / UTIL_SCALE < v:
        fp += 1
    return min(max(fp, 0), UTIL_MAX)


def anti_locality(locality_bytes: int) -> int:
    return (1 << 20) - min(locality_bytes >> 10, LOC_MAX)


def pack_key(util_fp: int, anti_loc: int, is_local: bool, node_idx: int):
    """(hi, lo) int pair; compare lexicographically (tuples compare so)."""
    hi = (util_fp << 10) | (anti_loc >> HI_LOC_SHIFT)
    lo = ((anti_loc & LO_LOC_MASK) << 16) | \
        ((0 if is_local else 1) << 15) | (node_idx & NODE_MAX)
    return (hi, lo)


def spread_threshold_fp(spread_threshold: float) -> int:
    return int(spread_threshold * UTIL_SCALE)
