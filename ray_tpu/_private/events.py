"""Structured event framework: JSON event lines with severity + labels,
and the CLUSTER EVENT PLANE they flow into.

Parity target: the reference's event framework (reference:
src/ray/util/event.h:40 RAY_EVENT macro, EventManager :97,
LogEventReporter :62 — structured JSON events appended to per-
component files under the session log dir, aggregated by the
dashboard's event module). Each process gets one emitter; events ALSO
flow to the GCS :class:`ClusterEventTable` so ``ray_tpu.state.
list_cluster_events()`` / ``/api/events`` / the status page see them
without scraping files:

* :class:`EventEmitter` — per-process file emitter; when given a
  ``buffer``, every emit also lands in that bounded
  :class:`ClusterEventBuffer`.
* :class:`ClusterEventBuffer` — bounded per-process buffer with the
  series' honest-truncation contract (drop-newest + monotonic drop
  counter, GIL-atomic popleft drain — same shape as TaskEventBuffer).
  Drained piggybacked on the existing shipping cadences: raylets on
  the heartbeat (``cluster_events`` header keys), workers/drivers on
  the metrics-report loop (``AddClusterEvents``). Never its own RPC.
* :class:`ClusterEventTable` — the GCS-side capped, eviction-counted,
  queryable table (filters: severity / label / source / node). Each
  event gets a GCS-assigned monotonic ``seq`` at ingest so "what
  happened in what order" reads straight off the list even when
  reporter wall clocks disagree.

Event catalogue (labels emitted by the runtime itself): NODE_DIED,
GCS_RESTARTED, RAYLET_STARTED, WORKER_DIED, WORKER_OOM_KILLED,
MEMORY_PRESSURE / MEMORY_PRESSURE_CLEARED (lease backpressure
engage/clear, reject counts attached), LEASE_CREDITS_REVOKED (memory-
pressure window zeroing), ZYGOTE_FALLBACK, OBJECT_LEAK_RECLAIMED.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.task_events import TaskEventBuffer

SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR", "FATAL")


class ClusterEventBuffer(TaskEventBuffer):
    """Bounded per-process cluster-event buffer (the shipping half of
    the plane). Inherits the TaskEventBuffer contract — capacity
    check + GIL-atomic deque append, drop-newest with a MONOTONIC
    counter, popleft drain reporting the drop DELTA since the last
    drain — but stores ready wire dicts (events are structured at
    emit time), so ``add``/``drain`` replace the tuple-shaped
    ``record``/``drain_wire``."""

    __slots__ = ()

    def __init__(self, capacity: int = 4096, enabled: bool = True):
        super().__init__(capacity, enabled)

    def add(self, event: dict) -> None:
        if not self.enabled:
            return
        if len(self._buf) >= self.capacity:
            self.dropped += 1
            return
        self._buf.append(event)

    def drain(self) -> Tuple[List[dict], int]:
        return self._drain_raw()


class ClusterEventTable:
    """GCS-side cluster-event table — the queryable plane the
    ``_private/events.py`` docstring always promised. Capped with
    COUNTED eviction (oldest first; a truncated view always reports as
    truncated), aggregating reporter-side buffer drops the same way the
    task/object tables do. Every ingested event gets a monotonic
    ``seq`` so ordering is total and stable under equal timestamps."""

    def __init__(self, capacity: int = 10_000):
        self.capacity = max(1, int(capacity))
        self._events: "deque[dict]" = deque()
        self._seq = 0
        self.evicted = 0
        self.dropped_reporter_events = 0

    def __len__(self) -> int:
        return len(self._events)

    def add(self, event: dict) -> None:
        self._seq += 1
        event = dict(event)
        event["seq"] = self._seq
        if len(self._events) >= self.capacity:
            self._events.popleft()
            self.evicted += 1
        self._events.append(event)

    def ingest(self, events, dropped: int = 0) -> None:
        """Fold one reporter batch in (heartbeat piggyback or
        AddClusterEvents)."""
        self.dropped_reporter_events += int(dropped or 0)
        for ev in events:
            if isinstance(ev, dict):
                self.add(ev)

    @staticmethod
    def _node_of(ev: dict) -> str:
        node = ev.get("node") or \
            (ev.get("custom_fields") or {}).get("node") or ""
        return str(node)

    def list(self, severity: Optional[str] = None,
             label: Optional[str] = None,
             source: Optional[str] = None,
             node: Optional[str] = None,
             limit: int = 1000) -> List[dict]:
        """Filtered tail, ingest-ordered (``seq`` ascending). Filters:
        ``severity`` exact, ``label`` substring, ``source`` exact,
        ``node`` node-id-hex prefix. ``limit`` <= 0 returns nothing
        (same no-alias contract as the task/object tables)."""
        try:
            limit = int(limit if limit is not None else 0)
        except (TypeError, ValueError):
            limit = 0
        if limit <= 0:
            return []
        out = []
        for ev in self._events:
            if severity and ev.get("severity") != severity:
                continue
            if label and label not in (ev.get("label") or ""):
                continue
            if source and ev.get("source_type") != source:
                continue
            if node and not self._node_of(ev).startswith(node):
                continue
            out.append(ev)
        return out[-limit:]

    def summary(self) -> dict:
        by_severity: Dict[str, int] = {}
        by_label: Dict[str, int] = {}
        for ev in self._events:
            sv = ev.get("severity") or "?"
            by_severity[sv] = by_severity.get(sv, 0) + 1
            lb = ev.get("label") or "?"
            by_label[lb] = by_label.get(lb, 0) + 1
        return {
            "num_events": len(self._events),
            "by_severity": by_severity,
            "by_label": by_label,
            "evicted": self.evicted,
            "dropped_reporter_events": self.dropped_reporter_events,
        }


class EventEmitter:
    """Appends JSON event lines to ``<log_dir>/events/event_<source>.log``
    and, when constructed with a ``buffer``, feeds every event into the
    cluster-event plane (shipped to the GCS on the process's existing
    reporting cadence)."""

    def __init__(self, source: str, log_dir: Optional[str] = None,
                 buffer: Optional[ClusterEventBuffer] = None):
        self.source = source
        self.buffer = buffer
        self._lock = threading.Lock()
        self._file = None
        if log_dir:
            event_dir = os.path.join(log_dir, "events")
            os.makedirs(event_dir, exist_ok=True)
            self._path = os.path.join(event_dir,
                                      f"event_{source}.log")
        else:
            self._path = None

    def emit(self, severity: str, label: str, message: str,
             **fields: Any) -> Dict[str, Any]:
        if severity not in SEVERITIES:
            raise ValueError(f"severity must be one of {SEVERITIES}")
        event = {
            "timestamp": time.time(),
            "severity": severity,
            "label": label,
            "message": message,
            "source_type": self.source,
            "pid": os.getpid(),
            "custom_fields": fields,
        }
        if self.buffer is not None:
            self.buffer.add(event)
        if self._path is not None:
            line = json.dumps(event) + "\n"
            with self._lock:
                if self._file is None:
                    self._file = open(self._path, "a")
                self._file.write(line)
                self._file.flush()
        return event

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


def read_events(log_dir: str) -> list:
    """Parse every event file under ``<log_dir>/events``."""
    out = []
    event_dir = os.path.join(log_dir, "events")
    try:
        names = sorted(os.listdir(event_dir))
    except FileNotFoundError:
        return out
    for name in names:
        with open(os.path.join(event_dir, name)) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
    return out
