"""Structured event framework: JSON event lines with severity + labels.

Parity target: the reference's event framework (reference:
src/ray/util/event.h:40 RAY_EVENT macro, EventManager :97,
LogEventReporter :62 — structured JSON events appended to per-
component files under the session log dir). Each process gets one
emitter; events also flow to the GCS cluster-event table so
``ray_tpu.state``/dashboards see them without scraping files.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR", "FATAL")


class EventEmitter:
    """Appends JSON event lines to ``<log_dir>/events/event_<source>.log``."""

    def __init__(self, source: str, log_dir: Optional[str] = None):
        self.source = source
        self._lock = threading.Lock()
        self._file = None
        if log_dir:
            event_dir = os.path.join(log_dir, "events")
            os.makedirs(event_dir, exist_ok=True)
            self._path = os.path.join(event_dir,
                                      f"event_{source}.log")
        else:
            self._path = None

    def emit(self, severity: str, label: str, message: str,
             **fields: Any) -> Dict[str, Any]:
        if severity not in SEVERITIES:
            raise ValueError(f"severity must be one of {SEVERITIES}")
        event = {
            "timestamp": time.time(),
            "severity": severity,
            "label": label,
            "message": message,
            "source_type": self.source,
            "pid": os.getpid(),
            "custom_fields": fields,
        }
        if self._path is not None:
            line = json.dumps(event) + "\n"
            with self._lock:
                if self._file is None:
                    self._file = open(self._path, "a")
                self._file.write(line)
                self._file.flush()
        return event

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


def read_events(log_dir: str) -> list:
    """Parse every event file under ``<log_dir>/events``."""
    out = []
    event_dir = os.path.join(log_dir, "events")
    try:
        names = sorted(os.listdir(event_dir))
    except FileNotFoundError:
        return out
    for name in names:
        with open(os.path.join(event_dir, name)) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
    return out
