"""Node orchestration: boot GCS + raylet (+ workers) for a head or worker
node.

Role parity: reference python/ray/node.py + _private/services.py — the
``Node`` object starts and supervises the per-node daemons. Here the GCS
and raylet are asyncio services hosted on a dedicated IO thread inside the
node process (head) or inside a standalone ``python -m
ray_tpu._private.node`` process (worker nodes / multi-node tests); worker
processes are always real subprocesses.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import shutil
import tempfile
import time
from typing import Dict, Optional

from ray_tpu._private import rpc
from ray_tpu._private.config import RayTpuConfig
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.raylet import Raylet

logger = logging.getLogger(__name__)


def new_session_dir() -> str:
    base = os.environ.get("RAY_TPU_TMPDIR", "/tmp/ray_tpu")
    path = os.path.join(base, f"session_{int(time.time()*1000)}_{os.getpid()}")
    os.makedirs(os.path.join(path, "sockets"), exist_ok=True)
    os.makedirs(os.path.join(path, "logs"), exist_ok=True)
    return path


class Node:
    """A head node (GCS + raylet) or worker node (raylet only)."""

    def __init__(self, config: Optional[RayTpuConfig] = None,
                 num_cpus: int = 1, num_tpus: Optional[int] = None,
                 custom_resources: Optional[Dict[str, float]] = None,
                 session_dir: str = "", node_name: str = ""):
        self.config = config or RayTpuConfig.create()
        self.num_cpus = num_cpus
        resources = dict(custom_resources or {})
        if num_tpus is None:
            try:
                # TPU resource autodetection without importing jax (workers
                # must stay light): the driver sets it explicitly instead.
                num_tpus = 0
            except Exception:
                num_tpus = 0
        if num_tpus:
            resources["TPU"] = float(num_tpus)
        self.custom_resources = resources
        self.session_dir = session_dir or new_session_dir()
        self.node_name = node_name
        self.gcs: Optional[GcsServer] = None
        self.raylet: Optional[Raylet] = None
        self.gcs_address = ""
        self.raylet_address = ""
        self._loop_thread: Optional[rpc.EventLoopThread] = None
        self._owns_session_dir = not session_dir

    async def _boot_gcs(self, gcs_listen: str = ""):
        self.gcs = GcsServer(self.config)
        self.gcs_address = await self.gcs.start(
            gcs_listen or
            (f"tcp://127.0.0.1:{self.config.gcs_port}"
             if self.config.gcs_port else "tcp://127.0.0.1:0"))

    def start_head(self, gcs_listen: str = ""):
        self._loop_thread = rpc.EventLoopThread("rtpu-node-io")

        async def _boot():
            await self._boot_gcs(gcs_listen)
            self.raylet = Raylet(self.config, self.num_cpus,
                                 self.custom_resources, self.session_dir,
                                 self.node_name)
            self.raylet_address = await self.raylet.start(self.gcs_address)

        self._loop_thread.run(_boot(), timeout=30)
        return self

    def start_gcs_only(self, gcs_listen: str = ""):
        """GCS process without a raylet (for GCS fault-tolerance setups
        where raylets live in other processes and survive a GCS restart)."""
        self._loop_thread = rpc.EventLoopThread("rtpu-gcs-io")
        self._loop_thread.run(self._boot_gcs(gcs_listen), timeout=30)
        return self

    def start_worker_node(self, gcs_address: str):
        self._loop_thread = rpc.EventLoopThread("rtpu-node-io")
        self.gcs_address = gcs_address

        async def _boot():
            self.raylet = Raylet(self.config, self.num_cpus,
                                 self.custom_resources, self.session_dir,
                                 self.node_name)
            self.raylet_address = await self.raylet.start(gcs_address)

        self._loop_thread.run(_boot(), timeout=30)
        return self

    def stop(self):
        if self._loop_thread is None:
            return

        async def _stop():
            if self.raylet:
                await self.raylet.stop()
            if self.gcs:
                await self.gcs.stop()

        try:
            self._loop_thread.run(_stop(), timeout=10)
        except Exception:
            logger.debug("node stop incomplete", exc_info=True)
        self._loop_thread.stop()
        self._loop_thread = None
        if self._owns_session_dir and not os.environ.get("RAY_TPU_KEEP_SESSION_DIR"):
            shutil.rmtree(self.session_dir, ignore_errors=True)


def main(argv=None):
    """Standalone node process: ``python -m ray_tpu._private.node``."""
    parser = argparse.ArgumentParser()
    parser.add_argument("--head", action="store_true")
    parser.add_argument("--gcs-only", action="store_true",
                        help="run only the GCS (no raylet) in this process")
    parser.add_argument("--gcs-address", default="")
    parser.add_argument("--gcs-listen", default="",
                        help="head only: address for the GCS to listen on")
    parser.add_argument("--num-cpus", type=int, default=1)
    parser.add_argument("--resources", default="",
                        help="comma list k=v of custom resources")
    parser.add_argument("--session-dir", default="")
    parser.add_argument("--node-name", default="")
    parser.add_argument("--address-file", default="",
                        help="write 'gcs_address raylet_address' here when up")
    args = parser.parse_args(argv)

    logging.basicConfig(level="INFO")
    # Deterministic fault schedules reach standalone GCS/raylet
    # processes through the environment (chaos + fault-tolerance tests).
    from ray_tpu._private import faultpoints
    faultpoints.arm_from_env()
    resources = {}
    if args.resources:
        for kv in args.resources.split(","):
            k, _, v = kv.partition("=")
            resources[k] = float(v)

    node = Node(num_cpus=args.num_cpus, custom_resources=resources,
                session_dir=args.session_dir, node_name=args.node_name)
    if args.gcs_only:
        node.start_gcs_only(gcs_listen=args.gcs_listen)
    elif args.head:
        node.start_head(gcs_listen=args.gcs_listen)
    else:
        if not args.gcs_address:
            parser.error("--gcs-address required for worker nodes")
        node.start_worker_node(args.gcs_address)

    if args.address_file:
        tmp = args.address_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"{node.gcs_address}\n{node.raylet_address}\n"
                    f"{node.session_dir}\n")
        os.replace(tmp, args.address_file)

    try:
        while True:
            # raylint: disable=async-blocking — head daemon main thread parks forever; all work is on the IO loop thread
            time.sleep(3600)
    except KeyboardInterrupt:
        node.stop()


if __name__ == "__main__":
    main()
