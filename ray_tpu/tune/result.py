"""Experiment results (reference: python/ray/tune/analysis/
experiment_analysis.py ExperimentAnalysis): best trial/config lookup over
live Trial objects or a persisted experiment_state.pkl."""

from __future__ import annotations

import math
import os
import pickle
from typing import Any, Dict, List, Optional


class ExperimentAnalysis:
    def __init__(self, experiment_dir: str, trials: Optional[list] = None,
                 metric: str = "score", mode: str = "max"):
        self.experiment_dir = experiment_dir
        self.default_metric = metric
        self.default_mode = mode
        if trials is not None:
            self._trials = [{
                "trial_id": t.trial_id, "config": t.config,
                "status": t.status, "results": t.results, "error": t.error,
                "iteration": t.iteration,
                "latest_checkpoint": getattr(t, "latest_checkpoint", None),
            } for t in trials]
        else:
            path = os.path.join(experiment_dir, "experiment_state.pkl")
            with open(path, "rb") as f:
                self._trials = pickle.load(f)["trials"]

    # ------------------------------------------------------------- queries

    @property
    def trials(self) -> List[Dict[str, Any]]:
        return self._trials

    def _best(self, metric: Optional[str], mode: Optional[str]):
        metric = metric or self.default_metric
        mode = mode or self.default_mode
        sign = 1.0 if mode == "max" else -1.0

        def score(t):
            vals = [r[metric] for r in t["results"] if metric in r]
            return max(sign * v for v in vals) if vals else -math.inf

        scored = [t for t in self._trials if t["results"]]
        if not scored:
            return None
        return max(scored, key=score)

    def best_trial(self, metric: Optional[str] = None,
                   mode: Optional[str] = None) -> Optional[Dict[str, Any]]:
        return self._best(metric, mode)

    def best_config(self, metric: Optional[str] = None,
                    mode: Optional[str] = None) -> Optional[Dict[str, Any]]:
        t = self._best(metric, mode)
        return t["config"] if t else None

    def best_result(self, metric: Optional[str] = None,
                    mode: Optional[str] = None) -> Optional[Dict[str, Any]]:
        metric = metric or self.default_metric
        mode = mode or self.default_mode
        t = self._best(metric, mode)
        if not t:
            return None
        sign = 1.0 if mode == "max" else -1.0
        return max((r for r in t["results"] if metric in r),
                   key=lambda r: sign * r[metric])

    def best_checkpoint(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Optional[str]:
        t = self._best(metric, mode)
        return t.get("latest_checkpoint") if t else None

    def results_df(self):
        """Flat per-trial summary rows (a list of dicts; no pandas
        dependency — reference returns a DataFrame)."""
        rows = []
        for t in self._trials:
            row = {"trial_id": t["trial_id"], "status": t["status"],
                   "iterations": t["iteration"]}
            row.update({f"config/{k}": v for k, v in t["config"].items()})
            if t["results"]:
                row.update(t["results"][-1])
            rows.append(row)
        return rows
