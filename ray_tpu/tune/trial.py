"""Trial state + the trial-hosting actor.

Reference: python/ray/tune/trial.py (Trial :187) and
tune/function_runner.py: a trainable is either a function
``f(config)`` that calls ``tune.report(**metrics)`` (possibly many
times) or a class with setup/step/save/load. Function trainables run
stepwise here too: the actor runs the function on a thread and parks
each report until the driver asks for the next result.
"""

from __future__ import annotations

import itertools
import os
import pickle
import queue as _q
import threading
from typing import Any, Callable, Dict, Optional

import ray_tpu

# trial status (reference: trial.py Trial.PENDING/RUNNING/...)
PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"

_DONE = "__trial_done__"

# set inside trial actors while the trainable runs (tune.report target)
_report_queue: Optional[_q.Queue] = None


def report(**metrics):
    """Called by function trainables to emit an intermediate result."""
    if _report_queue is not None:
        _report_queue.put(metrics)


class _TrialActor:
    """Hosts one trainable; driver polls ``next_result``."""

    def __init__(self, trainable: Callable, config: Dict[str, Any]):
        global _report_queue
        self._config = config
        self._queue: _q.Queue = _q.Queue()
        self._step_iter = None
        self._error: Optional[BaseException] = None
        if isinstance(trainable, type):
            # class API: setup/step/save/load
            self._instance = trainable()
            if hasattr(self._instance, "setup"):
                self._instance.setup(config)
        else:
            self._instance = None
            _report_queue = self._queue

            def _run():
                global _report_queue
                try:
                    out = trainable(config)
                    if isinstance(out, dict):
                        self._queue.put(out)
                except BaseException as e:  # noqa: BLE001
                    self._error = e
                finally:
                    self._queue.put(_DONE)

            self._thread = threading.Thread(target=_run, daemon=True)
            self._thread.start()

    def next_result(self, timeout: float = 30.0):
        """One (metrics, done) pair; class API steps synchronously."""
        if self._instance is not None:
            metrics = self._instance.step()
            done = bool(metrics.get("done", False))
            return metrics, done
        item = self._queue.get(timeout=timeout)
        if isinstance(item, str) and item == _DONE:
            if self._error is not None:
                raise self._error
            return None, True
        return item, False

    def save_checkpoint(self, path: str):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        if self._instance is not None and hasattr(self._instance, "save"):
            self._instance.save(path)
        return path

    def restore_checkpoint(self, path: str):
        if self._instance is not None and hasattr(self._instance, "load"):
            self._instance.load(path)

    def get_config(self):
        return self._config

    def stop(self):
        if self._instance is not None and \
                hasattr(self._instance, "cleanup"):
            self._instance.cleanup()
        return True


class Trial:
    _ids = itertools.count()

    def __init__(self, trainable, config: Dict[str, Any],
                 experiment_dir: str = "",
                 trial_id: Optional[str] = None):
        self.trial_id = trial_id or f"trial_{next(Trial._ids):05d}"
        self.trainable = trainable
        self.config = config
        self.status = PENDING
        self.actor = None
        self.pending_result = None
        self.last_result: Dict[str, Any] = {}
        self.results: list = []
        self.iteration = 0
        self.error: Optional[str] = None
        self.experiment_dir = experiment_dir
        self.latest_checkpoint: Optional[str] = None

    def start(self, resources: Optional[dict] = None):
        opts = dict(resources or {})
        cls = ray_tpu.remote(_TrialActor)
        if opts:
            cls = cls.options(**opts)
        self.actor = cls.remote(self.trainable, self.config)
        self.status = RUNNING

    def fetch_next(self):
        self.pending_result = self.actor.next_result.remote()
        return self.pending_result

    def stop(self, status: str = TERMINATED):
        if self.actor is not None:
            try:
                ray_tpu.kill(self.actor)
            except Exception:  # noqa: BLE001
                pass
            self.actor = None
        self.status = status

    def checkpoint_path(self) -> str:
        return os.path.join(self.experiment_dir, self.trial_id,
                            f"checkpoint_{self.iteration:06d}")

    def __repr__(self):
        return (f"Trial({self.trial_id}, {self.status}, "
                f"it={self.iteration})")
