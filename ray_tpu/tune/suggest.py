"""Search algorithms: the Searcher seam + an in-tree TPE implementation.

Parity target: the reference's suggestion layer
(reference: python/ray/tune/suggest/suggestion.py — Searcher with
``suggest``/``on_trial_complete``/``save``/``restore``; 15+ external
wrappers live in tune/suggest/). Here the seam is the same protocol,
with two in-tree implementations: BasicVariantGenerator (grid × random,
the default) and TPESearcher (a Tree-structured Parzen Estimator — the
algorithm behind hyperopt, reimplemented over this module's Domain
primitives so no external dependency is needed).

Searcher state is checkpointed alongside the experiment
(reference: tune/suggest/suggestion.py save/restore +
durable_trainable.py), so a killed experiment resumes both trials and
the searcher's observation history.
"""

from __future__ import annotations

import math
import pickle
import random
from typing import Any, Dict, List, Optional

from ray_tpu.tune.sample import (Choice, Domain, GridSearch, LogUniform,
                                 RandInt, Uniform, generate_configs)


class Searcher:
    """Suggest/observe protocol (reference: suggestion.py Searcher)."""

    def __init__(self):
        self.metric: str = "score"
        self.mode: str = "max"

    def set_search_properties(self, metric: str, mode: str,
                              space: Dict[str, Any]) -> None:
        self.metric, self.mode = metric, mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        """Next config to try, or None when the search space is
        exhausted."""
        raise NotImplementedError

    def on_trial_result(self, trial_id: str,
                        result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        pass

    # -- persistence (reference: Searcher.save/restore) --

    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            pickle.dump(self.__dict__, f)

    def restore(self, path: str) -> None:
        with open(path, "rb") as f:
            self.__dict__.update(pickle.load(f))


class BasicVariantGenerator(Searcher):
    """Grid × random expansion behind the Searcher seam (reference:
    tune/suggest/basic_variant.py)."""

    def __init__(self, space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        super().__init__()
        self._configs = generate_configs(space or {}, num_samples,
                                         seed=seed) or [{}]
        self._next = 0

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._next >= len(self._configs):
            return None
        cfg = self._configs[self._next]
        self._next += 1
        return cfg


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator (Bergstra et al., 2011).

    Observations are split at the γ-quantile of the objective into
    "good" and "bad" sets; each dimension gets a Parzen (kernel
    mixture) density for both sets, candidates are drawn from the good
    density, and the candidate maximizing the density ratio l(x)/g(x)
    is suggested. Independent per-dimension treatment, matching the
    canonical algorithm (and hyperopt's default behavior, which the
    reference wraps in tune/suggest/hyperopt.py).
    """

    def __init__(self, space: Dict[str, Any], gamma: float = 0.15,
                 n_initial_points: int = 8, n_candidates: int = 24,
                 seed: Optional[int] = None):
        super().__init__()
        for key, dom in (space or {}).items():
            if isinstance(dom, GridSearch):
                raise ValueError(
                    f"TPESearcher does not combine with grid_search "
                    f"(key {key!r}); use BasicVariantGenerator")
        self.space = dict(space or {})
        self.gamma = gamma
        self.n_initial = n_initial_points
        self.n_candidates = n_candidates
        self.rng = random.Random(seed)
        # trial_id -> (unit-space config, objective); completed only
        self.observations: List[tuple] = []
        self._live: Dict[str, Dict[str, float]] = {}

    # -- unit-space transforms per Domain --

    def _to_unit(self, key: str, value: Any) -> float:
        dom = self.space[key]
        if isinstance(dom, Uniform):
            return (value - dom.low) / (dom.high - dom.low)
        if isinstance(dom, LogUniform):
            return (math.log(value) - dom._lo) / (dom._hi - dom._lo)
        if isinstance(dom, RandInt):
            return (value - dom.low) / max(1, dom.high - 1 - dom.low)
        if isinstance(dom, Choice):
            return float(dom.categories.index(value))
        return float(value)

    def _from_unit(self, key: str, u: float) -> Any:
        dom = self.space[key]
        u = min(1.0, max(0.0, u))
        if isinstance(dom, Uniform):
            return dom.low + u * (dom.high - dom.low)
        if isinstance(dom, LogUniform):
            return math.exp(dom._lo + u * (dom._hi - dom._lo))
        if isinstance(dom, RandInt):
            return dom.low + round(u * max(1, dom.high - 1 - dom.low))
        if isinstance(dom, Choice):
            return dom.categories[int(round(u))]
        return u

    def _random_config(self) -> Dict[str, Any]:
        cfg = {}
        for k, dom in self.space.items():
            cfg[k] = dom.sample(self.rng) if isinstance(dom, Domain) \
                else (dom() if callable(dom) else dom)
        return cfg

    # -- the estimator --

    def _model_observations(self) -> List[tuple]:
        """The observation set the estimator fits on (hook: BOHB
        narrows this to a single fidelity)."""
        return self.observations

    def _split(self):
        """Sort observations by objective (best first) and split at the
        γ-quantile."""
        sign = -1.0 if self.mode == "max" else 1.0
        ranked = sorted(self._model_observations(),
                        key=lambda o: sign * o[1])
        n_good = max(1, int(math.ceil(self.gamma * len(ranked))))
        return ranked[:n_good], ranked[n_good:]

    @staticmethod
    def _parzen_logpdf(x: float, centers: List[float],
                       sigma: float) -> float:
        """Log-density of a gaussian mixture at the centers PLUS one
        uniform-prior component (the prior keeps real exploration mass
        in l(x), as in the canonical parzen estimator)."""
        norm = 1.0 / (sigma * math.sqrt(2.0 * math.pi))
        inv = 1.0 / (2.0 * sigma * sigma)
        acc = 1.0  # the uniform component's density on [0, 1]
        for c in centers:
            acc += norm * math.exp(-(x - c) * (x - c) * inv)
        return math.log(acc / (len(centers) + 1) + 1e-12)

    def _suggest_dim(self, key: str, good, bad) -> Any:
        dom = self.space[key]
        if isinstance(dom, Choice):
            ncat = len(dom.categories)
            cg = [1.0] * ncat
            cb = [1.0] * ncat
            for cfg, _ in good:
                cg[int(cfg[key])] += 1
            for cfg, _ in bad:
                cb[int(cfg[key])] += 1
            weights = [cg[i] / cb[i] for i in range(ncat)]
            # sample ∝ ratio: exploration without argmax lock-in
            return self.rng.choices(dom.categories, weights=weights)[0]
        g_centers = [cfg[key] for cfg, _ in good]
        b_centers = [cfg[key] for cfg, _ in bad]
        sigma = max(0.05, 1.0 / (1.0 + len(g_centers)))
        best_u, best_score = None, -float("inf")
        for _ in range(self.n_candidates):
            # draw from l: a good-center gaussian or the uniform prior
            if self.rng.random() < 1.0 / (len(g_centers) + 1):
                u = self.rng.random()
            else:
                u = min(1.0, max(0.0, self.rng.gauss(
                    self.rng.choice(g_centers), sigma)))
            # EI surrogate: argmax l(x)/g(x) with EQUAL bandwidths — a
            # widened g flattens the denominator and the ratio
            # degenerates to mode-seeking (premature convergence).
            score = (self._parzen_logpdf(u, g_centers, sigma)
                     - self._parzen_logpdf(u, b_centers, sigma))
            if score > best_score:
                best_u, best_score = u, score
        return self._from_unit(key, best_u)

    # -- Searcher protocol --

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self._model_observations()) < self.n_initial \
                or not self.space:
            cfg = self._random_config()
        else:
            good, bad = self._split()
            cfg = {}
            for k, dom in self.space.items():
                if isinstance(dom, Domain):
                    cfg[k] = self._suggest_dim(k, good, bad)
                else:  # constants / callables pass through
                    cfg[k] = dom() if callable(dom) else dom
        self._live[trial_id] = {
            k: self._to_unit(k, v) for k, v in cfg.items()
            if k in self.space and isinstance(self.space[k], Domain)}
        return cfg

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        unit_cfg = self._live.pop(trial_id, None)
        if error or unit_cfg is None or result is None:
            return
        value = result.get(self.metric)
        if value is None:
            return
        self.observations.append((unit_cfg, float(value)))

    def save(self, path: str) -> None:
        state = dict(self.__dict__)
        state["rng"] = self.rng.getstate()
        with open(path, "wb") as f:
            pickle.dump(state, f)

    def restore(self, path: str) -> None:
        with open(path, "rb") as f:
            state = pickle.load(f)
        rng_state = state.pop("rng")
        self.__dict__.update(state)
        self.rng = random.Random()
        self.rng.setstate(rng_state)


class BOHBSearcher(TPESearcher):
    """BOHB's model component (Falkner et al. 2018, "BOHB: Robust and
    Efficient Hyperparameter Optimization at Scale"): the TPE/KDE model
    fit on observations from the LARGEST budget (fidelity) that has
    accumulated enough points — intermediate results at every budget
    feed the model, so early ASHA rungs inform suggestions long before
    any trial finishes. Pair with AsyncHyperBandScheduler for the full
    BOHB structure (reference: tune/suggest/bohb.py TuneBOHB +
    schedulers/hb_bohb.py; re-derived from the public algorithm, no
    hpbandster dependency).
    """

    def __init__(self, space: Dict[str, Any],
                 min_points_in_model: int = 8, **kw):
        super().__init__(space, n_initial_points=min_points_in_model,
                         **kw)
        # budget (training_iteration) -> [(unit config, value)]
        self.budget_obs: Dict[int, List[tuple]] = {}
        # (trial_id, budget) pairs already recorded: a resumed trial
        # replaying iterations must not double-count its config's mass
        self._seen: set = set()

    def _model_observations(self) -> List[tuple]:
        best: List[tuple] = []
        for budget in sorted(self.budget_obs):
            obs = self.budget_obs[budget]
            if len(obs) >= self.n_initial:
                best = obs  # keep climbing to the largest viable budget
        return best

    def on_trial_result(self, trial_id: str,
                        result: Dict[str, Any]) -> None:
        unit_cfg = self._live.get(trial_id)
        value = result.get(self.metric)
        if unit_cfg is None or value is None:
            return
        budget = int(result.get("training_iteration", 1))
        if (trial_id, budget) in self._seen:
            return
        self._seen.add((trial_id, budget))
        self.budget_obs.setdefault(budget, []).append(
            (dict(unit_cfg), float(value)))

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        # the final result was already recorded per-budget by
        # on_trial_result; just retire the live entry
        self._live.pop(trial_id, None)
