"""The Tune driver: TrialRunner loop + ``run()``.

Parity target: reference tune.run (python/ray/tune/tune.py) driving
TrialRunner (tune/trial_runner.py:147, step :566) and RayTrialExecutor
(tune/ray_trial_executor.py:149). Trials are actors; the driver polls
their ``next_result`` futures with ``ray_tpu.wait``, routes every result
through the scheduler, enforces stop criteria, checkpoints experiment
state after every event, and returns an ExperimentAnalysis.
"""

from __future__ import annotations

import logging
import os
import pickle
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional, Union

import ray_tpu
from ray_tpu.tune import trial as trial_mod
from ray_tpu.tune.result import ExperimentAnalysis
from ray_tpu.tune.sample import generate_configs
from ray_tpu.tune.schedulers import (
    CONTINUE, STOP, FIFOScheduler, PopulationBasedTraining, TrialScheduler,
)
from ray_tpu.tune.trial import ERROR, PENDING, RUNNING, TERMINATED, Trial

logger = logging.getLogger(__name__)


class TrialRunner:
    """Event loop over trial actors (reference: TrialRunner.step —
    process one ready result per step, consult scheduler, refill)."""

    def __init__(self, trials: List[Trial], scheduler: TrialScheduler,
                 metric: str, mode: str,
                 stop: Union[Dict[str, Any], Callable, None],
                 resources_per_trial: Optional[dict],
                 max_concurrent: int, experiment_dir: str,
                 checkpoint_freq: int = 0):
        self.trials = trials
        self.scheduler = scheduler
        self.metric = metric
        self.mode = mode
        self.stop_criteria = stop
        self.resources = resources_per_trial or {}
        self.max_concurrent = max_concurrent
        self.experiment_dir = experiment_dir
        self.checkpoint_freq = checkpoint_freq
        self._pending: Dict[Any, Trial] = {}  # result future -> trial
        self._last_ckpt = 0.0
        self.checkpoint_period_s = 5.0
        scheduler.set_objective(metric, mode)

    # ------------------------------------------------------------- plumbing

    def _startable(self) -> Optional[Trial]:
        running = sum(1 for t in self.trials if t.status == RUNNING)
        if running >= self.max_concurrent:
            return None
        return next((t for t in self.trials if t.status == PENDING), None)

    def _start_trial(self, t: Trial):
        t.experiment_dir = self.experiment_dir
        t.start(self.resources)
        self._fetch(t)

    def _fetch(self, t: Trial):
        self._pending[t.fetch_next()] = t

    def is_finished(self) -> bool:
        return all(t.status in (TERMINATED, ERROR) for t in self.trials)

    # ------------------------------------------------------------ main loop

    def step(self):
        """Start what can start, then process ONE ready result."""
        while True:
            t = self._startable()
            if t is None:
                break
            self._start_trial(t)
        if not self._pending:
            return
        ready, _ = ray_tpu.wait(list(self._pending), num_returns=1,
                                timeout=10.0)
        if not ready:
            return
        fut = ready[0]
        t = self._pending.pop(fut)
        if t.status != RUNNING:
            return  # stopped (e.g. PBT exploit) while the result was in flight
        try:
            metrics, done = ray_tpu.get(fut)
        except Exception as e:  # noqa: BLE001 — trial failure, not ours
            logger.warning("trial %s errored: %s", t.trial_id, e)
            t.error = repr(e)
            t.stop(status=ERROR)
            self._checkpoint_experiment(force=True)
            return
        if done and metrics is None:
            self._complete(t)
            return
        t.iteration += 1
        metrics.setdefault("training_iteration", t.iteration)
        metrics.setdefault("trial_id", t.trial_id)
        metrics.setdefault("timestamp", time.time())
        t.last_result = metrics
        t.results.append(metrics)
        if self.checkpoint_freq and t.iteration % self.checkpoint_freq == 0:
            try:
                ray_tpu.get(t.actor.save_checkpoint.remote(
                    t.checkpoint_path()))
                t.latest_checkpoint = t.checkpoint_path()
            except Exception:  # noqa: BLE001
                logger.exception("checkpoint of %s failed", t.trial_id)
        if done or self._hit_stop_criteria(t, metrics):
            self._complete(t)
            return
        actor_before = t.actor
        decision = self.scheduler.on_trial_result(self, t, metrics)
        if t.actor is not actor_before or t.status != RUNNING:
            # the scheduler exploited/replaced this trial; its new actor
            # already has a pending fetch — fetching again here would leave
            # two concurrent next_result futures on one trial
            self._checkpoint_experiment()
            return
        if decision == STOP:
            self._complete(t)
        else:
            self._fetch(t)
        self._checkpoint_experiment()

    def _hit_stop_criteria(self, t: Trial, metrics: Dict[str, Any]) -> bool:
        s = self.stop_criteria
        if s is None:
            return False
        if callable(s):
            return bool(s(t.trial_id, metrics))
        return any(metrics.get(k) is not None and metrics[k] >= v
                   for k, v in s.items())

    def _complete(self, t: Trial):
        self.scheduler.on_trial_complete(self, t)
        t.stop(status=TERMINATED)
        self._checkpoint_experiment(force=True)

    # ------------------------------------------------------------ PBT hook

    def exploit(self, t: Trial, donor: Trial, new_config: Dict[str, Any]):
        """Clone donor's weights into ``t`` and restart it with
        ``new_config`` (reference: PBT _exploit + RayTrialExecutor
        reset/restore)."""
        path = donor.checkpoint_path()
        try:
            ray_tpu.get(donor.actor.save_checkpoint.remote(path))
        except Exception:  # noqa: BLE001 — donor died; skip the exploit
            logger.exception("PBT donor checkpoint failed")
            return
        donor.latest_checkpoint = path
        t.stop(status=PENDING)
        t.config = new_config
        t.start(self.resources)
        try:
            ray_tpu.get(t.actor.restore_checkpoint.remote(path))
        except Exception:  # noqa: BLE001
            logger.exception("PBT restore failed")
        t.iteration = donor.iteration
        self._fetch(t)

    # --------------------------------------------------------- persistence

    def _checkpoint_experiment(self, force: bool = False):
        # Re-pickling every result after EVERY event is O((trials *
        # results)^2) disk traffic over an experiment: throttle periodic
        # snapshots; trial state transitions (complete/error) force one
        # (reference: TrialRunner checkpoint_period).
        now = time.time()
        if not force and now - self._last_ckpt < self.checkpoint_period_s:
            return
        self._last_ckpt = now
        state = {
            "metric": self.metric, "mode": self.mode,
            "trials": [{
                "trial_id": t.trial_id, "config": t.config,
                "status": t.status, "results": t.results,
                "error": t.error, "iteration": t.iteration,
                "latest_checkpoint": getattr(t, "latest_checkpoint", None),
            } for t in self.trials],
        }
        tmp = os.path.join(self.experiment_dir, ".experiment_state.tmp")
        with open(tmp, "wb") as f:
            pickle.dump(state, f)
        os.replace(tmp, os.path.join(self.experiment_dir,
                                     "experiment_state.pkl"))


def run(trainable, config: Optional[Dict[str, Any]] = None,
        num_samples: int = 1, metric: str = "score", mode: str = "max",
        scheduler: Optional[TrialScheduler] = None,
        stop: Union[Dict[str, Any], Callable, None] = None,
        resources_per_trial: Optional[dict] = None,
        max_concurrent_trials: int = 0,
        local_dir: str = "", name: str = "",
        checkpoint_freq: int = 0,
        seed: Optional[int] = None,
        verbose: int = 1) -> ExperimentAnalysis:
    """Run an experiment; returns an ExperimentAnalysis
    (reference: tune.run, python/ray/tune/tune.py)."""
    assert mode in ("max", "min"), "mode must be 'max' or 'min'"
    configs = generate_configs(config or {}, num_samples, seed=seed)
    if not configs:
        configs = [{}]
    base = local_dir or os.path.join(tempfile.gettempdir(), "ray_tpu_tune")
    exp_name = name or f"exp_{int(time.time())}"
    experiment_dir = os.path.join(base, exp_name)
    os.makedirs(experiment_dir, exist_ok=True)

    trials = [Trial(trainable, cfg, experiment_dir) for cfg in configs]
    scheduler = scheduler or FIFOScheduler()
    if isinstance(scheduler, PopulationBasedTraining) and not checkpoint_freq:
        checkpoint_freq = scheduler.interval
    runner = TrialRunner(
        trials, scheduler, metric, mode, stop, resources_per_trial,
        max_concurrent_trials or len(trials), experiment_dir,
        checkpoint_freq=checkpoint_freq)

    if verbose:
        logger.info("tune: %d trials -> %s", len(trials), experiment_dir)
    try:
        while not runner.is_finished():
            runner.step()
    finally:
        for t in trials:
            if t.status == RUNNING:
                t.stop(status=TERMINATED)
    return ExperimentAnalysis(experiment_dir, trials=trials,
                              metric=metric, mode=mode)
