"""The Tune driver: TrialRunner loop + ``run()``.

Parity target: reference tune.run (python/ray/tune/tune.py) driving
TrialRunner (tune/trial_runner.py:147, step :566) and RayTrialExecutor
(tune/ray_trial_executor.py:149). Trials are actors; the driver polls
their ``next_result`` futures with ``ray_tpu.wait``, routes every result
through the scheduler, enforces stop criteria, checkpoints experiment
state after every event, and returns an ExperimentAnalysis.
"""

from __future__ import annotations

import logging
import os
import pickle
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional, Union

import ray_tpu
from ray_tpu.tune import trial as trial_mod
from ray_tpu.tune.result import ExperimentAnalysis
from ray_tpu.tune.schedulers import (
    CONTINUE, STOP, FIFOScheduler, PopulationBasedTraining, TrialScheduler,
)
from ray_tpu.tune.trial import ERROR, PENDING, RUNNING, TERMINATED, Trial

logger = logging.getLogger(__name__)


def _pack_checkpoint(path: str) -> bytes:
    """Checkpoint (file OR directory) -> one durable blob."""
    if os.path.isdir(path):
        import io
        import zipfile

        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w") as zf:
            for root, _dirs, files in os.walk(path):
                for fname in sorted(files):
                    full = os.path.join(root, fname)
                    zf.write(full, os.path.relpath(full, path))
        return b"DIR0" + buf.getvalue()
    with open(path, "rb") as f:
        return b"FIL0" + f.read()


def _unpack_checkpoint(blob: bytes, path: str) -> None:
    tag, payload = blob[:4], blob[4:]
    if tag == b"DIR0":
        import io
        import zipfile

        os.makedirs(path, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(payload)) as zf:
            zf.extractall(path)
    else:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(payload)


class TrialRunner:
    """Event loop over trial actors (reference: TrialRunner.step —
    process one ready result per step, consult scheduler, refill).
    Trials are created lazily from the search algorithm
    (reference: SearchGenerator wrapping a Searcher,
    tune/suggest/search_generator.py)."""

    def __init__(self, trainable: Any, search_alg, max_trials: int,
                 scheduler: TrialScheduler,
                 metric: str, mode: str,
                 stop: Union[Dict[str, Any], Callable, None],
                 resources_per_trial: Optional[dict],
                 max_concurrent: int, experiment_dir: str,
                 checkpoint_freq: int = 0,
                 trials: Optional[List[Trial]] = None,
                 storage=None, exp_name: str = ""):
        self.trainable = trainable
        self.search_alg = search_alg
        self.max_trials = max_trials
        self.trials: List[Trial] = list(trials or [])
        self.scheduler = scheduler
        self.metric = metric
        self.mode = mode
        self.stop_criteria = stop
        self.resources = resources_per_trial or {}
        self.max_concurrent = max_concurrent
        self.experiment_dir = experiment_dir
        self.checkpoint_freq = checkpoint_freq
        self._pending: Dict[Any, Trial] = {}  # result future -> trial
        self._last_ckpt = 0.0
        self._exhausted = False
        self.checkpoint_period_s = 5.0
        # Durable experiments (reference: durable_trainable.py +
        # tune/syncer.py): experiment + searcher state and trial
        # checkpoints mirror into a workflow Storage backend (file:// /
        # kv:// / s3://) so a DIFFERENT driver can resume after the
        # head dies — the local experiment_dir is just a working copy.
        self.storage = storage
        self.exp_name = exp_name
        scheduler.set_objective(metric, mode)

    # ------------------------------------------------------------- plumbing

    def _startable(self) -> Optional[Trial]:
        running = sum(1 for t in self.trials if t.status == RUNNING)
        if running >= self.max_concurrent:
            return None
        t = next((t for t in self.trials if t.status == PENDING), None)
        if t is not None:
            return t
        if self._exhausted or self.search_alg is None or \
                len(self.trials) >= self.max_trials:
            return None
        tid = f"trial_{next(trial_mod.Trial._ids):05d}"
        cfg = self.search_alg.suggest(tid)
        if cfg is None:
            self._exhausted = True
            return None
        t = Trial(self.trainable, cfg, self.experiment_dir, trial_id=tid)
        self.trials.append(t)
        return t

    def _start_trial(self, t: Trial):
        t.experiment_dir = self.experiment_dir
        t.start(self.resources)
        self._maybe_restore(t)
        self._fetch(t)

    def _maybe_restore(self, t: Trial):
        """Resume an interrupted trial from its latest checkpoint.

        The durable blob carries the iteration it was taken at, and is
        unpacked into THIS driver's experiment_dir (the dead driver's
        local paths are assumed gone). Trial metadata (iteration,
        results) only rolls forward to the checkpoint if the actor
        restore actually succeeds — a fresh start keeps clean metadata
        instead of a stitched history."""
        prior_results = getattr(t, "_prior_results", None)
        if prior_results is None:
            return
        t._prior_results = None
        path, ckpt_iter = None, None
        if self.storage is not None:
            raw = self.storage.get(self._ckpt_key(t))
            if raw is not None:
                try:
                    meta = pickle.loads(raw)
                    ckpt_iter = int(meta["iteration"])
                    path = os.path.join(
                        self.experiment_dir, t.trial_id,
                        f"checkpoint_{ckpt_iter:06d}")
                    if not os.path.exists(path):
                        _unpack_checkpoint(meta["blob"], path)
                except Exception:  # noqa: BLE001 — corrupt blob
                    logger.exception("durable checkpoint of %s unusable",
                                     t.trial_id)
                    path, ckpt_iter = None, None
        if path is None:
            # same-machine resume: the local checkpoint may still exist
            p = getattr(t, "_prior_ckpt_path", None)
            it = getattr(t, "_prior_ckpt_iter", None)
            if p and it is not None and os.path.exists(p):
                path, ckpt_iter = p, int(it)
        if path is None or ckpt_iter is None:
            return  # fresh start
        try:
            ray_tpu.get(t.actor.restore_checkpoint.remote(path))
        except Exception:  # noqa: BLE001 — fresh start is the fallback
            logger.exception("restore of %s failed; starting fresh",
                             t.trial_id)
            return
        t.iteration = ckpt_iter
        t.results = list(prior_results[:ckpt_iter])
        t.last_result = t.results[-1] if t.results else {}
        t.latest_checkpoint = path
        t.checkpoint_iteration = ckpt_iter

    def _ckpt_key(self, t: Trial) -> str:
        return f"tune/{self.exp_name}/ckpt/{t.trial_id}"

    def _fetch(self, t: Trial):
        self._pending[t.fetch_next()] = t

    def is_finished(self) -> bool:
        more = (self.search_alg is not None and not self._exhausted
                and len(self.trials) < self.max_trials)
        return not more and all(
            t.status in (TERMINATED, ERROR) for t in self.trials)

    # ------------------------------------------------------------ main loop

    def step(self):
        """Start what can start, then process ONE ready result."""
        while True:
            t = self._startable()
            if t is None:
                break
            self._start_trial(t)
        if not self._pending:
            return
        ready, _ = ray_tpu.wait(list(self._pending), num_returns=1,
                                timeout=10.0)
        if not ready:
            return
        fut = ready[0]
        t = self._pending.pop(fut)
        if t.status != RUNNING:
            return  # stopped (e.g. PBT exploit) while the result was in flight
        try:
            metrics, done = ray_tpu.get(fut)
        except Exception as e:  # noqa: BLE001 — trial failure, not ours
            logger.warning("trial %s errored: %s", t.trial_id, e)
            t.error = repr(e)
            t.stop(status=ERROR)
            if self.search_alg is not None:
                self.search_alg.on_trial_complete(t.trial_id, error=True)
            self._checkpoint_experiment(force=True)
            return
        if done and metrics is None:
            self._complete(t)
            return
        t.iteration += 1
        metrics.setdefault("training_iteration", t.iteration)
        metrics.setdefault("trial_id", t.trial_id)
        metrics.setdefault("timestamp", time.time())
        t.last_result = metrics
        t.results.append(metrics)
        if self.search_alg is not None:
            self.search_alg.on_trial_result(t.trial_id, metrics)
        if self.checkpoint_freq and t.iteration % self.checkpoint_freq == 0:
            try:
                path = t.checkpoint_path()
                ray_tpu.get(t.actor.save_checkpoint.remote(path))
                # function trainables write nothing — no checkpoint then
                if os.path.exists(path):
                    t.latest_checkpoint = path
                    t.checkpoint_iteration = t.iteration
                    if self.storage is not None:
                        # self-describing blob: resume looks this key up
                        # directly, no experiment-state force needed
                        self.storage.put(self._ckpt_key(t), pickle.dumps(
                            {"iteration": t.iteration,
                             "blob": _pack_checkpoint(path)}))
            except Exception:  # noqa: BLE001
                logger.exception("checkpoint of %s failed", t.trial_id)
        if done or self._hit_stop_criteria(t, metrics):
            self._complete(t)
            return
        actor_before = t.actor
        decision = self.scheduler.on_trial_result(self, t, metrics)
        if t.actor is not actor_before or t.status != RUNNING:
            # the scheduler exploited/replaced this trial; its new actor
            # already has a pending fetch — fetching again here would leave
            # two concurrent next_result futures on one trial
            self._checkpoint_experiment()
            return
        if decision == STOP:
            self._complete(t)
        else:
            self._fetch(t)
        self._checkpoint_experiment()

    def _hit_stop_criteria(self, t: Trial, metrics: Dict[str, Any]) -> bool:
        s = self.stop_criteria
        if s is None:
            return False
        if callable(s):
            return bool(s(t.trial_id, metrics))
        return any(metrics.get(k) is not None and metrics[k] >= v
                   for k, v in s.items())

    def _complete(self, t: Trial):
        self.scheduler.on_trial_complete(self, t)
        if self.search_alg is not None:
            self.search_alg.on_trial_complete(t.trial_id, t.last_result)
        t.stop(status=TERMINATED)
        self._checkpoint_experiment(force=True)

    # ------------------------------------------------------------ PBT hook

    def exploit(self, t: Trial, donor: Trial, new_config: Dict[str, Any]):
        """Clone donor's weights into ``t`` and restart it with
        ``new_config`` (reference: PBT _exploit + RayTrialExecutor
        reset/restore)."""
        path = donor.checkpoint_path()
        try:
            ray_tpu.get(donor.actor.save_checkpoint.remote(path))
        except Exception:  # noqa: BLE001 — donor died; skip the exploit
            logger.exception("PBT donor checkpoint failed")
            return
        donor.latest_checkpoint = path
        t.stop(status=PENDING)
        t.config = new_config
        t.start(self.resources)
        try:
            ray_tpu.get(t.actor.restore_checkpoint.remote(path))
        except Exception:  # noqa: BLE001
            logger.exception("PBT restore failed")
        t.iteration = donor.iteration
        self._fetch(t)

    # --------------------------------------------------------- persistence

    def _checkpoint_experiment(self, force: bool = False):
        # Re-pickling every result after EVERY event is O((trials *
        # results)^2) disk traffic over an experiment: throttle periodic
        # snapshots; trial state transitions (complete/error) force one
        # (reference: TrialRunner checkpoint_period).
        now = time.time()
        if not force and now - self._last_ckpt < self.checkpoint_period_s:
            return
        self._last_ckpt = now
        state = {
            "metric": self.metric, "mode": self.mode,
            "trials": [{
                "trial_id": t.trial_id, "config": t.config,
                "status": t.status, "results": t.results,
                "error": t.error, "iteration": t.iteration,
                "latest_checkpoint": getattr(t, "latest_checkpoint", None),
                "checkpoint_iteration":
                    getattr(t, "checkpoint_iteration", None),
            } for t in self.trials],
        }
        blob = pickle.dumps(state)
        tmp = os.path.join(self.experiment_dir, ".experiment_state.tmp")
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, os.path.join(self.experiment_dir,
                                     "experiment_state.pkl"))
        if self.storage is not None:
            try:
                self.storage.put(
                    f"tune/{self.exp_name}/experiment_state", blob)
            except Exception:  # noqa: BLE001 — never kill the loop
                logger.exception("durable experiment checkpoint failed")
        if self.search_alg is not None:
            # Searcher state rides the same checkpoint cadence so a
            # killed experiment resumes its observation history too
            # (reference: SearchAlgorithm save alongside trial-runner
            # checkpoints, tune/suggest/suggestion.py save/restore).
            tmp = os.path.join(self.experiment_dir, ".searcher_state.tmp")
            try:
                self.search_alg.save(tmp)
                if self.storage is not None:
                    with open(tmp, "rb") as f:
                        self.storage.put(
                            f"tune/{self.exp_name}/searcher_state",
                            f.read())
                os.replace(tmp, os.path.join(self.experiment_dir,
                                             "searcher_state.pkl"))
            except Exception:  # noqa: BLE001 — never kill the loop
                logger.exception("searcher checkpoint failed")


def _restore_trials(trainable, experiment_dir: str,
                    state: Optional[dict] = None) -> List[Trial]:
    """Rebuild Trial objects from a persisted experiment_state
    (local pickle, or a pre-loaded dict from durable storage):
    completed/errored trials keep their results; interrupted ones
    re-run — from their latest durable checkpoint when one exists
    (reference: TrialRunner.resume, tune/trial_runner.py +
    durable_trainable.py restore path)."""
    import itertools

    if state is None:
        path = os.path.join(experiment_dir, "experiment_state.pkl")
        with open(path, "rb") as f:
            state = pickle.load(f)
    trials: List[Trial] = []
    max_id = -1
    for rec in state["trials"]:
        t = Trial(trainable, rec["config"], experiment_dir,
                  trial_id=rec["trial_id"])
        if rec["status"] in (TERMINATED, ERROR):
            t.status = rec["status"]
            t.results = rec["results"]
            t.last_result = rec["results"][-1] if rec["results"] else {}
            t.iteration = rec["iteration"]
            t.error = rec["error"]
        else:
            t.status = PENDING  # interrupted: re-run
            # checkpoint-based continuation is decided at start time
            # (TrialRunner._maybe_restore), where restore success is
            # known; until then metadata stays fresh-start clean
            t._prior_results = rec["results"]
            t._prior_ckpt_path = rec.get("latest_checkpoint")
            t._prior_ckpt_iter = rec.get("checkpoint_iteration")
        t.latest_checkpoint = rec.get("latest_checkpoint")
        trials.append(t)
        try:
            max_id = max(max_id, int(rec["trial_id"].split("_")[-1]))
        except ValueError:
            pass
    # keep fresh trial ids disjoint from the restored ones
    trial_mod.Trial._ids = itertools.count(max_id + 1)
    return trials


def run(trainable, config: Optional[Dict[str, Any]] = None,
        num_samples: int = 1, metric: str = "score", mode: str = "max",
        scheduler: Optional[TrialScheduler] = None,
        search_alg=None,
        stop: Union[Dict[str, Any], Callable, None] = None,
        resources_per_trial: Optional[dict] = None,
        max_concurrent_trials: int = 0,
        local_dir: str = "", name: str = "",
        checkpoint_freq: int = 0,
        seed: Optional[int] = None,
        resume: bool = False,
        upload_dir: str = "",
        verbose: int = 1) -> ExperimentAnalysis:
    """Run an experiment; returns an ExperimentAnalysis
    (reference: tune.run, python/ray/tune/tune.py).

    ``search_alg`` is any :class:`ray_tpu.tune.suggest.Searcher`; the
    default expands ``config`` as grid × random (the reference's
    BasicVariantGenerator). ``resume=True`` reloads trials AND searcher
    state from a previous run of the same ``name``.

    ``upload_dir`` makes the experiment DURABLE (reference:
    durable_trainable.py + tune/syncer.py): a workflow-storage URL
    (``file:///shared/dir``, ``kv://prefix``, ``s3://bucket/...``)
    that experiment state, searcher state, and trial checkpoints
    mirror into — ``resume=True`` with the same ``name`` +
    ``upload_dir`` restores from it on ANY driver, even if the
    original head and its local_dir are gone.
    """
    assert mode in ("max", "min"), "mode must be 'max' or 'min'"
    from ray_tpu.tune.suggest import BasicVariantGenerator

    base = local_dir or os.path.join(tempfile.gettempdir(), "ray_tpu_tune")
    exp_name = name or f"exp_{int(time.time())}"
    experiment_dir = os.path.join(base, exp_name)
    os.makedirs(experiment_dir, exist_ok=True)
    storage = None
    if upload_dir:
        from ray_tpu.workflow.storage import storage_from_url
        storage = storage_from_url(upload_dir)

    if search_alg is None:
        search_alg = BasicVariantGenerator(config or {}, num_samples,
                                           seed=seed)
        max_trials = len(search_alg._configs)
    else:
        max_trials = num_samples
    search_alg.set_search_properties(metric, mode, config)

    restored: List[Trial] = []
    if resume:
        state_path = os.path.join(experiment_dir, "experiment_state.pkl")
        searcher_path = os.path.join(experiment_dir, "searcher_state.pkl")
        state = None
        if storage is not None:
            blob = storage.get(f"tune/{exp_name}/experiment_state")
            if blob is not None:
                state = pickle.loads(blob)
            sblob = storage.get(f"tune/{exp_name}/searcher_state")
            if sblob is not None:
                # searcher restore() reads a file path: materialize
                with open(searcher_path, "wb") as f:
                    f.write(sblob)
        if state is not None:
            restored = _restore_trials(trainable, experiment_dir, state)
        elif os.path.exists(state_path):
            restored = _restore_trials(trainable, experiment_dir)
        if os.path.exists(searcher_path):
            search_alg.restore(searcher_path)

    scheduler = scheduler or FIFOScheduler()
    if isinstance(scheduler, PopulationBasedTraining) and not checkpoint_freq:
        checkpoint_freq = scheduler.interval
    runner = TrialRunner(
        trainable, search_alg, max_trials, scheduler, metric, mode, stop,
        resources_per_trial,
        max_concurrent_trials or max_trials, experiment_dir,
        checkpoint_freq=checkpoint_freq, trials=restored,
        storage=storage, exp_name=exp_name)

    if verbose:
        logger.info("tune: up to %d trials -> %s", max_trials,
                    experiment_dir)
    try:
        while not runner.is_finished():
            runner.step()
    finally:
        for t in runner.trials:
            if t.status == RUNNING:
                t.stop(status=TERMINATED)
    return ExperimentAnalysis(experiment_dir, trials=runner.trials,
                              metric=metric, mode=mode)
