"""Trial schedulers: FIFO, ASHA, median stopping, PBT.

Parity target: the reference's scheduler suite (reference:
python/ray/tune/schedulers/trial_scheduler.py FIFOScheduler,
schedulers/async_hyperband.py AsyncHyperBandScheduler,
schedulers/median_stopping_rule.py, schedulers/pbt.py
PopulationBasedTraining). A scheduler sees every intermediate result and
answers CONTINUE / STOP; PBT additionally rewrites a lagging trial's
config + weights from a leader (exploit) and perturbs it (explore).
"""

from __future__ import annotations

import logging
import math
import random
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.tune.sample import Domain

logger = logging.getLogger(__name__)

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    """Decision hook; stateless base = FIFO (run every trial to the end)."""

    def set_objective(self, metric: str, mode: str):
        self.metric = metric
        self.mode = mode

    def _score(self, result: Dict[str, Any]) -> float:
        v = result.get(self.metric)
        if v is None:
            return -math.inf
        return float(v) if self.mode == "max" else -float(v)

    def on_trial_result(self, runner, trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, runner, trial):
        pass


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: schedulers/async_hyperband.py _Bracket): rungs at
    grace_period * reduction_factor**k; a trial reaching a rung continues
    only if its metric is in the top 1/reduction_factor of everything
    recorded at that rung so far (async — no waiting for full rungs)."""

    def __init__(self, time_attr: str = "training_iteration",
                 grace_period: int = 1, max_t: int = 100,
                 reduction_factor: float = 3.0):
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.max_t = max_t
        self.rf = reduction_factor
        # rung milestone -> list of recorded scores
        self.rungs: Dict[int, List[float]] = {}
        milestone = grace_period
        while milestone < max_t:
            self.rungs[int(milestone)] = []
            milestone *= reduction_factor
        # per-trial, highest milestone already judged (avoid double counting)
        self._judged: Dict[str, int] = {}

    def on_trial_result(self, runner, trial, result) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        score = self._score(result)
        decision = CONTINUE
        for milestone in sorted(self.rungs):
            if t < milestone or self._judged.get(trial.trial_id, 0) >= milestone:
                continue
            self._judged[trial.trial_id] = milestone
            recorded = self.rungs[milestone]
            recorded.append(score)
            k = max(1, int(len(recorded) / self.rf))
            cutoff = sorted(recorded, reverse=True)[k - 1]
            if score < cutoff:
                decision = STOP
        return decision


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running-average metric falls below the median of
    the running averages of all trials at the same step (reference:
    schedulers/median_stopping_rule.py)."""

    def __init__(self, time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._sums: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}

    def on_trial_result(self, runner, trial, result) -> str:
        tid = trial.trial_id
        self._sums[tid] = self._sums.get(tid, 0.0) + self._score(result)
        self._counts[tid] = self._counts.get(tid, 0) + 1
        t = result.get(self.time_attr, 0)
        if t < self.grace_period or len(self._counts) < self.min_samples:
            return CONTINUE
        avgs = [self._sums[i] / self._counts[i] for i in self._counts]
        median = sorted(avgs)[len(avgs) // 2]
        mine = self._sums[tid] / self._counts[tid]
        return STOP if mine < median else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: schedulers/pbt.py): every perturbation_interval
    iterations, a trial in the bottom quantile clones the config +
    checkpoint of a random top-quantile trial (exploit) and perturbs the
    cloned hyperparameters (explore: resample with prob. resample_prob,
    else scale by 0.8 / 1.2). The runner performs the actual actor
    restart via ``runner.exploit(trial, donor, new_config)``."""

    def __init__(self, time_attr: str = "training_iteration",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self.rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        self._latest: Dict[str, float] = {}
        self.num_exploits = 0

    def on_trial_result(self, runner, trial, result) -> str:
        tid = trial.trial_id
        self._latest[tid] = self._score(result)
        t = result.get(self.time_attr, 0)
        if t - self._last_perturb.get(tid, 0) < self.interval:
            return CONTINUE
        self._last_perturb[tid] = t
        lower, upper = self._quantiles()
        if tid in lower and upper:
            donor_id = self.rng.choice(upper)
            donor = next(tr for tr in runner.trials
                         if tr.trial_id == donor_id)
            new_config = self._explore(dict(donor.config))
            logger.info("PBT exploit: %s <- %s, explored %s",
                        tid, donor_id, new_config)
            self.num_exploits += 1
            runner.exploit(trial, donor, new_config)
        return CONTINUE

    def _quantiles(self):
        if len(self._latest) < 2:
            return [], []
        ordered = sorted(self._latest, key=self._latest.get)
        n = max(1, int(len(ordered) * self.quantile))
        return ordered[:n], ordered[-n:]

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        for key, spec in self.mutations.items():
            if self.rng.random() < self.resample_prob:
                config[key] = self._resample(spec)
            elif isinstance(config.get(key), (int, float)):
                factor = self.rng.choice([0.8, 1.2])
                value = config[key] * factor
                if isinstance(config[key], int):
                    value = max(1, int(round(value)))
                config[key] = value
            else:
                config[key] = self._resample(spec)
        return config

    def _resample(self, spec):
        if isinstance(spec, Domain):
            return spec.sample(self.rng)
        if isinstance(spec, (list, tuple)):
            return self.rng.choice(list(spec))
        if callable(spec):
            return spec()
        return spec


class PB2(PopulationBasedTraining):
    """Population-Based Bandits (public formulation: Parker-Holder et
    al. 2020, "Provably Efficient Online Hyperparameter Optimization
    with Population-Based Bandits"; reference role: tune/schedulers/
    pb2.py): PBT where the EXPLORE step is a GP-UCB suggestion fit on
    the observed (hyperparameters -> reward improvement) history
    instead of a random perturbation.  ``hyperparam_bounds`` maps each
    tuned key to a continuous (low, high) range; exploit/clone
    mechanics are inherited from PBT.
    """

    def __init__(self, time_attr: str = "training_iteration",
                 perturbation_interval: int = 4,
                 hyperparam_bounds: Optional[
                     Dict[str, "tuple[float, float]"]] = None,
                 quantile_fraction: float = 0.25,
                 ucb_kappa: float = 1.0,
                 num_candidates: int = 256,
                 seed: Optional[int] = None):
        if not hyperparam_bounds:
            raise ValueError("PB2 requires hyperparam_bounds")
        super().__init__(time_attr=time_attr,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={},
                         quantile_fraction=quantile_fraction,
                         seed=seed)
        # built after super() so the resamplers draw from the SEEDED
        # self.rng, not the global random stream
        self.mutations = {
            k: (lambda lo=lo, hi=hi: self.rng.uniform(lo, hi))
            for k, (lo, hi) in hyperparam_bounds.items()}
        self.bounds = dict(hyperparam_bounds)
        self.kappa = ucb_kappa
        self.num_candidates = num_candidates
        # (normalized config vector, score delta) observations; only
        # the newest window is ever fit, so cap the memory to it
        from collections import deque
        self._obs_x: Any = deque(maxlen=256)
        self._obs_y: Any = deque(maxlen=256)
        self._prev_score: Dict[str, float] = {}

    # -- data collection ---------------------------------------------------

    def on_trial_result(self, runner, trial, result) -> str:
        tid = trial.trial_id
        score = self._score(result)
        prev = self._prev_score.get(tid)
        if prev is not None and math.isfinite(prev) \
                and math.isfinite(score):
            self._obs_x.append(self._vec(trial.config))
            self._obs_y.append(score - prev)
        self._prev_score[tid] = score
        before = self.num_exploits
        decision = super().on_trial_result(runner, trial, result)
        if self.num_exploits != before:
            # this trial just cloned a donor's checkpoint: its next
            # score jump reflects the clone, not the explored config —
            # drop the stale baseline so that delta never reaches the GP
            self._prev_score.pop(tid, None)
        return decision

    def _vec(self, config: Dict[str, Any]) -> List[float]:
        out = []
        for k, (lo, hi) in self.bounds.items():
            v = float(config.get(k, lo))
            out.append((v - lo) / (hi - lo) if hi > lo else 0.0)
        return out

    # -- GP-UCB explore ----------------------------------------------------

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        if len(self._obs_y) < 4:
            # cold start: PBT mutation, CLAMPED to the declared bounds
            # (the multiplicative 0.8/1.2 branch can step outside them)
            return self._clamp(super()._explore(config))
        try:
            from sklearn.gaussian_process import GaussianProcessRegressor
            from sklearn.gaussian_process.kernels import (
                RBF, WhiteKernel)
        except Exception:  # noqa: BLE001 — sklearn absent: PBT fallback
            return self._clamp(super()._explore(config))
        import numpy as np

        x = np.asarray(self._obs_x, dtype=float)
        y = np.asarray(self._obs_y, dtype=float)
        ystd = y.std() or 1.0
        gp = GaussianProcessRegressor(
            kernel=RBF(length_scale=0.3) + WhiteKernel(1e-3),
            normalize_y=True, alpha=1e-6)
        gp.fit(x, y / ystd)
        cand = np.asarray([
            [self.rng.random() for _ in self.bounds]
            for _ in range(self.num_candidates)])
        mean, std = gp.predict(cand, return_std=True)
        best = cand[int(np.argmax(mean + self.kappa * std))]
        for (k, (lo, hi)), u in zip(self.bounds.items(), best):
            v = lo + float(u) * (hi - lo)
            if isinstance(config.get(k), int):
                v = self._int_in_bounds(v, lo, hi)
            config[k] = v
        return config

    @staticmethod
    def _int_in_bounds(v: float, lo: float, hi: float) -> int:
        # nearest integer that still respects the DECLARED bounds
        # (plain round() could exceed a fractional hi; a hard floor of
        # 1 would narrow a legal lo of 0)
        return int(min(math.floor(hi), max(math.ceil(lo), round(v))))

    def _clamp(self, config: Dict[str, Any]) -> Dict[str, Any]:
        for k, (lo, hi) in self.bounds.items():
            v = config.get(k)
            if isinstance(v, (int, float)):
                c = min(max(float(v), lo), hi)
                config[k] = self._int_in_bounds(c, lo, hi) \
                    if isinstance(v, int) else c
        return config
