"""Trial schedulers: FIFO, ASHA, median stopping, PBT.

Parity target: the reference's scheduler suite (reference:
python/ray/tune/schedulers/trial_scheduler.py FIFOScheduler,
schedulers/async_hyperband.py AsyncHyperBandScheduler,
schedulers/median_stopping_rule.py, schedulers/pbt.py
PopulationBasedTraining). A scheduler sees every intermediate result and
answers CONTINUE / STOP; PBT additionally rewrites a lagging trial's
config + weights from a leader (exploit) and perturbs it (explore).
"""

from __future__ import annotations

import logging
import math
import random
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.tune.sample import Domain

logger = logging.getLogger(__name__)

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    """Decision hook; stateless base = FIFO (run every trial to the end)."""

    def set_objective(self, metric: str, mode: str):
        self.metric = metric
        self.mode = mode

    def _score(self, result: Dict[str, Any]) -> float:
        v = result.get(self.metric)
        if v is None:
            return -math.inf
        return float(v) if self.mode == "max" else -float(v)

    def on_trial_result(self, runner, trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, runner, trial):
        pass


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: schedulers/async_hyperband.py _Bracket): rungs at
    grace_period * reduction_factor**k; a trial reaching a rung continues
    only if its metric is in the top 1/reduction_factor of everything
    recorded at that rung so far (async — no waiting for full rungs)."""

    def __init__(self, time_attr: str = "training_iteration",
                 grace_period: int = 1, max_t: int = 100,
                 reduction_factor: float = 3.0):
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.max_t = max_t
        self.rf = reduction_factor
        # rung milestone -> list of recorded scores
        self.rungs: Dict[int, List[float]] = {}
        milestone = grace_period
        while milestone < max_t:
            self.rungs[int(milestone)] = []
            milestone *= reduction_factor
        # per-trial, highest milestone already judged (avoid double counting)
        self._judged: Dict[str, int] = {}

    def on_trial_result(self, runner, trial, result) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        score = self._score(result)
        decision = CONTINUE
        for milestone in sorted(self.rungs):
            if t < milestone or self._judged.get(trial.trial_id, 0) >= milestone:
                continue
            self._judged[trial.trial_id] = milestone
            recorded = self.rungs[milestone]
            recorded.append(score)
            k = max(1, int(len(recorded) / self.rf))
            cutoff = sorted(recorded, reverse=True)[k - 1]
            if score < cutoff:
                decision = STOP
        return decision


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running-average metric falls below the median of
    the running averages of all trials at the same step (reference:
    schedulers/median_stopping_rule.py)."""

    def __init__(self, time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._sums: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}

    def on_trial_result(self, runner, trial, result) -> str:
        tid = trial.trial_id
        self._sums[tid] = self._sums.get(tid, 0.0) + self._score(result)
        self._counts[tid] = self._counts.get(tid, 0) + 1
        t = result.get(self.time_attr, 0)
        if t < self.grace_period or len(self._counts) < self.min_samples:
            return CONTINUE
        avgs = [self._sums[i] / self._counts[i] for i in self._counts]
        median = sorted(avgs)[len(avgs) // 2]
        mine = self._sums[tid] / self._counts[tid]
        return STOP if mine < median else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: schedulers/pbt.py): every perturbation_interval
    iterations, a trial in the bottom quantile clones the config +
    checkpoint of a random top-quantile trial (exploit) and perturbs the
    cloned hyperparameters (explore: resample with prob. resample_prob,
    else scale by 0.8 / 1.2). The runner performs the actual actor
    restart via ``runner.exploit(trial, donor, new_config)``."""

    def __init__(self, time_attr: str = "training_iteration",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self.rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        self._latest: Dict[str, float] = {}
        self.num_exploits = 0

    def on_trial_result(self, runner, trial, result) -> str:
        tid = trial.trial_id
        self._latest[tid] = self._score(result)
        t = result.get(self.time_attr, 0)
        if t - self._last_perturb.get(tid, 0) < self.interval:
            return CONTINUE
        self._last_perturb[tid] = t
        lower, upper = self._quantiles()
        if tid in lower and upper:
            donor_id = self.rng.choice(upper)
            donor = next(tr for tr in runner.trials
                         if tr.trial_id == donor_id)
            new_config = self._explore(dict(donor.config))
            logger.info("PBT exploit: %s <- %s, explored %s",
                        tid, donor_id, new_config)
            self.num_exploits += 1
            runner.exploit(trial, donor, new_config)
        return CONTINUE

    def _quantiles(self):
        if len(self._latest) < 2:
            return [], []
        ordered = sorted(self._latest, key=self._latest.get)
        n = max(1, int(len(ordered) * self.quantile))
        return ordered[:n], ordered[-n:]

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        for key, spec in self.mutations.items():
            if self.rng.random() < self.resample_prob:
                config[key] = self._resample(spec)
            elif isinstance(config.get(key), (int, float)):
                factor = self.rng.choice([0.8, 1.2])
                value = config[key] * factor
                if isinstance(config[key], int):
                    value = max(1, int(round(value)))
                config[key] = value
            else:
                config[key] = self._resample(spec)
        return config

    def _resample(self, spec):
        if isinstance(spec, Domain):
            return spec.sample(self.rng)
        if isinstance(spec, (list, tuple)):
            return self.rng.choice(list(spec))
        if callable(spec):
            return spec()
        return spec
