"""Hyperparameter tuning over trial actors.

Parity target: the reference's Tune (reference: python/ray/tune/ —
TrialRunner trial_runner.py:147, Trial trial.py:187, RayTrialExecutor
ray_trial_executor.py:149, schedulers/, suggest/). Trials run as
actors; the driver loop polls results, consults the scheduler
(ASHA/HyperBand/PBT/median) and searcher (grid/random), and
checkpoints to the experiment dir.
"""

from ray_tpu.tune.tune import TrialRunner, run  # noqa: F401
from ray_tpu.tune.trial import Trial, report  # noqa: F401
from ray_tpu.tune.sample import (  # noqa: F401
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_tpu.tune.schedulers import (  # noqa: F401
    PB2,
    AsyncHyperBandScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
)
from ray_tpu.tune.result import ExperimentAnalysis  # noqa: F401
from ray_tpu.tune.suggest import (  # noqa: F401
    BasicVariantGenerator,
    BOHBSearcher,
    Searcher,
    TPESearcher,
)


def __getattr__(name):
    # OptunaSearcher loads lazily: optuna is an optional dependency and
    # importing ray_tpu.tune must not require it (reference analog:
    # tune/suggest/optuna.py is only imported on use).
    if name == "OptunaSearcher":
        from ray_tpu.tune.optuna import OptunaSearcher
        return OptunaSearcher
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
