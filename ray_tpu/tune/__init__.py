"""Hyperparameter tuning over trial actors.

Parity target: the reference's Tune (reference: python/ray/tune/ —
TrialRunner trial_runner.py:147, Trial trial.py:187, RayTrialExecutor
ray_trial_executor.py:149, schedulers/, suggest/). Trials run as
actors; the driver loop polls results, consults the scheduler
(ASHA/HyperBand/PBT/median) and searcher (grid/random), and
checkpoints to the experiment dir.
"""

from ray_tpu.tune.tune import TrialRunner, run  # noqa: F401
from ray_tpu.tune.trial import Trial, report  # noqa: F401
from ray_tpu.tune.sample import (  # noqa: F401
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_tpu.tune.schedulers import (  # noqa: F401
    PB2,
    AsyncHyperBandScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
)
from ray_tpu.tune.result import ExperimentAnalysis  # noqa: F401
from ray_tpu.tune.suggest import (  # noqa: F401
    BasicVariantGenerator,
    BOHBSearcher,
    Searcher,
    TPESearcher,
)
