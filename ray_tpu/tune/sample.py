"""Search space primitives (reference: python/ray/tune/sample.py)."""

from __future__ import annotations

import random
from typing import Any, Dict, List


class Domain:
    def sample(self, rng: random.Random):
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low, high):
        import math
        self._lo, self._hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math
        return math.exp(rng.uniform(self._lo, self._hi))


class RandInt(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> RandInt:
    return RandInt(low, high)


def choice(categories) -> Choice:
    return Choice(categories)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


def generate_configs(space: Dict[str, Any], num_samples: int,
                     seed: int | None = None) -> List[Dict[str, Any]]:
    """Expand grid axes (cartesian) × num_samples draws of the random
    axes (reference: suggest/variant_generator.py)."""
    import itertools

    rng = random.Random(seed)
    grid_keys = [k for k, v in space.items()
                 if isinstance(v, GridSearch)]
    grid_values = [space[k].values for k in grid_keys]
    combos = list(itertools.product(*grid_values)) if grid_keys else [()]
    configs = []
    for _ in range(num_samples):
        for combo in combos:
            cfg = {}
            for k, v in space.items():
                if isinstance(v, GridSearch):
                    cfg[k] = combo[grid_keys.index(k)]
                elif isinstance(v, Domain):
                    cfg[k] = v.sample(rng)
                elif callable(v):
                    cfg[k] = v()
                else:
                    cfg[k] = v
            configs.append(cfg)
    return configs
