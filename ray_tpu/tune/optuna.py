"""Optuna-backed Searcher: the external-library proof of the seam.

Parity target: the reference's OptunaSearch wrapper
(reference: python/ray/tune/suggest/optuna.py:41) — third-party search
libraries plug in behind the same Searcher protocol the in-tree TPE/BOHB
implementations use, with no changes to the TrialRunner.

Optuna is an OPTIONAL dependency: importing this module without optuna
installed raises ImportError with an actionable message, and the test
suite skips loudly (tests/test_tune.py) so CI shows the integration as
unexercised rather than silently green.

Design: optuna's ask/tell interface (study.ask() -> Trial,
study.tell(trial, value)) maps 1:1 onto suggest/on_trial_complete; the
tune search space (sample.py Domains) is translated to optuna
distributions at ask time via trial.suggest_*. Intermediate results
feed optuna pruners through Trial.report.
"""

from __future__ import annotations

import math
import pickle
from typing import Any, Dict, Optional

from ray_tpu.tune.sample import (Choice, Domain, GridSearch, LogUniform,
                                 RandInt, Uniform)
from ray_tpu.tune.suggest import Searcher

try:
    import optuna
except ImportError:  # pragma: no cover - exercised when optuna present
    optuna = None


class OptunaSearcher(Searcher):
    """``tune.run(..., search_alg=OptunaSearcher(space))`` with any
    optuna sampler (TPESampler by default, matching the reference
    wrapper's default)."""

    def __init__(self, space: Dict[str, Any], sampler=None,
                 seed: Optional[int] = None):
        if optuna is None:
            raise ImportError(
                "OptunaSearcher requires the `optuna` package "
                "(pip install optuna); the in-tree TPESearcher/"
                "BOHBSearcher cover the same role without it")
        super().__init__()
        for key, dom in (space or {}).items():
            if isinstance(dom, GridSearch):
                raise ValueError(
                    f"OptunaSearcher does not combine with grid_search "
                    f"(key {key!r}); use BasicVariantGenerator")
        self.space = dict(space or {})
        self._sampler = sampler or optuna.samplers.TPESampler(seed=seed)
        self._study = None  # created once metric/mode are known
        self._trials: Dict[str, "optuna.trial.Trial"] = {}

    def _ensure_study(self):
        if self._study is None:
            optuna.logging.set_verbosity(optuna.logging.WARNING)
            self._study = optuna.create_study(
                sampler=self._sampler,
                direction="maximize" if self.mode == "max" else "minimize")
        return self._study

    def _suggest_one(self, trial, key: str, dom: Any):
        if isinstance(dom, Uniform):
            return trial.suggest_float(key, dom.low, dom.high)
        if isinstance(dom, LogUniform):
            return trial.suggest_float(key, math.exp(dom._lo),
                                       math.exp(dom._hi), log=True)
        if isinstance(dom, RandInt):
            # sample.py RandInt is half-open [low, high) like randrange;
            # optuna's suggest_int is inclusive
            return trial.suggest_int(key, dom.low, dom.high - 1)
        if isinstance(dom, Choice):
            return trial.suggest_categorical(key, list(dom.categories))
        if isinstance(dom, Domain):  # unknown domain: fall back to sample
            import random
            return dom.sample(random.Random())
        return dom() if callable(dom) else dom

    # -- Searcher protocol ------------------------------------------------

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        study = self._ensure_study()
        t = study.ask()
        cfg = {k: self._suggest_one(t, k, dom)
               for k, dom in self.space.items()}
        self._trials[trial_id] = t
        return cfg

    def on_trial_result(self, trial_id: str,
                        result: Dict[str, Any]) -> None:
        t = self._trials.get(trial_id)
        value = (result or {}).get(self.metric)
        if t is None or value is None:
            return
        try:  # feeds optuna pruners; never fail the trial loop over it
            t.report(float(value),
                     step=int(result.get("training_iteration", 1)))
        except Exception:  # noqa: BLE001 - e.g. duplicate step
            pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        t = self._trials.pop(trial_id, None)
        if t is None:
            return
        value = None if result is None else result.get(self.metric)
        if error or value is None:
            self._study.tell(t, state=optuna.trial.TrialState.FAIL)
        else:
            self._study.tell(t, float(value))

    # -- persistence: the study (with its observation history) pickles --

    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            pickle.dump({"study": self._study,
                         "space": self.space,
                         "metric": self.metric,
                         "mode": self.mode}, f)

    def restore(self, path: str) -> None:
        with open(path, "rb") as f:
            state = pickle.load(f)
        self._study = state["study"]
        self.space = state["space"]
        self.metric, self.mode = state["metric"], state["mode"]
        self._trials = {}  # in-flight asks do not survive a restart
